"""DeepWalk: graph vertex embeddings via random walks + skip-gram.

Parity: deeplearning4j-graph graph/models/deepwalk/DeepWalk.java —
random-walk corpus (RandomWalkIterator) fed to a skip-gram trainer with
hierarchical softmax over a vertex Huffman tree (GraphHuffman.java).

TPU-native design: reuses the SequenceVectors trainer (the same
scan-chunked batched jit steps Word2Vec uses) with vertex ids as
tokens — the reference's bespoke GraphHuffman/gradient code collapses
into the shared path (build_huffman + _HierarchicSoftmaxStep)."""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.graph.graph import Graph
from deeplearning4j_tpu.graph.walks import RandomWalkIterator
from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors


class DeepWalk:
    """ref DeepWalk.Builder: vectorSize, windowSize, learningRate;
    initialize(graph) + fit(walk_iterator) or the one-call
    fit_graph(graph, walk_length, walks_per_vertex)."""

    def __init__(self, vector_size: int = 100, window_size: int = 5,
                 learning_rate: float = 0.025, epochs: int = 1,
                 use_hierarchic_softmax: bool = True, negative: int = 0,
                 seed: int = 0):
        self.vector_size = vector_size
        self.window_size = window_size
        self.learning_rate = learning_rate
        self.epochs = epochs
        self.use_hs = use_hierarchic_softmax
        self.negative = negative
        self.seed = seed
        self._sv: Optional[SequenceVectors] = None
        self.graph: Optional[Graph] = None

    class Builder:
        def __init__(self):
            self._kw = {}

        def vector_size(self, v):
            self._kw["vector_size"] = v
            return self

        def window_size(self, v):
            self._kw["window_size"] = v
            return self

        def learning_rate(self, v):
            self._kw["learning_rate"] = v
            return self

        def seed(self, v):
            self._kw["seed"] = v
            return self

        def build(self) -> "DeepWalk":
            return DeepWalk(**self._kw)

    # ----------------------------------------------------------------- api
    def initialize(self, graph: Graph) -> "DeepWalk":
        self.graph = graph
        self._sv = SequenceVectors(
            layer_size=self.vector_size, window=self.window_size,
            negative=self.negative,
            use_hierarchic_softmax=self.use_hs,
            min_word_frequency=1, learning_rate=self.learning_rate,
            epochs=self.epochs, seed=self.seed)
        return self

    def fit(self, walks) -> "DeepWalk":
        """Train on an iterator of walks (lists of vertex indices)
        (ref DeepWalk.fit(GraphWalkIterator))."""
        if self._sv is None:
            raise ValueError("call initialize(graph) first")
        self._sv.fit([[str(v) for v in walk] for walk in walks])
        return self

    def fit_graph(self, graph: Graph, walk_length: int = 40,
                  walks_per_vertex: int = 5) -> "DeepWalk":
        self.initialize(graph)
        walks = RandomWalkIterator(graph, walk_length,
                                   walks_per_vertex, seed=self.seed)
        return self.fit(walks)

    # ------------------------------------------------------------- vectors
    def get_vertex_vector(self, v: int) -> np.ndarray:
        vec = self._sv.get_word_vector(str(v))
        if vec is None:
            raise KeyError(f"vertex {v} not in the trained vocabulary")
        return vec

    def similarity(self, a: int, b: int) -> float:
        return self._sv.similarity(str(a), str(b))

    def verts_nearest(self, v: int, top_n: int = 10) -> List[int]:
        return [int(w) for w in
                self._sv.words_nearest(str(v), top_n=top_n)]
