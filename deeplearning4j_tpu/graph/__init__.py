from deeplearning4j_tpu.graph.graph import Graph, Vertex, Edge  # noqa: F401
from deeplearning4j_tpu.graph.loader import (  # noqa: F401
    load_delimited_edge_list,
    load_weighted_edge_list,
)
from deeplearning4j_tpu.graph.walks import (  # noqa: F401
    Node2VecWalkIterator,
    RandomWalkIterator,
    WeightedRandomWalkIterator,
)
from deeplearning4j_tpu.graph.deepwalk import DeepWalk  # noqa: F401
from deeplearning4j_tpu.graph.node2vec import Node2Vec  # noqa: F401
