"""In-memory (di)graph with optional edge weights.

Parity: deeplearning4j-graph graph/graph/Graph.java (IGraph API —
vertices, addEdge, getConnectedVertices, degree) with vertex payloads
(api/Vertex.java) and weighted edges (api/Edge.java)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class Vertex:
    idx: int
    value: Any = None


@dataclass
class Edge:
    frm: int
    to: int
    weight: float = 1.0
    directed: bool = False


class Graph:
    """ref Graph.java — adjacency-list graph over integer vertex ids."""

    def __init__(self, n_vertices: int, directed: bool = False,
                 values: Optional[List[Any]] = None):
        if n_vertices <= 0:
            raise ValueError("graph needs at least one vertex")
        self.directed = directed
        self.vertices = [Vertex(i, values[i] if values else None)
                         for i in range(n_vertices)]
        self._adj: Dict[int, List[Edge]] = {i: [] for i in range(n_vertices)}

    def num_vertices(self) -> int:
        return len(self.vertices)

    def add_edge(self, frm: int, to: int, weight: float = 1.0):
        self._check(frm)
        self._check(to)
        e = Edge(frm, to, weight, self.directed)
        self._adj[frm].append(e)
        if not self.directed:
            self._adj[to].append(Edge(to, frm, weight, False))
        return e

    def _check(self, v: int):
        if not 0 <= v < len(self.vertices):
            raise ValueError(
                f"vertex {v} out of range [0, {len(self.vertices)})")

    def edges_from(self, v: int) -> List[Edge]:
        self._check(v)
        return list(self._adj[v])

    def connected_vertices(self, v: int) -> List[int]:
        """ref Graph.getConnectedVertices."""
        return [e.to for e in self.edges_from(v)]

    def degree(self, v: int) -> int:
        self._check(v)
        return len(self._adj[v])
