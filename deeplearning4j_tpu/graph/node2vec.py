"""Node2Vec: p/q-biased random walks + skip-gram vertex embeddings.

Parity: deeplearning4j-nlp models/node2vec/ (the reference's
Node2Vec sits on its SequenceVectors like this one) with the biased
walk policy from the node2vec paper; reuses DeepWalk's training path."""

from __future__ import annotations

from deeplearning4j_tpu.graph.deepwalk import DeepWalk
from deeplearning4j_tpu.graph.graph import Graph
from deeplearning4j_tpu.graph.walks import Node2VecWalkIterator


class Node2Vec(DeepWalk):
    """DeepWalk with second-order p/q-biased walks."""

    def __init__(self, p: float = 1.0, q: float = 1.0, **kw):
        super().__init__(**kw)
        self.p = p
        self.q = q

    def fit_graph(self, graph: Graph, walk_length: int = 40,
                  walks_per_vertex: int = 5) -> "Node2Vec":
        self.initialize(graph)
        walks = Node2VecWalkIterator(
            graph, walk_length, walks_per_vertex,
            p=self.p, q=self.q, seed=self.seed)
        return self.fit(walks)
