"""Delimited edge-list loaders.

Parity: deeplearning4j-graph data/GraphLoader.java +
EdgeLineProcessor/WeightedEdgeLineProcessor — 'from<sep>to[<sep>weight]'
lines, '#' comments skipped."""

from __future__ import annotations

from deeplearning4j_tpu.graph.graph import Graph


def _lines(path: str):
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                yield line


def load_delimited_edge_list(path: str, n_vertices: int,
                             delimiter: str = ",",
                             directed: bool = False) -> Graph:
    g = Graph(n_vertices, directed=directed)
    for line in _lines(path):
        parts = line.split(delimiter)
        if len(parts) < 2:
            raise ValueError(f"bad edge line: {line!r}")
        g.add_edge(int(parts[0]), int(parts[1]))
    return g


def load_weighted_edge_list(path: str, n_vertices: int,
                            delimiter: str = ",",
                            directed: bool = False) -> Graph:
    g = Graph(n_vertices, directed=directed)
    for line in _lines(path):
        parts = line.split(delimiter)
        if len(parts) < 3:
            raise ValueError(f"bad weighted edge line: {line!r}")
        g.add_edge(int(parts[0]), int(parts[1]), float(parts[2]))
    return g
