"""Random-walk sequence generators over a Graph.

Parity: deeplearning4j-graph graph/iterator/RandomWalkIterator.java
(uniform next-hop, NoEdgeHandling SELF_LOOP_ON_DISCONNECTED) and
WeightedRandomWalkIterator.java (weight-proportional next-hop).
Each walk is a list of vertex indices, usable directly as a
"sentence" for SequenceVectors/DeepWalk."""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu.graph.graph import Graph


class RandomWalkIterator:
    """Uniform random walks of fixed length starting at every vertex
    (optionally repeated `walks_per_vertex` times)."""

    def __init__(self, graph: Graph, walk_length: int,
                 walks_per_vertex: int = 1, seed: int = 0,
                 weighted: bool = False):
        self.graph = graph
        self.walk_length = int(walk_length)
        self.walks_per_vertex = int(walks_per_vertex)
        self.seed = seed
        self.weighted = weighted

    def _next_hop(self, rng, v: int) -> Optional[int]:
        edges = self.graph.edges_from(v)
        if not edges:
            return v   # SELF_LOOP_ON_DISCONNECTED
        if self.weighted:
            w = np.array([e.weight for e in edges], np.float64)
            s = w.sum()
            if s <= 0:
                return edges[rng.integers(len(edges))].to
            return edges[rng.choice(len(edges), p=w / s)].to
        return edges[rng.integers(len(edges))].to

    def __iter__(self) -> Iterator[List[int]]:
        rng = np.random.default_rng(self.seed)
        n = self.graph.num_vertices()
        for _ in range(self.walks_per_vertex):
            order = rng.permutation(n)
            for start in order:
                walk = [int(start)]
                v = int(start)
                for _ in range(self.walk_length - 1):
                    v = self._next_hop(rng, v)
                    walk.append(int(v))
                yield walk


class WeightedRandomWalkIterator(RandomWalkIterator):
    """ref WeightedRandomWalkIterator.java — next hop proportional to
    edge weight."""

    def __init__(self, graph: Graph, walk_length: int,
                 walks_per_vertex: int = 1, seed: int = 0):
        super().__init__(graph, walk_length, walks_per_vertex, seed,
                         weighted=True)


class Node2VecWalkIterator(RandomWalkIterator):
    """Second-order biased walks (Grover & Leskovec node2vec; the
    reference's models/node2vec/ walk role): hop weight from v given the
    previous vertex t is edge_weight x (1/p if returning to t, 1 if the
    candidate neighbors t, else 1/q)."""

    def __init__(self, graph: Graph, walk_length: int,
                 walks_per_vertex: int = 1, p: float = 1.0, q: float = 1.0,
                 seed: int = 0):
        super().__init__(graph, walk_length, walks_per_vertex, seed)
        self.p = float(p)
        self.q = float(q)
        self._nbrs = {v: set(graph.connected_vertices(v))
                      for v in range(graph.num_vertices())}

    def __iter__(self):
        rng = np.random.default_rng(self.seed)
        n = self.graph.num_vertices()
        for _ in range(self.walks_per_vertex):
            for start in rng.permutation(n):
                walk = [int(start)]
                prev = None
                v = int(start)
                for _ in range(self.walk_length - 1):
                    edges = self.graph.edges_from(v)
                    if not edges:
                        walk.append(v)   # SELF_LOOP_ON_DISCONNECTED
                        continue
                    w = np.empty(len(edges), np.float64)
                    for i, e in enumerate(edges):
                        bias = 1.0
                        if prev is not None:
                            if e.to == prev:
                                bias = 1.0 / self.p
                            elif e.to in self._nbrs[prev]:
                                bias = 1.0
                            else:
                                bias = 1.0 / self.q
                        w[i] = max(e.weight, 0.0) * bias
                    s = w.sum()
                    nxt = (edges[rng.integers(len(edges))].to if s <= 0
                           else edges[rng.choice(len(edges), p=w / s)].to)
                    prev, v = v, int(nxt)
                    walk.append(v)
                yield walk
