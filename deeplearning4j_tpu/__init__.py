"""deeplearning4j_tpu — a TPU-native deep-learning framework.

A brand-new JAX/XLA/Pallas implementation of the capabilities of
Deeplearning4j (reference surveyed in SURVEY.md): typed JSON-serializable
network configuration, sequential (MultiLayerNetwork) and DAG
(ComputationGraph) containers, a full layer library, training
infrastructure (updaters, LR schedules, listeners, evaluation, early
stopping, transfer learning, checkpointing), and data-parallel training
via XLA collectives over a `jax.sharding.Mesh` (replacing the reference's
ParallelWrapper / Spark / Aeron parameter-server stack).

Not a port: the reference's hand-written backprop and flattened parameter
views (ref: deeplearning4j-nn/.../nn/multilayer/MultiLayerNetwork.java:440,1169)
become pure functions under `jax.grad` and pytrees here.
"""

__version__ = "0.1.0"

from deeplearning4j_tpu.nn.conf import (  # noqa: F401
    NeuralNetConfiguration,
    MultiLayerConfiguration,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork  # noqa: F401

from deeplearning4j_tpu.nn.conf import ComputationGraphConfiguration  # noqa: F401,E402
from deeplearning4j_tpu.nn.graph import ComputationGraph  # noqa: F401,E402

# the training engine (PR 9): ONE compiled step + ONE host supervisor
# shared by every fit entry point
from deeplearning4j_tpu.engine import (  # noqa: F401,E402
    StepHarness,
    StepProgram,
)
