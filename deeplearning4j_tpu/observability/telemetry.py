"""TelemetryListener: registry emission for plain `net.fit` loops.

TrainingMaster / ParallelWrapper / ParallelInference emit natively (the
hooks live inside their loops); a bare `net.fit(...)` has no such loop
to instrument, so this listener is the adapter — attach it like any
other training listener and every iteration lands in the global
MetricsRegistry:

    net.listeners.append(TelemetryListener(frequency=10))
    net.fit(batches)
    print(get_registry().prometheus_text())

Per iteration it emits `dl4j_train_steps_total` and
`dl4j_train_step_seconds` (wall clock between iteration_done calls — on
an async backend this is dispatch cadence, not device latency; the
forced sync happens only on loss-sampling iterations). Every
`frequency` iterations it syncs the score to host and sets
`dl4j_train_loss` — budget that sync like StatsListener's collection
cadence. With a `tracer` attached, each loss-sampling iteration also
records a "train_step" span, so a plain fit shows up on the shared
timeline next to serving and checkpoint spans.
"""

from __future__ import annotations

import time
from typing import Optional

from deeplearning4j_tpu.observability import metrics as _obs
from deeplearning4j_tpu.observability.tracing import Tracer


class TelemetryListener:
    """Emit per-iteration training metrics into the global registry.

    All emission rides the guarded helpers (`obs.emit` fault point), so
    a telemetry failure never breaks the fit."""

    def __init__(self, frequency: int = 10,
                 tracer: Optional[Tracer] = None):
        self.frequency = max(1, int(frequency))
        self.tracer = tracer
        self._last: Optional[float] = None

    def iteration_done(self, model, iteration: int):
        now = time.perf_counter()
        if self._last is None:
            _obs.count("dl4j_train_steps_total")
        else:
            _obs.count_observe(
                "dl4j_train_steps_total", "dl4j_train_step_seconds",
                now - self._last)
            if (self.tracer is not None
                    and iteration % self.frequency == 0):
                try:
                    self.tracer.record(
                        "train_step", self._last, now, cat="train",
                        args={"iteration": int(iteration)})
                except Exception:   # noqa: BLE001 - telemetry best-effort
                    pass
        self._last = now
        if iteration % self.frequency == 0:
            try:
                score = model.score()
            except Exception:   # noqa: BLE001 - telemetry best-effort
                score = None
            if score is not None:
                _obs.set_gauge("dl4j_train_loss", float(score))
