"""Performance introspection: cost-model MFU accounting, step phase
attribution, cross-rank metric aggregation.

ROADMAP item 2 ("profile the step, then attack") needs the repo to
explain its own step time before anything cuts it. Four instruments,
all riding the PR 5 telemetry substrate:

  CostModel            per-compiled-program flops / bytes-accessed /
                       peak-memory from XLA cost analysis
                       (`lowered.compile().cost_analysis()`), with an
                       analytic fallback for backends that return
                       nothing. Yields exact MFU (measured step time x
                       program flops / device peak), arithmetic
                       intensity, and a roofline classification — the
                       flops/bytes accounting the TPP (arXiv
                       2104.05755) and weight-update-sharding (arXiv
                       2004.13336) work both lean on to decide WHERE
                       to optimize. `perf_report()` lands the numbers
                       as registry gauges and a dict.
  StepPhaseProfiler    decomposes every training step into named
                       phases (data_wait / h2d / dispatch /
                       device_compute / host_sync / checkpoint /
                       telemetry) from perf_counter marks the fit
                       loops already pay for; emits
                       `dl4j_train_phase_seconds{phase=...}` through
                       the owning loop's StepAccumulator so the
                       overhead stays under the PR 5 <2% bar.
  recompile forensics  lives in nn/jit_cache.py (signature + duration
                       ring per new trace, `dl4j_jit_compiles_total`);
                       `CostModel.register_jit_entry` attaches cost
                       digests to the ring.
  aggregate_snapshots  rank-0 pull path: merge per-rank
                       MetricsRegistry snapshot dumps (written by
                       `dump_snapshot`, e.g. from distributed_worker
                       at exit) into ONE fleet-level snapshot —
                       counters summed, histogram buckets merged,
                       gauges re-keyed per rank — rendered through the
                       same `render_prometheus` as a single /metrics
                       body.

Everything here is host-side bookkeeping: no jax import at module
scope, so the aggregation path stays usable in no-jax drills
(cluster supervisor, tier-1 tests).
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.observability import metrics as _obs
from deeplearning4j_tpu.observability.metrics import render_prometheus

# per-chip peak compute (bf16 unless the hardware has no bf16 units)
# and HBM bandwidth — the two roofline axes. "cpu" entries are nominal
# placeholders: MFU on CPU is a smoke-test number, not a claim.
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,    # v5e bf16
    "TPU v4": 275e12,
    "TPU v3": 123e12,
    "cpu": 1e12,
}
PEAK_BYTES_PER_S = {
    "TPU v5 lite": 819e9,
    "TPU v4": 1228e9,
    "TPU v3": 900e9,
    "cpu": 50e9,
}
_DEFAULT_PEAK_FLOPS = 197e12
_DEFAULT_PEAK_BW = 819e9


def device_peaks(device=None) -> Tuple[float, float, str]:
    """(peak_flops, peak_bytes_per_s, device_kind) for `device` (default
    jax.devices()[0]); unknown kinds fall back to the v5e numbers."""
    kind = "unknown"
    try:
        if device is None:
            import jax

            device = jax.devices()[0]
        kind = str(device.device_kind)
    except Exception:   # noqa: BLE001 - no backend: nominal peaks
        pass
    return (PEAK_FLOPS.get(kind, _DEFAULT_PEAK_FLOPS),
            PEAK_BYTES_PER_S.get(kind, _DEFAULT_PEAK_BW), kind)


# ------------------------------------------------ analytic flop counts
def matmul_flops(m: int, k: int, n: int) -> float:
    """[m,k] @ [k,n]: one multiply + one add per MAC."""
    return 2.0 * m * k * n


def conv2d_flops(batch: int, out_h: int, out_w: int, c_out: int,
                 kh: int, kw: int, c_in: int) -> float:
    """Direct convolution MACs x2 (XLA's accounting for VALID padding;
    SAME padding does fewer real MACs at the edges, which XLA also
    counts exactly — use this only as the fallback/cross-check)."""
    return 2.0 * batch * out_h * out_w * c_out * kh * kw * c_in


def train_step_flops_from_params(n_params: int, rows: int) -> float:
    """The classic 6NB estimate (2NB forward + 4NB backward) for a
    dense model with N params on a B-row batch — the coarse analytic
    fallback when XLA reports nothing and no exact count is known."""
    return 6.0 * float(n_params) * float(rows)


# ------------------------------------------------- XLA cost extraction
def _normalize_cost(ca) -> Optional[dict]:
    """`cost_analysis()` returns a dict on some backends and a list of
    per-computation dicts on others; fold either into
    {flops, bytes_accessed} or None when nothing usable came back."""
    if ca is None:
        return None
    entries = ca if isinstance(ca, (list, tuple)) else [ca]
    flops = 0.0
    bytes_accessed = 0.0
    for e in entries:
        if not isinstance(e, dict):
            continue
        flops += float(e.get("flops", 0.0) or 0.0)
        bytes_accessed += float(e.get("bytes accessed", 0.0) or 0.0)
    if flops <= 0.0:
        return None
    return {"flops": flops, "bytes_accessed": bytes_accessed}


def extract_cost(target, *args, **kwargs) -> Optional[dict]:
    """Pull {flops, bytes_accessed, peak_bytes} from XLA cost analysis.

    `target` is either a `jax.jit`-wrapped callable — lowered and
    compiled here with the given example (or ShapeDtypeStruct) args —
    or an already-compiled jax.stages object (the AOT path benches use
    to avoid a duplicate compile). Returns None when the backend
    reports nothing usable (the analytic-fallback trigger)."""
    try:
        compiled = target
        if not hasattr(compiled, "cost_analysis"):
            compiled = target.lower(*args, **kwargs).compile()
        entry = _normalize_cost(compiled.cost_analysis())
        if entry is None:
            return None
        try:
            mem = compiled.memory_analysis()
            entry["peak_bytes"] = int(
                getattr(mem, "temp_size_in_bytes", 0)
                + getattr(mem, "argument_size_in_bytes", 0)
                + getattr(mem, "output_size_in_bytes", 0))
        except Exception:   # noqa: BLE001 - memory stats are optional
            entry["peak_bytes"] = None
        return entry
    except Exception:   # noqa: BLE001 - cost extraction must never raise
        return None


class CostModel:
    """Per-program flops/bytes registry + MFU / roofline arithmetic.

    Register each compiled program once (outside the timed region),
    then `perf_report(key, seconds_per_call=...)` turns a measured
    step time into MFU, arithmetic intensity, and a roofline verdict —
    and lands them as `dl4j_perf_*` registry gauges so the dashboard
    and /metrics see the same numbers the bench JSON records."""

    def __init__(self, peak_flops: Optional[float] = None,
                 peak_bytes_per_s: Optional[float] = None,
                 device=None):
        det_flops, det_bw, kind = device_peaks(device)
        self.peak_flops = float(peak_flops or det_flops)
        self.peak_bytes_per_s = float(peak_bytes_per_s or det_bw)
        self.device_kind = kind
        self._entries: Dict[str, dict] = {}

    # ------------------------------------------------------- register
    def register_compiled(self, key, target, *args,
                          analytic_flops: Optional[float] = None,
                          analytic_bytes: Optional[float] = None,
                          **kwargs) -> dict:
        """XLA cost analysis first; `analytic_*` are the fallback for
        backends whose cost analysis returns nothing. Raises ValueError
        only when BOTH sources are empty."""
        entry = extract_cost(target, *args, **kwargs)
        if entry is not None:
            entry["source"] = "xla_cost_analysis"
        elif analytic_flops:
            entry = {"flops": float(analytic_flops),
                     "bytes_accessed": float(analytic_bytes or 0.0),
                     "peak_bytes": None, "source": "analytic"}
        else:
            raise ValueError(
                f"no cost available for {key!r}: XLA cost analysis "
                "returned nothing and no analytic fallback was given")
        self._entries[str(key)] = entry
        return dict(entry)

    def register_analytic(self, key, flops: float,
                          bytes_accessed: float = 0.0) -> dict:
        entry = {"flops": float(flops),
                 "bytes_accessed": float(bytes_accessed),
                 "peak_bytes": None, "source": "analytic"}
        self._entries[str(key)] = entry
        return dict(entry)

    def register_jit_entry(self, cache, key, *args,
                           analytic_flops: Optional[float] = None,
                           analytic_bytes: Optional[float] = None,
                           **kwargs) -> Optional[dict]:
        """Cost for a JitCache entry: unwraps the cache's forensics
        wrapper, extracts/falls back, and hands the digest back to the
        cache so its recompile ring carries it. Returns None (instead
        of raising) when no cost is available — serving warmup calls
        this opportunistically."""
        fn = cache.get(key)
        if fn is None:
            return None
        fn = getattr(fn, "__wrapped__", fn)
        try:
            entry = self.register_compiled(
                key, fn, *args, analytic_flops=analytic_flops,
                analytic_bytes=analytic_bytes, **kwargs)
        except ValueError:
            return None
        if hasattr(cache, "register_cost"):
            cache.register_cost(key, entry)
        return entry

    # ----------------------------------------------------------- reads
    def entry(self, key) -> Optional[dict]:
        e = self._entries.get(str(key))
        return dict(e) if e is not None else None

    def keys(self) -> List[str]:
        return list(self._entries)

    def arithmetic_intensity(self, key) -> Optional[float]:
        e = self._entries.get(str(key))
        if e is None or not e.get("bytes_accessed"):
            return None
        return e["flops"] / e["bytes_accessed"]

    def mfu(self, key, seconds_per_call: float) -> Optional[float]:
        """Model flops utilization: program flops / wall seconds /
        device peak. The honest headline — counts the flops the model
        NEEDS (as compiled), not the flops the kernel burned."""
        e = self._entries.get(str(key))
        if e is None or seconds_per_call <= 0.0:
            return None
        return e["flops"] / seconds_per_call / self.peak_flops

    def roofline(self, key) -> Optional[dict]:
        """Where this program sits on the roofline: arithmetic
        intensity vs the ridge point (peak_flops / peak_bw), plus the
        bandwidth-bound attainable flops ceiling."""
        ai = self.arithmetic_intensity(key)
        if ai is None:
            return None
        ridge = self.peak_flops / self.peak_bytes_per_s
        return {
            "arithmetic_intensity": ai,
            "ridge_point": ridge,
            "bound": "compute" if ai >= ridge else "memory",
            "attainable_flops_per_s": min(
                self.peak_flops, ai * self.peak_bytes_per_s),
        }

    def perf_report(self, key, seconds_per_call: Optional[float] = None,
                    items_per_call: Optional[float] = None) -> dict:
        """One dict with everything ROADMAP item 2 needs to cite:
        flops, bytes, arithmetic intensity, roofline verdict, and (when
        a measured `seconds_per_call` is given) MFU + achieved
        flops/s. Also lands the numbers as `dl4j_perf_*` gauges."""
        e = self._entries.get(str(key))
        if e is None:
            raise KeyError(f"no cost registered for {key!r}")
        report = {
            "program": str(key),
            "source": e["source"],
            "flops": e["flops"],
            "bytes_accessed": e["bytes_accessed"],
            "peak_bytes": e.get("peak_bytes"),
            "device_kind": self.device_kind,
            "peak_flops": self.peak_flops,
            "peak_bytes_per_s": self.peak_bytes_per_s,
        }
        roof = self.roofline(key)
        if roof is not None:
            report.update(roof)
        if items_per_call:
            report["flops_per_item"] = e["flops"] / items_per_call
        if seconds_per_call:
            report["seconds_per_call"] = seconds_per_call
            report["achieved_flops_per_s"] = \
                e["flops"] / seconds_per_call
            report["mfu"] = self.mfu(key, seconds_per_call)
        labels = {"program": str(key)}
        _obs.set_gauge("dl4j_perf_program_flops", e["flops"],
                       labels=labels)
        _obs.set_gauge("dl4j_perf_program_bytes", e["bytes_accessed"],
                       labels=labels)
        if roof is not None:
            _obs.set_gauge("dl4j_perf_arithmetic_intensity",
                           roof["arithmetic_intensity"], labels=labels)
        if report.get("mfu") is not None:
            _obs.set_gauge("dl4j_perf_mfu", report["mfu"],
                           labels=labels)
        return report

    def digest(self, key) -> Optional[dict]:
        """Compact {flops, bytes, ai} for the JitCache forensics ring."""
        e = self._entries.get(str(key))
        if e is None:
            return None
        ai = self.arithmetic_intensity(key)
        return {"flops": e["flops"],
                "bytes_accessed": e["bytes_accessed"],
                "arithmetic_intensity":
                    round(ai, 3) if ai is not None else None}


# ------------------------------------------------ step phase profiler
PHASES = ("data_wait", "h2d", "dispatch", "device_compute",
          "host_sync", "checkpoint", "telemetry")
# pre-resolved accumulator keys: the per-step emission fast path pays
# a dict lookup per phase, not a label-dict build + sort per phase
_PHASE_KEYS = {p: ("dl4j_train_phase_seconds", (("phase", p),))
               for p in PHASES}


class StepPhaseProfiler:
    """Attribute every training step's wall time to named phases.

    The owning fit loop calls `begin_step()` once per step, `mark(p)`
    at each phase boundary (phase p runs from its mark to the next
    mark), optionally `sync(device_value)` right after dispatch — when
    this step samples a device sync (`sync_every`), the blocked
    `block_until_ready` interval becomes the device_compute phase —
    and `end_step()` in its finally. Durations land as
    `dl4j_train_phase_seconds{phase=...}` through the loop's
    StepAccumulator (container appends per step, one guarded registry
    write per flush — the PR 5 <2% discipline), cumulative totals stay
    on the instance for `report()`, and with a tracer attached each
    phase records a span on the shared timeline.

    NOT thread-safe — one owner loop per instance, like the
    accumulator it feeds."""

    def __init__(self, accumulator=None, tracer=None,
                 sync_every: int = 1):
        self.accumulator = accumulator
        self.tracer = tracer
        # sync_every=N blocks on the device value every Nth step (0 =
        # never): device_compute becomes visible at 1/N the host-sync
        # cost; un-synced steps leave device time inside dispatch.
        self.sync_every = max(0, int(sync_every))
        self.totals: Dict[str, float] = {p: 0.0 for p in PHASES}
        self.wall_s = 0.0
        self.steps = 0
        self._marks: List[Tuple[str, float]] = []
        self._t_begin: Optional[float] = None
        self._step = None

    def begin_step(self, step=None) -> None:
        self._t_begin = time.perf_counter()
        self._marks = []
        self._step = step

    def mark(self, phase: str) -> None:
        """Phase `phase` starts now (and the previous phase ends)."""
        self._marks.append((phase, time.perf_counter()))

    def should_sync(self, step=None) -> bool:
        if self.sync_every <= 0:
            return False
        s = self.steps if step is None else int(step)
        return s % self.sync_every == 0

    def sync(self, value, step=None) -> None:
        """Sampled device sync: on sampling steps, block until `value`
        is ready and attribute the blocked interval to device_compute.
        Swallows everything — profiling must never fail a step."""
        if value is None or not self.should_sync(step):
            return
        self.mark("device_compute")
        try:
            import jax

            jax.block_until_ready(value)
        except Exception:   # noqa: BLE001 - profiling is best-effort
            pass

    def end_step(self) -> None:
        if self._t_begin is None:
            return
        t_end = time.perf_counter()
        marks = self._marks
        durs: Dict[str, float] = {}
        for i, (ph, t) in enumerate(marks):
            t_next = marks[i + 1][1] if i + 1 < len(marks) else t_end
            durs[ph] = durs.get(ph, 0.0) + max(0.0, t_next - t)
        acc = self.accumulator
        tr = self.tracer
        for ph, d in durs.items():
            self.totals[ph] = self.totals.get(ph, 0.0) + d
            key = _PHASE_KEYS.get(ph)
            if acc is not None and key is not None:
                acc.observe_keyed(key, d)
            else:
                _obs.observe("dl4j_train_phase_seconds", d,
                             labels={"phase": ph})
        if tr is not None:
            for i, (ph, t) in enumerate(marks):
                t_next = marks[i + 1][1] if i + 1 < len(marks) else t_end
                tr.record(f"phase:{ph}", t, t_next, cat="phase",
                          args={"step": self._step})
        # the profiler's own emission cost is telemetry time too —
        # attribute it so coverage stays honest, not flattering
        t_done = time.perf_counter()
        self.totals["telemetry"] += t_done - t_end
        self.wall_s += t_done - self._t_begin
        self.steps += 1
        self._t_begin = None
        self._marks = []

    def report(self) -> dict:
        """Cumulative per-phase seconds + shares and the coverage
        fraction (sum of attributed phase time / wall time of the
        profiled steps) — the ≥95% acceptance observable."""
        attributed = sum(self.totals.values())
        phases = {
            p: {"seconds": round(s, 6),
                "share": (s / attributed) if attributed else 0.0}
            for p, s in self.totals.items() if s > 0.0}
        return {
            "steps": self.steps,
            "wall_s": round(self.wall_s, 6),
            "attributed_s": round(attributed, 6),
            "coverage": (attributed / self.wall_s) if self.wall_s
            else 0.0,
            "phases": phases,
        }

    def top_phases(self, n: int = 2) -> List[Tuple[str, float]]:
        """The n largest phases by share — the dashboard line's view."""
        attributed = sum(self.totals.values())
        if attributed <= 0.0:
            return []
        ranked = sorted(self.totals.items(), key=lambda kv: -kv[1])
        return [(p, s / attributed) for p, s in ranked[:n] if s > 0.0]


# --------------------------------------------- cross-rank aggregation
def dump_snapshot(path: str, registry=None, rank: Optional[int] = None,
                  extra: Optional[dict] = None) -> str:
    """Write this process's MetricsRegistry snapshot to `path` (tmp +
    os.replace so a reader never sees a torn file) — the per-rank half
    of the rank-0 pull path. `distributed_worker` calls this at exit;
    `aggregate_snapshots` merges the files."""
    snap = (registry or _obs.get_registry()).snapshot()
    doc = {"rank": rank, "wall_time": time.time(), "snapshot": snap}
    if extra:
        doc.update(extra)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def _load_snapshot(source, fallback_rank: int) -> Tuple[dict, int]:
    if isinstance(source, str):
        with open(source) as f:
            source = json.load(f)
    rank = fallback_rank
    snap = source
    if isinstance(source, dict) and "snapshot" in source:
        if source.get("rank") is not None:
            rank = int(source["rank"])
        snap = source["snapshot"]
    return snap, rank


def _with_rank(label_str: str, rank: int) -> str:
    inner = f'rank="{rank}"'
    if not label_str:
        return "{" + inner + "}"
    return label_str[:-1] + "," + inner + "}"


def aggregate_snapshots(sources) -> dict:
    """Merge per-rank snapshot dumps (paths, dump_snapshot docs, or raw
    snapshot dicts) into ONE fleet-level snapshot: counters summed per
    (name, label set), histogram buckets/counts/sums merged (ring
    quantiles cannot merge exactly and are dropped), gauges re-keyed
    with a rank label so per-rank values stay distinguishable. The
    result renders through `render_prometheus` — the fleet /metrics
    body MULTICHIP benches and the cluster supervisor report instead
    of rank-local numbers."""
    merged: dict = {"counters": {}, "gauges": {}, "histograms": {},
                    "ranks": 0, "uptime_s": 0.0}
    for i, source in enumerate(sources):
        snap, rank = _load_snapshot(source, i)
        for name, series in snap.get("counters", {}).items():
            tgt = merged["counters"].setdefault(name, {})
            for lab, v in series.items():
                tgt[lab] = tgt.get(lab, 0.0) + float(v)
        for name, series in snap.get("gauges", {}).items():
            tgt = merged["gauges"].setdefault(name, {})
            for lab, v in series.items():
                tgt[_with_rank(lab, rank)] = float(v)
        for name, h in snap.get("histograms", {}).items():
            tgt = merged["histograms"].setdefault(
                name, {"count": 0, "sum": 0.0, "buckets": {},
                       "p50": None, "p90": None, "p99": None})
            tgt["count"] += int(h.get("count", 0))
            tgt["sum"] = round(tgt["sum"] + float(h.get("sum", 0.0)), 9)
            for le, c in h.get("buckets", {}).items():
                tgt["buckets"][le] = tgt["buckets"].get(le, 0) + int(c)
        merged["ranks"] += 1
        merged["uptime_s"] = max(merged["uptime_s"],
                                 float(snap.get("uptime_s", 0.0)))
    return merged


def aggregate_prometheus_text(sources) -> str:
    """One fleet-level Prometheus exposition from per-rank snapshot
    files/dicts — `render_prometheus(aggregate_snapshots(...))`."""
    return render_prometheus(aggregate_snapshots(sources))


__all__ = [
    "PEAK_FLOPS", "PEAK_BYTES_PER_S", "PHASES",
    "CostModel", "StepPhaseProfiler",
    "device_peaks", "extract_cost",
    "matmul_flops", "conv2d_flops", "train_step_flops_from_params",
    "dump_snapshot", "aggregate_snapshots", "aggregate_prometheus_text",
    "render_prometheus",
]
