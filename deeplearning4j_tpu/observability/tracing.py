"""Span tracing with cross-thread parenting and Chrome trace export.

A `Tracer` records host-side spans — per-step training phases
(fetch → dispatch → device → fetch-result → checkpoint) and per-request
serving phases (enqueue → assemble → dispatch → complete → deliver) —
into a bounded in-memory ring buffer. Two parenting modes:

  implicit   `with tracer.span("outer"): with tracer.span("inner"):`
             nests via a thread-local stack (same thread);
  explicit   `tracer.begin("complete", parent=dispatch_span)` parents
             across threads — the serving pipeline's completion stage
             and the StepWatchdog's monitor thread both attach their
             spans to work that STARTED on another thread.

`export_chrome_trace()` writes Chrome trace-event JSON (Perfetto /
chrome://tracing loadable): "X" complete events on their real thread
tracks, thread-name metadata, and "s"/"f" flow events binding every
cross-thread parent→child edge so the handoff renders as an arrow, not
a coincidence. A `jax.profiler` device trace captured in the same run
(ProfilerListener) is registered on this timeline as a span carrying
its trace_dir, so host spans and the device profile can be correlated.

Continuous export: `start_background_flush(path, interval_s)` runs a
daemon thread that periodically DRAINS the ring buffer to a JSONL file
(one span dict per line) — long-running jobs stop losing spans to ring
wrap-around, and the export no longer depends on someone remembering
to call it. `stop_background_flush()` flushes the remainder;
`load_flushed(path)` reads the file back. The in-memory ring keeps
feeding `export_chrome_trace()` for ad-hoc snapshots between flushes.

Tracing is opt-in per component (`tracer=None` default everywhere):
the hot paths pay nothing unless a tracer is attached.

Cross-process requests: a generation that migrates between replicas
(or is recovered from the journal after a cold restart) leaves one
trace LEG per process, each tagged with the same `trace` arg (a
`new_trace_id()` riding the wire meta next to `request_id`).
`merge_chrome_traces()` folds the per-process exports into ONE
Perfetto document — distinct pids per leg, clocks aligned via each
doc's `unix_time_origin_s`, and an "s"/"f" flow arrow binding each
trace's consecutive legs so the hop renders as an arrow, not two
unrelated timelines.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional


def new_trace_id() -> str:
    """Fresh 16-hex trace id (traceparent-style, wire-safe). Minted by
    whichever hop sees the request first (router, server, or engine)
    and then propagated verbatim alongside `request_id`."""
    return uuid.uuid4().hex[:16]


class Span:
    """One finished-or-open span. `end()` is idempotent; the span holds
    its tracer so a handle can be resolved from any thread."""

    __slots__ = ("id", "name", "cat", "tid", "thread_name", "parent_id",
                 "args", "t0_us", "dur_us", "_tracer", "_done")

    def __init__(self, tracer: "Tracer", span_id: int, name: str,
                 cat: str, parent_id: Optional[int], t0_us: float,
                 args: Optional[dict]):
        self._tracer = tracer
        self.id = span_id
        self.name = name
        self.cat = cat
        self.parent_id = parent_id
        t = threading.current_thread()
        self.tid = t.ident or 0
        self.thread_name = t.name
        self.t0_us = t0_us
        self.dur_us: Optional[float] = None
        self.args = dict(args) if args else {}
        self._done = False

    def end(self, **extra_args) -> None:
        if self._done:
            return
        self._done = True
        if extra_args:
            self.args.update(extra_args)
        self._tracer._finish(self)

    def to_dict(self) -> dict:
        return {"id": self.id, "name": self.name, "cat": self.cat,
                "tid": self.tid, "thread_name": self.thread_name,
                "parent_id": self.parent_id, "t0_us": self.t0_us,
                "dur_us": self.dur_us, "args": dict(self.args)}


class Tracer:
    """Bounded-buffer span recorder (thread-safe)."""

    def __init__(self, max_spans: int = 20000,
                 flush_path: Optional[str] = None,
                 flush_interval_s: float = 2.0):
        """`flush_path` (optional) starts the continuous background
        flush at construction: every `flush_interval_s` the ring is
        drained to that JSONL file (and once more on stop)."""
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=max(1, int(max_spans)))
        self.max_spans = int(max_spans)
        self._ids = itertools.count(1)
        self._recorded = 0
        self._flushed = 0
        self._t0 = time.perf_counter()
        self._wall0 = time.time()
        self._local = threading.local()
        self._flush_path: Optional[str] = None
        self._flush_interval_s = float(flush_interval_s)
        self._flush_stop = threading.Event()
        self._flush_wake = threading.Event()
        self._flush_thread: Optional[threading.Thread] = None
        self._flush_file_lock = threading.Lock()
        if flush_path is not None:
            self.start_background_flush(flush_path, flush_interval_s)

    def _append(self, sp: "Span") -> None:
        """Buffer a finished span. Under continuous flush the ring
        never drops: a half-full ring wakes the flusher early, and a
        FULL ring makes the producer drain it inline (one amortized
        write per max_spans/2 spans, only when the flusher is starved)
        — the perfetto-style stall-don't-lose discipline."""
        with self._lock:
            full = (self._flush_path is not None
                    and len(self._buf) >= self.max_spans - 1)
            self._buf.append(sp)
            self._recorded += 1
            pressure = (self._flush_path is not None
                        and 2 * len(self._buf) >= self.max_spans)
        if full:
            self.flush_now()
        elif pressure:
            self._flush_wake.set()

    # ------------------------------------------------------------ clock
    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _to_us(self, perf_t: float) -> float:
        return (perf_t - self._t0) * 1e6

    # ------------------------------------------------------------ stack
    def _stack(self) -> List[Span]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def current(self) -> Optional[Span]:
        """This thread's innermost open span (hand it to another thread
        as an explicit `parent=`)."""
        st = self._stack()
        return st[-1] if st else None

    # ---------------------------------------------------------- record
    @staticmethod
    def _parent_id(parent) -> Optional[int]:
        if parent is None:
            return None
        return parent.id if isinstance(parent, Span) else int(parent)

    def begin(self, name: str, cat: str = "host", parent=None,
              args: Optional[dict] = None) -> Span:
        """Open a span. `parent` may be a Span (any thread) or id; when
        None the current thread's stack top parents it implicitly."""
        pid = self._parent_id(parent)
        if pid is None:
            cur = self.current()
            pid = cur.id if cur is not None else None
        return Span(self, next(self._ids), name, cat, pid,
                    self._now_us(), args)

    def _finish(self, span: Span) -> None:
        if span.dur_us is None:
            span.dur_us = max(0.0, self._now_us() - span.t0_us)
        self._append(span)

    @contextmanager
    def span(self, name: str, cat: str = "host", parent=None,
             args: Optional[dict] = None):
        sp = self.begin(name, cat=cat, parent=parent, args=args)
        st = self._stack()
        st.append(sp)
        try:
            yield sp
        finally:
            if st and st[-1] is sp:
                st.pop()
            sp.end()

    def record(self, name: str, start_perf: float, end_perf: float,
               cat: str = "host", parent=None,
               args: Optional[dict] = None) -> Span:
        """Record an already-measured interval (perf_counter values) —
        the fit loops already time their phases, so the span rides the
        same two clock reads."""
        sp = Span(self, next(self._ids), name, cat,
                  self._parent_id(parent), self._to_us(start_perf), args)
        sp.dur_us = max(0.0, (end_perf - start_perf) * 1e6)
        sp._done = True
        self._append(sp)
        return sp

    def instant(self, name: str, cat: str = "host", parent=None,
                args: Optional[dict] = None) -> Span:
        sp = self.begin(name, cat=cat, parent=parent, args=args)
        sp.dur_us = 0.0
        sp._done = True
        self._append(sp)
        return sp

    # ------------------------------------------------------------ reads
    def spans(self) -> List[dict]:
        with self._lock:
            return [s.to_dict() for s in self._buf]

    def stats(self) -> dict:
        with self._lock:
            buffered = len(self._buf)
            recorded = self._recorded
            flushed = self._flushed
        return {"recorded": recorded, "buffered": buffered,
                "flushed": flushed,
                "dropped": recorded - buffered - flushed,
                "max_spans": self.max_spans,
                "flush_path": self._flush_path,
                "flush_running": (
                    self._flush_thread is not None
                    and self._flush_thread.is_alive())}

    # ------------------------------------------------- continuous flush
    def start_background_flush(self, path: str,
                               interval_s: Optional[float] = None
                               ) -> None:
        """Start (or retarget) the continuous flush: a daemon thread
        drains the ring to `path` as JSONL every `interval_s` seconds,
        so spans survive ring wrap-around without manual exports.
        Idempotent per path; `stop_background_flush()` flushes the
        remainder and joins the thread."""
        if interval_s is not None:
            self._flush_interval_s = float(interval_s)
        self._flush_path = path
        if self._flush_thread is not None \
                and self._flush_thread.is_alive():
            return
        self._flush_stop.clear()
        self._flush_thread = threading.Thread(
            target=self._flush_loop, daemon=True,
            name="Tracer-span-flush")
        self._flush_thread.start()

    def stop_background_flush(self) -> int:
        """Stop the flush thread and flush whatever is still buffered
        (the flush-on-stop half of the contract). Returns the number
        of spans written by the final flush. Safe to call twice."""
        self._flush_stop.set()
        self._flush_wake.set()   # unblock the interval wait
        t, self._flush_thread = self._flush_thread, None
        if t is not None and t.is_alive():
            t.join(timeout=5.0)
        self._flush_stop.clear()   # a later start() can restart
        return self.flush_now()

    def flush_now(self) -> int:
        """Drain every completed span in the ring to the flush file
        (JSONL, one span dict per line). Returns spans written; no-op
        without a flush path."""
        if self._flush_path is None:
            return 0
        with self._lock:
            spans = [s.to_dict() for s in self._buf]
            self._buf.clear()
            self._flushed += len(spans)
        if not spans:
            return 0
        try:
            with self._flush_file_lock:
                with open(self._flush_path, "a") as f:
                    for s in spans:
                        f.write(json.dumps(s) + "\n")
        except OSError:
            # a full disk must not take down the job — the spans are
            # simply lost (still counted as flushed, not buffered)
            pass
        return len(spans)

    def _flush_loop(self) -> None:
        while True:
            self._flush_wake.wait(self._flush_interval_s)
            self._flush_wake.clear()
            if self._flush_stop.is_set():
                return   # stop_background_flush does the final drain
            self.flush_now()

    @staticmethod
    def load_flushed(path: str) -> List[dict]:
        """Read a flush file back into span dicts (skips torn tail
        lines from a crash mid-write)."""
        out: List[dict] = []
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue
        except OSError:
            pass
        return out

    # ----------------------------------------------------------- export
    def export_chrome_trace(self, path: Optional[str] = None) -> dict:
        """Chrome trace-event JSON (Perfetto-loadable). Every span is an
        "X" complete event on its real thread; cross-thread parent→child
        edges additionally emit an "s"/"f" flow pair so the handoff is
        drawn as an arrow between tracks."""
        pid = os.getpid()
        with self._lock:
            spans = list(self._buf)
        by_id: Dict[int, Span] = {s.id: s for s in spans}
        events: List[dict] = []
        seen_tids: Dict[int, str] = {}
        for s in spans:
            seen_tids.setdefault(s.tid, s.thread_name)
        for tid, tname in seen_tids.items():
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": tid, "args": {"name": tname}})
        for s in spans:
            args = dict(s.args)
            if s.parent_id is not None:
                args["parent_id"] = s.parent_id
            events.append({
                "ph": "X", "name": s.name, "cat": s.cat, "pid": pid,
                "tid": s.tid, "ts": round(s.t0_us, 3),
                "dur": round(s.dur_us or 0.0, 3), "args": args})
            parent = (by_id.get(s.parent_id)
                      if s.parent_id is not None else None)
            if parent is not None and parent.tid != s.tid:
                # flow: start at the parent, finish (enclosing-slice
                # binding) at the child — the cross-thread arrow
                events.append({
                    "ph": "s", "id": s.id, "name": "handoff",
                    "cat": "flow", "pid": pid, "tid": parent.tid,
                    "ts": round(parent.t0_us, 3)})
                events.append({
                    "ph": "f", "bp": "e", "id": s.id, "name": "handoff",
                    "cat": "flow", "pid": pid, "tid": s.tid,
                    "ts": round(s.t0_us, 3)})
        doc = {"traceEvents": events, "displayTimeUnit": "ms",
               "otherData": {"unix_time_origin_s": self._wall0,
                             "exporter": "deeplearning4j_tpu"}}
        if path:
            with open(path, "w") as f:
                json.dump(doc, f)
        return doc


# ------------------------------------------------- cross-process merge
def _load_trace_doc(doc_or_path):
    if isinstance(doc_or_path, str):
        with open(doc_or_path) as f:
            return json.load(f)
    return doc_or_path


def merge_chrome_traces(docs, path: Optional[str] = None,
                        labels: Optional[List[str]] = None) -> dict:
    """Merge per-process `export_chrome_trace()` docs into ONE
    Perfetto-loadable document (the snapshot-aggregation pattern,
    applied to traces).

    Each input doc becomes a distinct pid (its process_name from
    `labels`, else "proc<i>"), timestamps are rebased onto a shared
    origin using each doc's `otherData.unix_time_origin_s` wall clock,
    and per-doc flow ids are remapped so they cannot collide. Then, for
    every trace id seen (the `trace` span arg), the legs — one group of
    spans per input doc — are ordered by start time and consecutive
    legs are bound with an "s"/"f" flow pair named "trace-leg": the
    migration (or journal-recovery) hop renders as an arrow from the
    end of the last span of one replica's leg to the first span of the
    next replica's leg. Accepts doc dicts or file paths."""
    loaded = [_load_trace_doc(d) for d in docs]
    origins = [float((d.get("otherData") or {})
                     .get("unix_time_origin_s", 0.0)) for d in loaded]
    base = min(origins) if origins else 0.0
    events: List[dict] = []
    # per-trace-id legs: {trace_id: {doc_idx: [(ts, end_ts, ev), ...]}}
    legs: Dict[str, Dict[int, List[tuple]]] = {}
    for i, (doc, origin) in enumerate(zip(loaded, origins)):
        pid = i + 1
        shift_us = (origin - base) * 1e6
        name = (labels[i] if labels and i < len(labels)
                else f"proc{i}")
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": name}})
        events.append({"ph": "M", "name": "process_sort_index",
                       "pid": pid, "tid": 0,
                       "args": {"sort_index": i}})
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = pid
            if "ts" in ev:
                ev["ts"] = round(float(ev["ts"]) + shift_us, 3)
            if ev.get("cat") == "flow" and "id" in ev:
                # keep intra-doc flow pairs bound, but namespace them
                # per doc so two replicas' span ids cannot collide
                ev["id"] = f"p{pid}.{ev['id']}"
            events.append(ev)
            tid = (ev.get("args") or {}).get("trace")
            if ev.get("ph") == "X" and tid:
                t0 = float(ev["ts"])
                t1 = t0 + float(ev.get("dur", 0.0))
                legs.setdefault(str(tid), {}).setdefault(
                    i, []).append((t0, t1, ev))
    flow_ids = itertools.count(1)
    for trace_id, by_doc in sorted(legs.items()):
        groups = sorted(by_doc.values(),
                        key=lambda g: min(t0 for t0, _, _ in g))
        for prev, nxt in zip(groups, groups[1:]):
            _, src_end, src = max(prev, key=lambda g: g[1])
            dst_start, _, dst = min(nxt, key=lambda g: g[0])
            fid = f"trace.{trace_id}.{next(flow_ids)}"
            events.append({
                "ph": "s", "id": fid, "name": "trace-leg",
                "cat": "flow", "pid": src["pid"], "tid": src["tid"],
                "ts": round(src_end, 3)})
            events.append({
                "ph": "f", "bp": "e", "id": fid, "name": "trace-leg",
                "cat": "flow", "pid": dst["pid"], "tid": dst["tid"],
                "ts": round(dst_start, 3)})
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "otherData": {"unix_time_origin_s": base,
                         "exporter": "deeplearning4j_tpu",
                         "merged_docs": len(loaded)}}
    if path:
        with open(path, "w") as f:
            json.dump(doc, f)
    return doc
