"""Span tracing with cross-thread parenting and Chrome trace export.

A `Tracer` records host-side spans — per-step training phases
(fetch → dispatch → device → fetch-result → checkpoint) and per-request
serving phases (enqueue → assemble → dispatch → complete → deliver) —
into a bounded in-memory ring buffer. Two parenting modes:

  implicit   `with tracer.span("outer"): with tracer.span("inner"):`
             nests via a thread-local stack (same thread);
  explicit   `tracer.begin("complete", parent=dispatch_span)` parents
             across threads — the serving pipeline's completion stage
             and the StepWatchdog's monitor thread both attach their
             spans to work that STARTED on another thread.

`export_chrome_trace()` writes Chrome trace-event JSON (Perfetto /
chrome://tracing loadable): "X" complete events on their real thread
tracks, thread-name metadata, and "s"/"f" flow events binding every
cross-thread parent→child edge so the handoff renders as an arrow, not
a coincidence. A `jax.profiler` device trace captured in the same run
(ProfilerListener) is registered on this timeline as a span carrying
its trace_dir, so host spans and the device profile can be correlated.

Tracing is opt-in per component (`tracer=None` default everywhere):
the hot paths pay nothing unless a tracer is attached.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional


class Span:
    """One finished-or-open span. `end()` is idempotent; the span holds
    its tracer so a handle can be resolved from any thread."""

    __slots__ = ("id", "name", "cat", "tid", "thread_name", "parent_id",
                 "args", "t0_us", "dur_us", "_tracer", "_done")

    def __init__(self, tracer: "Tracer", span_id: int, name: str,
                 cat: str, parent_id: Optional[int], t0_us: float,
                 args: Optional[dict]):
        self._tracer = tracer
        self.id = span_id
        self.name = name
        self.cat = cat
        self.parent_id = parent_id
        t = threading.current_thread()
        self.tid = t.ident or 0
        self.thread_name = t.name
        self.t0_us = t0_us
        self.dur_us: Optional[float] = None
        self.args = dict(args) if args else {}
        self._done = False

    def end(self, **extra_args) -> None:
        if self._done:
            return
        self._done = True
        if extra_args:
            self.args.update(extra_args)
        self._tracer._finish(self)

    def to_dict(self) -> dict:
        return {"id": self.id, "name": self.name, "cat": self.cat,
                "tid": self.tid, "thread_name": self.thread_name,
                "parent_id": self.parent_id, "t0_us": self.t0_us,
                "dur_us": self.dur_us, "args": dict(self.args)}


class Tracer:
    """Bounded-buffer span recorder (thread-safe)."""

    def __init__(self, max_spans: int = 20000):
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=max(1, int(max_spans)))
        self.max_spans = int(max_spans)
        self._ids = itertools.count(1)
        self._recorded = 0
        self._t0 = time.perf_counter()
        self._wall0 = time.time()
        self._local = threading.local()

    # ------------------------------------------------------------ clock
    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _to_us(self, perf_t: float) -> float:
        return (perf_t - self._t0) * 1e6

    # ------------------------------------------------------------ stack
    def _stack(self) -> List[Span]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def current(self) -> Optional[Span]:
        """This thread's innermost open span (hand it to another thread
        as an explicit `parent=`)."""
        st = self._stack()
        return st[-1] if st else None

    # ---------------------------------------------------------- record
    @staticmethod
    def _parent_id(parent) -> Optional[int]:
        if parent is None:
            return None
        return parent.id if isinstance(parent, Span) else int(parent)

    def begin(self, name: str, cat: str = "host", parent=None,
              args: Optional[dict] = None) -> Span:
        """Open a span. `parent` may be a Span (any thread) or id; when
        None the current thread's stack top parents it implicitly."""
        pid = self._parent_id(parent)
        if pid is None:
            cur = self.current()
            pid = cur.id if cur is not None else None
        return Span(self, next(self._ids), name, cat, pid,
                    self._now_us(), args)

    def _finish(self, span: Span) -> None:
        if span.dur_us is None:
            span.dur_us = max(0.0, self._now_us() - span.t0_us)
        with self._lock:
            self._buf.append(span)
            self._recorded += 1

    @contextmanager
    def span(self, name: str, cat: str = "host", parent=None,
             args: Optional[dict] = None):
        sp = self.begin(name, cat=cat, parent=parent, args=args)
        st = self._stack()
        st.append(sp)
        try:
            yield sp
        finally:
            if st and st[-1] is sp:
                st.pop()
            sp.end()

    def record(self, name: str, start_perf: float, end_perf: float,
               cat: str = "host", parent=None,
               args: Optional[dict] = None) -> Span:
        """Record an already-measured interval (perf_counter values) —
        the fit loops already time their phases, so the span rides the
        same two clock reads."""
        sp = Span(self, next(self._ids), name, cat,
                  self._parent_id(parent), self._to_us(start_perf), args)
        sp.dur_us = max(0.0, (end_perf - start_perf) * 1e6)
        sp._done = True
        with self._lock:
            self._buf.append(sp)
            self._recorded += 1
        return sp

    def instant(self, name: str, cat: str = "host", parent=None,
                args: Optional[dict] = None) -> Span:
        sp = self.begin(name, cat=cat, parent=parent, args=args)
        sp.dur_us = 0.0
        sp._done = True
        with self._lock:
            self._buf.append(sp)
            self._recorded += 1
        return sp

    # ------------------------------------------------------------ reads
    def spans(self) -> List[dict]:
        with self._lock:
            return [s.to_dict() for s in self._buf]

    def stats(self) -> dict:
        with self._lock:
            buffered = len(self._buf)
            recorded = self._recorded
        return {"recorded": recorded, "buffered": buffered,
                "dropped": recorded - buffered,
                "max_spans": self.max_spans}

    # ----------------------------------------------------------- export
    def export_chrome_trace(self, path: Optional[str] = None) -> dict:
        """Chrome trace-event JSON (Perfetto-loadable). Every span is an
        "X" complete event on its real thread; cross-thread parent→child
        edges additionally emit an "s"/"f" flow pair so the handoff is
        drawn as an arrow between tracks."""
        pid = os.getpid()
        with self._lock:
            spans = list(self._buf)
        by_id: Dict[int, Span] = {s.id: s for s in spans}
        events: List[dict] = []
        seen_tids: Dict[int, str] = {}
        for s in spans:
            seen_tids.setdefault(s.tid, s.thread_name)
        for tid, tname in seen_tids.items():
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": tid, "args": {"name": tname}})
        for s in spans:
            args = dict(s.args)
            if s.parent_id is not None:
                args["parent_id"] = s.parent_id
            events.append({
                "ph": "X", "name": s.name, "cat": s.cat, "pid": pid,
                "tid": s.tid, "ts": round(s.t0_us, 3),
                "dur": round(s.dur_us or 0.0, 3), "args": args})
            parent = (by_id.get(s.parent_id)
                      if s.parent_id is not None else None)
            if parent is not None and parent.tid != s.tid:
                # flow: start at the parent, finish (enclosing-slice
                # binding) at the child — the cross-thread arrow
                events.append({
                    "ph": "s", "id": s.id, "name": "handoff",
                    "cat": "flow", "pid": pid, "tid": parent.tid,
                    "ts": round(parent.t0_us, 3)})
                events.append({
                    "ph": "f", "bp": "e", "id": s.id, "name": "handoff",
                    "cat": "flow", "pid": pid, "tid": s.tid,
                    "ts": round(s.t0_us, 3)})
        doc = {"traceEvents": events, "displayTimeUnit": "ms",
               "otherData": {"unix_time_origin_s": self._wall0,
                             "exporter": "deeplearning4j_tpu"}}
        if path:
            with open(path, "w") as f:
                json.dump(doc, f)
        return doc
