"""MetricsRegistry: one thread-safe substrate for every counter in the
stack.

Before this module each subsystem invented its own stats shape —
`training_stats()["resilience"]`, ParallelInference `stats()`, JitCache
trace counters, ClusterSupervisor ledgers, the dashboard's ad-hoc dicts.
The registry replaces those *transport* shapes with one namespace of
named metrics (the component-local `stats()` methods remain as richer
debugging views):

  counters    monotonic floats, optional labels ({"code": "503"})
  gauges      last-write-wins floats; `gauge_fn` registers a pull-style
              provider evaluated at snapshot/scrape time
  histograms  fixed-boundary buckets (Prometheus exposition) PLUS a
              bounded ring buffer of recent raw observations for
              p50/p90/p99 estimation without streaming sketches

Emission is failure-proof by construction: production code emits
through the module-level `count/observe/set_gauge/gauge_fn` helpers,
each of which passes through the `obs.emit` fault point and swallows
ANY exception (counted in `dl4j_obs_dropped_emissions_total`) — an
injected or real telemetry failure must never break a training step or
drop a request. `enable(False)` turns every helper into a constant-time
no-op (the bench_obs.py baseline).

`REGISTERED_METRICS` is the canonical name registry, pinned by a test
exactly like `faults.REGISTERED_POINTS`: every emission site in the
package must use a registered literal name, and every registered name
must be emitted somewhere and exercised by at least one test.
"""

from __future__ import annotations

import bisect
import re
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from deeplearning4j_tpu.resilience.faults import (
    fire as _fire,
    injector as _injector,
)

# latency-shaped default boundaries (seconds)
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
# row-count-shaped boundaries (batch occupancy, powers of two)
COUNT_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0)

# every metric name the package may emit (pinned by
# tests/test_observability.py: emission sites == registry == tested)
REGISTERED_METRICS = frozenset({
    # training domain
    "dl4j_train_steps_total",
    "dl4j_train_step_seconds",
    "dl4j_train_loss",
    "dl4j_train_data_wait_seconds",
    "dl4j_train_data_skipped_steps_total",
    "dl4j_train_guard_checks_total",
    "dl4j_train_guard_nonfinite_total",
    "dl4j_train_guard_spikes_total",
    "dl4j_train_guard_skipped_steps_total",
    "dl4j_train_guard_rollbacks_total",
    "dl4j_train_watchdog_hangs_total",
    "dl4j_train_preemptions_total",
    "dl4j_train_supervisor_restarts_total",
    # checkpoint domain
    "dl4j_checkpoint_writes_total",
    "dl4j_checkpoint_write_seconds",
    "dl4j_checkpoint_restores_total",
    "dl4j_checkpoint_restore_seconds",
    "dl4j_checkpoint_validate_failures_total",
    # serving domain
    "dl4j_serving_requests_total",
    "dl4j_serving_errors_total",
    "dl4j_serving_request_seconds",
    "dl4j_serving_queue_depth",
    "dl4j_serving_inflight_batches",
    "dl4j_serving_batches_total",
    "dl4j_serving_batch_occupancy",
    "dl4j_serving_bucket_splits_total",
    # serving control plane (multi-model registry, tenants, routing)
    "dl4j_serving_model_requests_total",
    "dl4j_serving_admitted_total",
    "dl4j_serving_shed_total",
    "dl4j_serving_swaps_total",
    "dl4j_serving_rollbacks_total",
    "dl4j_serving_load_rejected_total",
    "dl4j_serving_active_models",
    "dl4j_serving_replica_failovers_total",
    # fleet rollout controller (serving/controller.py)
    "dl4j_fleet_replicas",
    "dl4j_fleet_scale_events_total",
    "dl4j_fleet_replica_deaths_total",
    "dl4j_rollout_state",
    "dl4j_rollout_total",
    "dl4j_rollout_rollbacks_total",
    "dl4j_rollout_holddowns_total",
    "dl4j_rollout_detection_seconds",
    # continuous-batching decode engine (serving/continuous.py)
    "dl4j_decode_active_slots",
    "dl4j_decode_tokens_total",
    "dl4j_decode_tokens_per_s",
    "dl4j_decode_prefill_seconds",
    "dl4j_decode_slot_evictions_total",
    # paged KV virtual memory (prefix trie / chunked prefill / ring wrap)
    "dl4j_decode_prefix_hits_total",
    "dl4j_decode_prefix_pages_shared",
    "dl4j_decode_pages_free",
    "dl4j_decode_prefill_chunks_total",
    "dl4j_decode_ctx_wraps_total",
    # decode durability (quarantine / migration / watchdog / deadlines)
    "dl4j_decode_slot_quarantines_total",
    "dl4j_decode_migrations_total",
    "dl4j_decode_replays_total",
    "dl4j_decode_deadline_expired_total",
    "dl4j_decode_engine_restarts_total",
    # per-request latency attribution (TTFT / inter-token / queue wait,
    # labeled by tenant class) + the crash flight recorder
    "dl4j_decode_ttft_seconds",
    "dl4j_decode_itl_seconds",
    "dl4j_decode_queue_wait_seconds",
    "dl4j_decode_flight_dumps_total",
    "dl4j_jit_traces_total",
    "dl4j_jit_compiles_total",
    # performance introspection (observability/perf.py)
    "dl4j_perf_mfu",
    "dl4j_perf_program_flops",
    "dl4j_perf_program_bytes",
    "dl4j_perf_arithmetic_intensity",
    "dl4j_train_phase_seconds",
    # harness-owned input pipeline (engine/pipeline.py)
    "dl4j_pipeline_batches_total",
    "dl4j_pipeline_wait_seconds",
    "dl4j_pipeline_reseeks_total",
    "dl4j_pipeline_depth",
    # device-mesh sharding subsystem (engine/mesh.py, ZeRO-1 scale-out)
    "dl4j_mesh_world_size",
    "dl4j_mesh_reshard_total",
    "dl4j_mesh_allgather_seconds",
    # resilience plumbing
    "dl4j_retry_attempts_total",
    "dl4j_breaker_transitions_total",
    "dl4j_cluster_gang_restarts_total",
    "dl4j_cluster_quarantined_workers_total",
    "dl4j_cluster_spare_reschedules_total",
    "dl4j_cluster_shrinks_total",
    "dl4j_cluster_world_size",
    # durable serving journal (serving/journal.py)
    "dl4j_journal_records_total",
    "dl4j_journal_fsyncs_total",
    "dl4j_journal_torn_tails_total",
    "dl4j_journal_recovered_requests_total",
    "dl4j_journal_compactions_total",
    "dl4j_journal_bytes",
    "dl4j_journal_live",
    # derived by the registry itself (no count()/observe() call site)
    "dl4j_obs_dropped_emissions_total",
})

# registered names the registry synthesizes internally — the pin test
# excludes these from the "must have an emission call site" check
DERIVED_METRICS = frozenset({"dl4j_obs_dropped_emissions_total"})

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Optional[dict]) -> _LabelKey:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _label_str(key: _LabelKey) -> str:
    if not key:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in key) + "}"


class _Hist:
    __slots__ = ("buckets", "counts", "sum", "count", "ring")

    def __init__(self, buckets, ring_size: int):
        self.buckets: Tuple[float, ...] = tuple(
            sorted(float(b) for b in buckets))
        self.counts: List[int] = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.count = 0
        self.ring: deque = deque(maxlen=ring_size)

    def observe(self, v: float) -> None:
        self.counts[bisect.bisect_left(self.buckets, v)] += 1
        self.sum += v
        self.count += 1
        self.ring.append(v)

    def quantile(self, q: float) -> Optional[float]:
        """Estimate from the ring of recent raw observations (exact over
        the window, no sketch error — the window IS the estimator)."""
        if not self.ring:
            return None
        vals = sorted(self.ring)
        idx = min(len(vals) - 1, max(0, int(q * len(vals))))
        return vals[idx]


class MetricsRegistry:
    """Thread-safe named counters/gauges/histograms + exposition.

    All mutation happens under one lock — exact totals under concurrent
    emission (pinned by test) beat lock-free approximations here; the
    protected section is a couple of dict operations."""

    def __init__(self, ring_size: int = 512):
        self._lock = threading.Lock()
        self._ring_size = int(ring_size)
        self._counters: Dict[str, Dict[_LabelKey, float]] = {}
        self._gauges: Dict[str, Dict[_LabelKey, float]] = {}
        self._gauge_fns: Dict[str, Callable[[], float]] = {}
        # histograms are label-aware (dl4j_train_phase_seconds{phase=})
        # — one _Hist per (name, label set), unlabeled = the () key
        self._hists: Dict[str, Dict[_LabelKey, _Hist]] = {}
        self._created = time.monotonic()
        self.dropped = 0

    # ------------------------------------------------------------ writes
    def inc(self, name: str, n: float = 1.0,
            labels: Optional[dict] = None) -> None:
        key = _label_key(labels)
        with self._lock:
            series = self._counters.setdefault(name, {})
            series[key] = series.get(key, 0.0) + n

    def set_gauge(self, name: str, value: float,
                  labels: Optional[dict] = None) -> None:
        key = _label_key(labels)
        with self._lock:
            self._gauges.setdefault(name, {})[key] = float(value)

    def gauge_fn(self, name: str, fn: Callable[[], float]) -> None:
        """Register a pull-style gauge provider, evaluated (and
        swallowed on failure) at snapshot/scrape time."""
        with self._lock:
            self._gauge_fns[name] = fn

    def _hist(self, name: str, key: _LabelKey, buckets) -> _Hist:
        """The (name, label set) histogram, created on first observe.
        Caller holds the lock."""
        series = self._hists.setdefault(name, {})
        h = series.get(key)
        if h is None:
            h = _Hist(buckets if buckets is not None
                      else DEFAULT_BUCKETS, self._ring_size)
            series[key] = h
        return h

    def observe(self, name: str, value: float, buckets=None,
                labels: Optional[dict] = None) -> None:
        key = _label_key(labels)
        with self._lock:
            self._hist(name, key, buckets).observe(float(value))

    def inc_observe(self, counter_name: str, hist_name: str,
                    value: float, n: float = 1.0,
                    buckets=None) -> None:
        """Fused counter-increment + histogram-observe under ONE lock
        acquisition — the per-step hot path (steps_total +
        step_seconds, batches_total + occupancy) emits two metrics for
        one lock's worth of overhead."""
        with self._lock:
            series = self._counters.setdefault(counter_name, {})
            series[()] = series.get((), 0.0) + n
            self._hist(hist_name, (), buckets).observe(float(value))

    def apply_batch(self, counts: Dict[str, float],
                    hist_values: Dict, buckets=None) -> None:
        """Atomically fold in a StepAccumulator's pending aggregate —
        totals and observations identical to emitting one by one, for
        one lock acquisition per flush instead of per step. Histogram
        keys are either a name or a (name, label-key) tuple (the
        accumulator's labeled-observation form)."""
        with self._lock:
            for name, n in counts.items():
                series = self._counters.setdefault(name, {})
                series[()] = series.get((), 0.0) + n
            for hkey, vals in hist_values.items():
                name, lk = (hkey if isinstance(hkey, tuple)
                            else (hkey, ()))
                h = self._hist(name, lk, buckets)
                for v in vals:
                    h.observe(v)

    def note_dropped(self) -> None:
        with self._lock:
            self.dropped += 1

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._gauge_fns.clear()
            self._hists.clear()
            self.dropped = 0
            self._created = time.monotonic()

    # ------------------------------------------------------------- reads
    def uptime_s(self) -> float:
        return time.monotonic() - self._created

    def counter_value(self, name: str,
                      labels: Optional[dict] = None) -> float:
        """One series' value; with labels=None the sum over ALL label
        sets of `name` (the /status monotonic-total view)."""
        with self._lock:
            series = self._counters.get(name, {})
            if labels is None:
                return float(sum(series.values()))
            return float(series.get(_label_key(labels), 0.0))

    def gauge_value(self, name: str,
                    labels: Optional[dict] = None) -> Optional[float]:
        with self._lock:
            fn = self._gauge_fns.get(name)
            series = dict(self._gauges.get(name, {}))
        if fn is not None and labels is None:
            try:
                return float(fn())
            except Exception:   # noqa: BLE001 - provider must not break reads
                self.note_dropped()
                return None
        return series.get(_label_key(labels))

    def _eval_gauge_fns(self) -> Dict[str, float]:
        with self._lock:
            fns = dict(self._gauge_fns)
        out = {}
        for name, fn in fns.items():
            try:
                out[name] = float(fn())
            except Exception:   # noqa: BLE001 - provider must not break scrape
                self.note_dropped()
        return out

    def snapshot(self) -> dict:
        """One coherent dict of everything: the dashboard's (and any
        in-process consumer's) read surface."""
        pulled = self._eval_gauge_fns()
        with self._lock:
            counters = {
                name: {_label_str(k): v for k, v in series.items()}
                for name, series in self._counters.items()}
            gauges = {
                name: {_label_str(k): v for k, v in series.items()}
                for name, series in self._gauges.items()}
            hists = {}
            for name, series in self._hists.items():
                for lk, h in series.items():
                    # unlabeled series keeps the bare name (the
                    # pre-labeled-histogram snapshot contract)
                    hists[name + _label_str(lk)] = {
                        "count": h.count,
                        "sum": round(h.sum, 9),
                        "buckets": {("+Inf" if i == len(h.buckets)
                                     else repr(h.buckets[i])): c
                                    for i, c in enumerate(h.counts)},
                        "p50": h.quantile(0.50),
                        "p90": h.quantile(0.90),
                        "p99": h.quantile(0.99),
                    }
            dropped = self.dropped
        for name, v in pulled.items():
            gauges.setdefault(name, {})[""] = v
        counters.setdefault(
            "dl4j_obs_dropped_emissions_total", {})[""] = float(dropped)
        return {"counters": counters, "gauges": gauges,
                "histograms": hists, "uptime_s": self.uptime_s()}

    def prometheus_text(self) -> str:
        """Prometheus text exposition format 0.0.4 (the GET /metrics
        body)."""
        return render_prometheus(self.snapshot())


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _split_hist_name(full: str) -> Tuple[str, str]:
    """'name{a="b"}' -> ('name', 'a="b"'); bare names -> (name, '')."""
    base, _, lab = full.partition("{")
    return base, (lab[:-1] if lab.endswith("}") else lab)


def _bucket_order(item) -> float:
    le = item[0]
    return float("inf") if le == "+Inf" else float(le)


def render_prometheus(snap: dict) -> str:
    """Render a `MetricsRegistry.snapshot()`-shaped dict to Prometheus
    text exposition 0.0.4. Module-level so perf.aggregate_snapshots can
    render a merged fleet-level snapshot through the exact same code
    path as a single registry's /metrics body."""
    lines: List[str] = []
    for name in sorted(snap.get("counters", {})):
        lines.append(f"# TYPE {name} counter")
        for lab, v in sorted(snap["counters"][name].items()):
            lines.append(f"{name}{lab} {_fmt(v)}")
    for name in sorted(snap.get("gauges", {})):
        lines.append(f"# TYPE {name} gauge")
        for lab, v in sorted(snap["gauges"][name].items()):
            lines.append(f"{name}{lab} {_fmt(v)}")
    typed = set()
    for full in sorted(snap.get("histograms", {})):
        h = snap["histograms"][full]
        base, inner = _split_hist_name(full)
        if base not in typed:
            typed.add(base)
            lines.append(f"# TYPE {base} histogram")
        pre = inner + "," if inner else ""
        suffix = "{" + inner + "}" if inner else ""
        cum = 0
        for le, c in sorted(h["buckets"].items(), key=_bucket_order):
            cum += c
            lines.append(f'{base}_bucket{{{pre}le="{le}"}} {cum}')
        lines.append(f"{base}_sum{suffix} {_fmt(h['sum'])}")
        lines.append(f"{base}_count{suffix} {h['count']}")
    return "\n".join(lines) + "\n"


_LABEL_PAIR = re.compile(r'(\w+)="([^"]*)"')


def parse_prometheus_snapshot(text: str) -> dict:
    """Parse exposition text back into a `MetricsRegistry.snapshot()`-
    shaped dict — the inverse of `render_prometheus` (ring quantiles
    cannot survive the wire and come back as None; histogram bucket
    counts are de-cumulated back to per-bucket form).

    This is the scrape half of fleet-level aggregation: a controller
    scrapes each replica's /metrics body, rebuilds snapshots with this,
    and merges them through `perf.aggregate_snapshots` — the same merge
    path the cross-rank training exposition uses."""
    types: Dict[str, str] = {}
    snap: dict = {"counters": {}, "gauges": {}, "histograms": {}}
    hist_raw: Dict[str, dict] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        full, _, val = line.rpartition(" ")
        try:
            value = float(val)
        except ValueError:
            continue
        base, lab = _split_hist_name(full)
        for suffix in ("_bucket", "_sum", "_count"):
            if base.endswith(suffix) \
                    and types.get(base[:-len(suffix)]) == "histogram":
                hname = base[:-len(suffix)]
                pairs = _LABEL_PAIR.findall(lab)
                le = dict(pairs).get("le")
                rest = sorted((k, v) for k, v in pairs if k != "le")
                series_key = hname + _label_str(tuple(rest))
                h = hist_raw.setdefault(
                    series_key, {"count": 0, "sum": 0.0, "cum": []})
                if suffix == "_bucket" and le is not None:
                    h["cum"].append((le, value))
                elif suffix == "_sum":
                    h["sum"] = value
                else:
                    h["count"] = int(value)
                break
        else:
            kind = types.get(base)
            tgt = snap["gauges"] if kind == "gauge" else snap["counters"]
            tgt.setdefault(base, {})[
                "{" + lab + "}" if lab else ""] = value
    for series_key, h in hist_raw.items():
        cum = sorted(h["cum"], key=_bucket_order)
        buckets, prev = {}, 0
        for le, c in cum:
            buckets[le] = int(c) - prev
            prev = int(c)
        snap["histograms"][series_key] = {
            "count": h["count"], "sum": h["sum"], "buckets": buckets,
            "p50": None, "p90": None, "p99": None}
    return snap


def parse_prometheus(text: str) -> Dict[str, float]:
    """Parse exposition text into {sample_name_with_labels: value} —
    the ModelClient.metrics() helper tests assert against."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name, _, val = line.rpartition(" ")
        try:
            out[name] = float(val)
        except ValueError:
            continue
    return out


# ---------------------------------------------------- guarded emission
# process-global default registry: every subsystem emits here, /metrics
# scrapes here, the dashboard renders from here
_DEFAULT = MetricsRegistry()
_ENABLED = True
_INJ = _injector()


def _maybe_fire() -> None:
    """The `obs.emit` fault point, gated on a LOCK-FREE armed check:
    until some fault is armed the happy-path emission pays one dict
    truthiness read instead of fire()'s lock + hit accounting (measured
    ~3 us per call in situ — the dominant third of emission cost).
    Chaos runs arm a spec and get the full fire."""
    if _INJ._specs:
        _fire("obs.emit")


def get_registry() -> MetricsRegistry:
    return _DEFAULT


def enable(on: bool = True) -> None:
    """Global kill switch: enable(False) turns every emission helper
    into a constant-time no-op (the bench_obs.py off-baseline). Hot single-threaded loops (the
per-step training sites) batch through a `StepAccumulator` instead:
container appends per step, one guarded registry write per 32 steps —
same totals, ~10x less in-situ cost (PERF.md "Telemetry overhead")."""
    global _ENABLED
    _ENABLED = bool(on)


def telemetry_enabled() -> bool:
    return _ENABLED


def count(name: str, n: float = 1.0,
          labels: Optional[dict] = None) -> None:
    """Increment a counter. NEVER raises: the `obs.emit` fault point
    fires inside the guard, so injected (or real) emission failures are
    swallowed and counted as dropped — telemetry can't fail a step."""
    if not _ENABLED:
        return
    try:
        _maybe_fire()
        _DEFAULT.inc(name, n, labels)
    except Exception:   # noqa: BLE001 - telemetry must never propagate
        try:
            _DEFAULT.note_dropped()
        except Exception:   # noqa: BLE001 - even the drop note is best-effort
            pass


def observe(name: str, value: float, buckets=None,
            labels: Optional[dict] = None) -> None:
    if not _ENABLED:
        return
    try:
        _maybe_fire()
        _DEFAULT.observe(name, value, buckets=buckets, labels=labels)
    except Exception:   # noqa: BLE001 - telemetry must never propagate
        try:
            _DEFAULT.note_dropped()
        except Exception:   # noqa: BLE001
            pass


def count_observe(counter_name: str, hist_name: str, value: float,
                  n: float = 1.0, buckets=None) -> None:
    """Fused counter + histogram emission (one guarded call, one lock)
    for the hot per-step/per-batch sites."""
    if not _ENABLED:
        return
    try:
        _maybe_fire()
        _DEFAULT.inc_observe(counter_name, hist_name, value, n=n,
                             buckets=buckets)
    except Exception:   # noqa: BLE001 - telemetry must never propagate
        try:
            _DEFAULT.note_dropped()
        except Exception:   # noqa: BLE001
            pass


def set_gauge(name: str, value: float,
              labels: Optional[dict] = None) -> None:
    if not _ENABLED:
        return
    try:
        _maybe_fire()
        _DEFAULT.set_gauge(name, value, labels)
    except Exception:   # noqa: BLE001 - telemetry must never propagate
        try:
            _DEFAULT.note_dropped()
        except Exception:   # noqa: BLE001
            pass


def gauge_fn(name: str, fn: Callable[[], float]) -> None:
    if not _ENABLED:
        return
    try:
        _maybe_fire()
        _DEFAULT.gauge_fn(name, fn)
    except Exception:   # noqa: BLE001 - telemetry must never propagate
        try:
            _DEFAULT.note_dropped()
        except Exception:   # noqa: BLE001
            pass


class StepAccumulator:
    """Client-side aggregation for a single-threaded hot loop (the
    per-step training emissions): appends land in plain dicts/lists —
    no lock, no fault point, no histogram bisect — and the aggregate is
    flushed through ONE guarded registry write every `flush_every`
    loop iterations plus at loop end. In-situ emission cost on a
    dispatch-bound fit loop measured ~7 us/call (4-7x the tight-loop
    microbench — cold caches between XLA dispatches); batching makes
    the per-step cost two container appends (~0.2 us).

    Totals and histogram observations are exactly what per-step
    emission would have produced; a /metrics scrape between flushes
    just sees the registry up to `flush_every` steps stale. The flush
    passes the `obs.emit` fault point: an injected emission failure
    drops that flush's aggregate (counted in
    dl4j_obs_dropped_emissions_total) and never reaches the loop.

    NOT thread-safe by design — one owner loop per instance."""

    __slots__ = ("flush_every", "_counts", "_hist_vals", "_pending")

    def __init__(self, flush_every: int = 32):
        self.flush_every = max(1, int(flush_every))
        self._counts: Dict[str, float] = {}
        self._hist_vals: Dict[str, List[float]] = {}
        self._pending = 0

    def count(self, name: str, n: float = 1.0) -> None:
        if not _ENABLED:
            return
        self._counts[name] = self._counts.get(name, 0.0) + n

    def observe(self, name: str, value: float,
                labels: Optional[dict] = None) -> None:
        """Labeled observations (the phase-attribution site) key the
        pending dict on (name, label-key); apply_batch folds both forms
        into the registry identically."""
        if not _ENABLED:
            return
        key = (name, _label_key(labels)) if labels else name
        self._hist_vals.setdefault(key, []).append(float(value))

    def observe_keyed(self, key, value: float) -> None:
        """Pre-resolved (name, label-key) observation — the phase
        profiler's per-step fast path (no label dict built, no sort
        per call; the key tuples are computed once at import)."""
        if not _ENABLED:
            return
        self._hist_vals.setdefault(key, []).append(float(value))

    def count_observe(self, counter_name: str, hist_name: str,
                      value: float, n: float = 1.0) -> None:
        """The per-iteration site: also advances the flush cadence."""
        if not _ENABLED:
            return
        self._counts[counter_name] = \
            self._counts.get(counter_name, 0.0) + n
        self._hist_vals.setdefault(hist_name, []).append(float(value))
        self._pending += 1
        if self._pending >= self.flush_every:
            self.flush()

    def flush(self) -> None:
        """Push the pending aggregate through the guarded emission
        boundary. NEVER raises; a failure drops this batch only."""
        counts, hists = self._counts, self._hist_vals
        self._counts, self._hist_vals, self._pending = {}, {}, 0
        if not (counts or hists) or not _ENABLED:
            return
        try:
            _maybe_fire()
            _DEFAULT.apply_batch(counts, hists)
        except Exception:   # noqa: BLE001 - telemetry must never propagate
            try:
                _DEFAULT.note_dropped()
            except Exception:   # noqa: BLE001
                pass
