"""Unified telemetry substrate: metrics registry, span tracing,
Prometheus exposition, trace export.

One low-overhead layer beneath every workload (training, serving,
checkpointing, resilience) — the TPP-style uniform instrumentation
argument applied to this stack. See metrics.py and tracing.py module
docstrings for the design; README "Observability" for the operator
recipes (scrape /metrics, export a Perfetto trace)."""

from deeplearning4j_tpu.observability.metrics import (  # noqa: F401
    DERIVED_METRICS,
    MetricsRegistry,
    REGISTERED_METRICS,
    StepAccumulator,
    count,
    count_observe,
    enable,
    gauge_fn,
    get_registry,
    observe,
    parse_prometheus,
    parse_prometheus_snapshot,
    set_gauge,
    telemetry_enabled,
)
from deeplearning4j_tpu.observability.metrics import (  # noqa: F401
    render_prometheus,
)
from deeplearning4j_tpu.observability.perf import (  # noqa: F401
    CostModel,
    StepPhaseProfiler,
    aggregate_prometheus_text,
    aggregate_snapshots,
    dump_snapshot,
    extract_cost,
)
from deeplearning4j_tpu.observability.tracing import (  # noqa: F401
    Span,
    Tracer,
)
from deeplearning4j_tpu.observability.telemetry import (  # noqa: F401
    TelemetryListener,
)
