"""Device-mesh construction.

Axis convention (the framework's standard mesh axes; every parallel
component names these rather than inventing its own):

- ``dp``: data parallel — batch dim sharded, params replicated.
  Consumed by ParallelWrapper / sharding.shard_batch.
- ``tp``: tensor parallel — weight matrices sharded, activations gathered
  by XLA-inserted collectives. Consumed by sharding.param_shardings.
- ``sp``: sequence/context parallel — time dim sharded; consumed by
  parallel.ring_attention.ring_self_attention (blockwise ring attention
  with K/V ppermute rotation over ICI).
- ``pp``, ``ep``: reserved axis *names* (pipeline / expert parallel) so
  future components agree on naming; no component consumes them today and
  make_mesh keeps them at size 1 unless explicitly set.

The reference's ParallelWrapper pins one model replica per device thread
(ParallelWrapper.java:122,189); here a mesh axis of size N is the
declarative equivalent, and XLA lays collectives onto ICI links.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence

import jax
import numpy as np

AXES = ("dp", "pp", "sp", "ep", "tp")


@dataclass
class MeshSpec:
    """Declarative mesh shape. Unspecified axes default to 1.

    tp is the minor (fastest-varying) axis so tensor-parallel collectives
    ride the shortest ICI hops; dp is major.
    """

    dp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1
    tp: int = 1

    def total(self) -> int:
        return self.dp * self.pp * self.sp * self.ep * self.tp

    def axis_sizes(self) -> Dict[str, int]:
        return {a: getattr(self, a) for a in AXES}


def make_mesh(spec: Optional[MeshSpec] = None,
              devices: Optional[Sequence] = None,
              **axis_sizes) -> jax.sharding.Mesh:
    """Build a Mesh over the given (default: all) devices.

    make_mesh(dp=4, tp=2) → Mesh with axes ("dp","pp","sp","ep","tp") of
    sizes (4,1,1,1,2). An axis set to -1 absorbs all remaining devices.
    """
    if spec is None:
        spec = MeshSpec(**{a: axis_sizes.get(a, 1) for a in AXES})
    devices = list(jax.devices()) if devices is None else list(devices)
    sizes = spec.axis_sizes()
    wild = [a for a, s in sizes.items() if s == -1]
    if len(wild) > 1:
        raise ValueError("at most one mesh axis may be -1")
    if wild:
        fixed = int(np.prod([s for s in sizes.values() if s != -1]))
        if len(devices) % fixed:
            raise ValueError(
                f"{len(devices)} devices not divisible by fixed axes {fixed}")
        sizes[wild[0]] = len(devices) // fixed
    total = int(np.prod(list(sizes.values())))
    if total > len(devices):
        raise ValueError(
            f"mesh needs {total} devices, only {len(devices)} available")
    arr = np.array(devices[:total]).reshape([sizes[a] for a in AXES])
    return jax.sharding.Mesh(arr, AXES)
