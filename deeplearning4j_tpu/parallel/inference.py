"""ParallelInference: high-throughput inference serving.

Parity: deeplearning4j-scaleout-parallelwrapper/.../ParallelInference.java
(380 LoC; InferenceMode.java:7-8 SEQUENTIAL/BATCHED, dynamic batching via
observable queue in observers/BatchedInferenceObservable.java).

TPU-native design: the reference round-robins requests over per-device
model replicas. On TPU one compiled program already uses every chip in
the mesh, so SEQUENTIAL degenerates to direct calls; the valuable part is
BATCHED mode — coalescing concurrent small requests into one padded
batch so the MXU runs full tiles. Batch sizes are bucketed to powers of
two to bound XLA recompilation.
"""

from __future__ import annotations

import queue
import threading
from typing import List, Optional

import jax.numpy as jnp
import numpy as np


class InferenceMode:
    SEQUENTIAL = "sequential"
    BATCHED = "batched"


class _Pending:
    __slots__ = ("x", "event", "result")

    def __init__(self, x):
        self.x = x
        self.event = threading.Event()
        self.result = None


class ParallelInference:
    """Thread-safe inference front-end over a trained network.

    Builder parity: workers ~ mesh size (implicit), batch_limit, queue_limit.
    """

    def __init__(self, net, inference_mode: str = InferenceMode.BATCHED,
                 batch_limit: int = 32, queue_limit: int = 64,
                 max_wait_ms: float = 2.0):
        self.net = net
        self.mode = inference_mode
        self.batch_limit = batch_limit
        self.max_wait_ms = max_wait_ms
        self._queue: "queue.Queue[_Pending]" = queue.Queue(maxsize=queue_limit)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._worker: Optional[threading.Thread] = None
        if self.mode == InferenceMode.BATCHED:
            self._worker = threading.Thread(
                target=self._batch_loop, daemon=True,
                name="ParallelInference-batcher")
            self._worker.start()

    # ------------------------------------------------------------------
    def output(self, x) -> np.ndarray:
        x = np.asarray(x)
        if self.mode == InferenceMode.SEQUENTIAL:
            with self._lock:
                return np.asarray(self.net.output(x))
        p = _Pending(x)
        self._queue.put(p)
        p.event.wait()
        if isinstance(p.result, Exception):
            raise p.result
        return p.result

    def shutdown(self):
        self._stop.set()
        if self._worker is not None:
            self._worker.join(timeout=1.0)

    # ------------------------------------------------------------------
    @staticmethod
    def _bucket(n: int) -> int:
        b = 1
        while b < n:
            b <<= 1
        return b

    def _batch_loop(self):
        while not self._stop.is_set():
            try:
                first = self._queue.get(timeout=0.05)
            except queue.Empty:
                continue
            pending: List[_Pending] = [first]
            rows = first.x.shape[0]
            deadline = self.max_wait_ms / 1000.0
            import time
            t0 = time.monotonic()
            while rows < self.batch_limit:
                remaining = deadline - (time.monotonic() - t0)
                if remaining <= 0:
                    break
                try:
                    p = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                pending.append(p)
                rows += p.x.shape[0]
            try:
                big = np.concatenate([p.x for p in pending], axis=0)
                bucket = self._bucket(big.shape[0])
                if bucket > big.shape[0]:
                    pad = np.zeros((bucket - big.shape[0],) + big.shape[1:],
                                   big.dtype)
                    big = np.concatenate([big, pad], axis=0)
                with self._lock:
                    out = np.asarray(self.net.output(jnp.asarray(big)))
                ofs = 0
                for p in pending:
                    n = p.x.shape[0]
                    p.result = out[ofs:ofs + n]
                    ofs += n
                    p.event.set()
            except Exception as e:  # propagate to callers
                for p in pending:
                    p.result = e
                    p.event.set()
