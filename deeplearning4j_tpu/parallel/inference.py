"""ParallelInference: high-throughput inference serving.

Parity: deeplearning4j-scaleout-parallelwrapper/.../ParallelInference.java
(380 LoC; InferenceMode.java:7-8 SEQUENTIAL/BATCHED, dynamic batching via
observable queue in observers/BatchedInferenceObservable.java).

TPU-native design: the reference round-robins requests over per-device
model replicas. On TPU one compiled program already uses every chip in
the mesh, so SEQUENTIAL degenerates to direct calls; the valuable part is
BATCHED mode — coalescing concurrent small requests into one padded
batch so the MXU runs full tiles. Batch sizes are bucketed to powers of
two (hard-capped at next_pow2(batch_limit)) to bound XLA recompilation.

Pipelined data plane (perf): the batcher is a two-stage pipeline.
The ASSEMBLER stage coalesces requests directly into a preallocated
padded bucket buffer (one copy, no intermediate np.concatenate),
dispatches `net.output` and hands the *in-flight device value* to the
COMPLETION stage without blocking on the host fetch — JAX dispatch is
async, so batch N+1 assembles and dispatches while batch N computes.
The completion stage performs the host fetch (the 4-6 ms per-dispatch
RTT measured in PERF.md), slices rows back to their callers, and
returns the staging buffer to the pool. `completion_streams` (default
2) completion threads pay fetch RTTs CONCURRENTLY — with one stream a
slow fetch serializes the window even though the device is free.
Completions may land out of dispatch order; per-row-range delivery
makes that harmless. The in-flight window is bounded
(`pipeline_depth`), so backpressure still cascades: window full ->
assembler stalls -> request queue fills -> `output()` sheds load.
`pipeline_depth=0` degrades to the serialized dispatch-then-fetch loop
(the bench_serving.py comparison baseline).

Multi-input coalescing: a request may carry one array per network
input (`output(x_a, x_b)` — ComputationGraph-style named inputs), all
sharing the batch dim. Each input stream coalesces into its own pooled
bucket buffer and the batch dispatches as `net.output(*bufs)`;
multi-output models deliver a list of arrays per caller.

Compile-once guards: `warmup=True` pre-traces `net.output` for every
power-of-two bucket up to the cap at construction (shape derived from
the net's configured InputType), and `stats()` surfaces the net's
JitCache trace counters so "zero new traces under mixed-size load" is
an asserted regression property. `adaptive_wait` shrinks the batching
wait when the queue is deep (a full batch is already waiting — waiting
adds latency, not throughput) and grows it back while idle.

Graceful degradation (resilience subsystem): the request queue is
bounded and `output()` sheds load with OverloadedError instead of
blocking when it fills; every wait carries a deadline so a dead
pipeline thread surfaces as InferenceUnavailableError rather than a
hang; `shutdown()` fails fast — queued, in-flight, and carried requests
are signaled with ShutdownError, and the front-end reports itself
unhealthy via `healthy` (the /healthz source of truth in serving.py).
Death of EITHER pipeline stage (fault points `inference.batch` and
`inference.complete`) drains every waiter.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.observability import metrics as _obs
from deeplearning4j_tpu.observability.metrics import COUNT_BUCKETS
from deeplearning4j_tpu.resilience.errors import (
    DeadlineExceededError,
    InferenceUnavailableError,
    OverloadedError,
    ShutdownError,
)
from deeplearning4j_tpu.resilience.faults import fire as _fire

logger = logging.getLogger("deeplearning4j_tpu")

# warn once per process when warmup is silently impossible (underivable
# input shape) — tests may reset this to re-observe the warning
_WARMUP_SKIP_WARNED = False


class InferenceMode:
    SEQUENTIAL = "sequential"
    BATCHED = "batched"


# priority classes (mirrors serving/admission.py PRIORITY_CLASSES —
# kept literal here so the data plane never imports the control plane)
_PRIORITY_IDX = {"high": 0, "normal": 1, "low": 2}


class _RequestQueue(queue.Queue):
    """Bounded request queue with priority-class ordering: admitted
    requests dequeue high-before-normal-before-low, FIFO within one
    class — under a deep queue an admitted high-priority request no
    longer waits behind a wall of admitted normals.

    Built on queue.Queue's documented `_init/_qsize/_put/_get`
    extension points (the same mechanism queue.PriorityQueue uses), so
    the mutex and condition variables stay the stdlib-created C locks —
    load-bearing: daemon pipeline threads wait on them through
    interpreter finalization, where a pure-Python acquire frame is
    fatal (see analysis/sanitizers.py DEFAULT_SCOPE)."""

    def _init(self, maxsize: int) -> None:
        self._by_class = tuple(deque() for _ in range(3))

    def _qsize(self) -> int:
        return sum(len(d) for d in self._by_class)

    def _put(self, item) -> None:
        self._by_class[getattr(item, "priority_idx", 1)].append(item)

    def _get(self):
        for d in self._by_class:
            if d:
                return d.popleft()
        raise queue.Empty   # unreachable: guarded by queue.Queue's CV


class _Pending:
    """One caller's request — one or more equal-row input arrays (a
    multi-input ComputationGraph request is a tuple of named-input
    streams sharing one batch dim). Large requests may be split across
    several dispatched batches (bucket-cap overshoot guard); `deliver`
    collects row ranges per output stream and resolves once every row
    has arrived. Deliveries for one request never race (each row range
    lives in exactly one batch and batches touch disjoint ranges), so
    no lock of its own is needed."""

    __slots__ = ("xs", "event", "result", "_left", "_out", "span",
                 "priority_idx")

    def __init__(self, xs, priority_idx: int = 1):
        self.xs = xs               # tuple of per-input arrays
        self.event = threading.Event()
        self.result = None
        self._left = xs[0].shape[0]
        self._out = None           # list of per-output buffers (splits)
        self.span = None   # open request span (tracer attached only)
        self.priority_idx = priority_idx   # dequeue class (0 first)

    @property
    def rows(self) -> int:
        return self.xs[0].shape[0]

    def resolve(self, result):
        if not self.event.is_set():
            self.result = result
            self.event.set()
            if self.span is not None:
                try:
                    self.span.end(
                        error=type(result).__name__
                        if isinstance(result, Exception) else None)
                except Exception:   # noqa: BLE001 - telemetry best-effort
                    pass

    def deliver(self, start: int, rows_list: List[np.ndarray],
                multi: bool) -> bool:
        """Hand this request `rows_list` (one array per model OUTPUT)
        covering its rows [start, start+n). Returns True when the
        delivery completed the request. `multi` keeps the resolved
        shape honest: single-output models resolve to a bare array."""
        if self.event.is_set():
            return False
        n = self.xs[0].shape[0]
        got = rows_list[0].shape[0]
        if self._out is None and start == 0 and got == n:
            # whole request in one batch (the common case)
            self.resolve(list(rows_list) if multi else rows_list[0])
            return True
        if self._out is None:
            self._out = [np.empty((n,) + r.shape[1:], r.dtype)
                         for r in rows_list]
        for out, r in zip(self._out, rows_list):
            out[start:start + got] = r
        self._left -= got
        if self._left <= 0:
            self.resolve(self._out if multi else self._out[0])
            return True
        return False


# slot = (pending, src_row_start, n_rows): one contiguous row range of a
# request placed in the batch currently being assembled
_Slot = Tuple[_Pending, int, int]


class ParallelInference:
    """Thread-safe inference front-end over a trained network.

    Builder parity: workers ~ mesh size (implicit), batch_limit,
    queue_limit. `default_timeout_s` bounds every `output()` call
    (per-call override via the `timeout_s` kwarg)."""

    def __init__(self, net, inference_mode: str = InferenceMode.BATCHED,
                 batch_limit: int = 32, queue_limit: int = 64,
                 max_wait_ms: float = 2.0,
                 default_timeout_s: float = 30.0,
                 pipeline_depth: int = 2,
                 warmup: bool = True,
                 adaptive_wait: bool = True,
                 min_wait_ms: float = 0.0,
                 warmup_inputs=None,
                 completion_streams: int = 2,
                 tracer=None):
        """`warmup_inputs`: per-example input shapes for nets whose
        shape is underivable from the conf (stub nets, graphs without
        input types) — a sequence with one entry per network input,
        each either a shape tuple (no batch dim) or an example array
        whose leading dim is the batch. Multi-input ComputationGraphs
        with configured input types derive their shapes automatically;
        without either, warmup is skipped (warned once per process).

        `completion_streams`: how many completion-stage threads pay
        host-fetch RTTs concurrently (default 2 — one fetch at a time
        was the recorded PR 2 gap). Only meaningful with
        pipeline_depth > 0; completions may finish out of dispatch
        order, which per-row delivery makes harmless.

        `tracer` (observability.Tracer, optional): records per-request
        spans (enqueue→…→deliver) and per-batch spans on BOTH pipeline
        stages, explicitly parented across the assembler / completion
        threads. None (default) costs the hot path nothing."""
        self.net = net
        self.tracer = tracer
        self.warmup_inputs = warmup_inputs
        self.mode = inference_mode
        self.batch_limit = batch_limit
        self.max_wait_ms = max_wait_ms
        self.min_wait_ms = min_wait_ms
        self.adaptive_wait = adaptive_wait
        self.default_timeout_s = default_timeout_s
        self.pipeline_depth = max(0, int(pipeline_depth))
        self.completion_streams = max(1, int(completion_streams))
        self._cap = self._bucket(batch_limit)   # hard bucket-shape ceiling
        self._queue: "queue.Queue[_Pending]" = _RequestQueue(
            maxsize=queue_limit)
        self._lock = threading.Lock()
        self._count_lock = threading.Lock()   # _inflight_n (k completers)
        self._stop = threading.Event()
        self._shutdown = False
        self._failure: Optional[BaseException] = None
        self._worker: Optional[threading.Thread] = None
        self._completer: Optional[threading.Thread] = None
        self._completers: List[threading.Thread] = []
        self._inflight: Optional["queue.Queue"] = None
        # dispatched-but-not-completed batches, INCLUDING the one the
        # completion stage is currently fetching (queue size alone
        # undercounts it, which would let the assembler over-dispatch
        # undersized batches while the device is already saturated).
        # _slot_free wakes the assembler the moment a batch completes,
        # so the device never idles on a polling interval.
        self._inflight_n = 0
        self._slot_free = threading.Event()
        self._carry: Optional[Tuple[_Pending, int]] = None
        self._buf_pool: Dict[tuple, List[np.ndarray]] = {}
        self._wait_ms = float(max_wait_ms)
        self._warmed_buckets: List[int] = []
        self._batches_dispatched = 0
        self._requests_completed = 0
        # bucket -> [dispatches, real rows]: the pow2 fill accounting
        # the program lint's prog-excess-padding rule reads
        self._bucket_fill: Dict[int, List[int]] = {}
        if self.mode == InferenceMode.BATCHED:
            if warmup:
                self.warmup()
            if self.pipeline_depth > 0:
                self._inflight = queue.Queue()
                for i in range(self.completion_streams):
                    t = threading.Thread(
                        target=self._completion_loop, daemon=True,
                        name=f"ParallelInference-completer-{i}")
                    t.start()
                    self._completers.append(t)
                self._completer = self._completers[0]
            self._worker = threading.Thread(
                target=self._batch_loop, daemon=True,
                name="ParallelInference-batcher")
            self._worker.start()

    # ------------------------------------------------------------------
    @property
    def healthy(self) -> bool:
        """False once shut down or either pipeline thread has died."""
        if self._shutdown or self._failure is not None:
            return False
        if self.mode == InferenceMode.BATCHED:
            if self._worker is None or not self._worker.is_alive():
                return False
            if any(not t.is_alive() for t in self._completers):
                return False
        return True

    def queue_depth(self) -> int:
        return self._queue.qsize()

    @property
    def queue_limit(self) -> int:
        return self._queue.maxsize

    def trace_stats(self) -> dict:
        """The net's JitCache trace counters (empty for nets without
        one) — the recompile-regression observable — plus the compile-
        event forensics ring (signature, duration, cost digest per new
        trace) so /status can answer "what recompiled, and why"."""
        cache = getattr(self.net, "_jit_cache", None)
        if cache is None or not hasattr(cache, "trace_counts"):
            return {}
        out = {"trace_counts": cache.trace_counts(),
               "total_traces": cache.total_traces()}
        if hasattr(cache, "compile_events"):
            out["compiles_total"] = cache.compiles_total()
            out["compile_events"] = cache.compile_events()
        return out

    def bucket_fill(self) -> Dict[int, dict]:
        """Per-bucket padding accounting: {bucket: {dispatches, rows,
        fill}} where fill = real rows / (dispatches * bucket). The pow2
        coalescer guarantees fill > 0.5 per dispatch; the program
        lint's prog-excess-padding rule pins that invariant."""
        return {b: {"dispatches": d, "rows": r,
                    "fill": (r / (d * b)) if d else 0.0}
                for b, (d, r) in sorted(self._bucket_fill.items())}

    def lint_records(self) -> list:
        """ProgramRecords for the serving data plane: the net's cached
        predict program at the largest warmed bucket signature (with
        its registered precision policy) plus one fill-ratio record per
        dispatched bucket — the `--programs` registry entries for this
        front-end (analysis/program_lint)."""
        from deeplearning4j_tpu.analysis.program_lint import (
            ProgramRecord,
        )

        source = "deeplearning4j_tpu/parallel/inference.py"
        records = []
        cache = getattr(self.net, "_jit_cache", None)
        fn = cache.get("predict") if cache is not None else None
        shapes = self._warmup_shapes()
        if fn is not None and shapes:
            b = max(self._warmed_buckets or [self._cap])
            xs = [np.zeros((b,) + s, np.float32) for s in shapes]
            names = getattr(self.net.conf, "network_inputs", None)
            if names:   # ComputationGraph predict takes {name: x}
                args = (self.net.params, self.net.states,
                        dict(zip(names, xs)))
            else:
                args = (self.net.params, self.net.states, xs[0])
            records.append(ProgramRecord(
                name="serving_predict", fn=getattr(fn, "__wrapped__", fn),
                example_args=args,
                precision_policy=(cache.policy("predict")
                                  if hasattr(cache, "policy") else None),
                source=source))
        for b, agg in self.bucket_fill().items():
            records.append(ProgramRecord(
                name=f"serving_bucket_{b}", source=source,
                bucket_capacity=b,
                bucket_rows_per_dispatch=(
                    agg["rows"] / agg["dispatches"]
                    if agg["dispatches"] else 0.0)))
        return records

    def stats(self) -> dict:
        """Pipeline + compile-guard facts (surfaced on /status)."""
        out = {
            "pipeline_depth": self.pipeline_depth,
            "completion_streams": (self.completion_streams
                                   if self.pipeline_depth > 0 else 0),
            "in_flight": self._inflight_n,
            "queue_depth": self._queue.qsize(),
            "batches_dispatched": self._batches_dispatched,
            "requests_completed": self._requests_completed,
            "bucket_cap": self._cap,
            "warmed_buckets": list(self._warmed_buckets),
            "bucket_fill": self.bucket_fill(),
            "current_wait_ms": round(self._wait_ms, 4),
            "adaptive_wait": self.adaptive_wait,
        }
        out.update(self.trace_stats())
        return out

    # ------------------------------------------------------------ warmup
    def _warmup_tail_shape(self) -> Optional[tuple]:
        """Per-example input shape from the net's configured InputType
        (None when underivable, e.g. stub nets / multi-input graphs)."""
        conf = getattr(self.net, "conf", None)
        input_type = getattr(conf, "input_type", None)
        if input_type is None:
            return None
        try:
            return tuple(input_type.batch_shape(1))[1:]
        except Exception:   # noqa: BLE001 - underivable shape: skip
            return None

    def _warmup_shapes(self) -> Optional[List[tuple]]:
        """Per-example shape for every network input: explicit
        `warmup_inputs` first, then multi-input ComputationGraph input
        types, then the single-input conf InputType; None when
        underivable every way."""
        if self.warmup_inputs is not None:
            shapes = []
            for w in self.warmup_inputs:
                if isinstance(w, (tuple, list)) and all(
                        isinstance(d, (int, np.integer)) for d in w):
                    shapes.append(tuple(int(d) for d in w))
                else:
                    shapes.append(tuple(np.asarray(w).shape[1:]))
            return shapes
        conf = getattr(self.net, "conf", None)
        names = getattr(conf, "network_inputs", None)
        itypes = getattr(conf, "input_types", None)
        if names and itypes and set(itypes) >= set(names):
            try:
                return [tuple(itypes[n].batch_shape(1))[1:]
                        for n in names]
            except Exception:   # noqa: BLE001 - underivable shape: skip
                pass
        tail = self._warmup_tail_shape()
        return None if tail is None else [tail]

    def warmup(self) -> List[int]:
        """Pre-trace `net.output` for every power-of-two bucket up to
        the cap, so a mixed-size request load causes ZERO new traces
        (each one a full XLA recompile on TPU). Returns the buckets
        traced; skipped (with a once-per-process warning) when the
        input shape is underivable and no `warmup_inputs` were given."""
        shapes = self._warmup_shapes()
        if shapes is None:
            global _WARMUP_SKIP_WARNED
            if not _WARMUP_SKIP_WARNED:
                _WARMUP_SKIP_WARNED = True
                logger.warning(
                    "ParallelInference: warmup skipped — per-example "
                    "input shape underivable (multi-input graph or "
                    "stub net); pass warmup_inputs=[shape, ...] to "
                    "pre-trace buckets and avoid first-request "
                    "recompiles")
            return []
        done = []
        b = 1
        while b <= self._cap:
            xs = [np.zeros((b,) + s, np.float32) for s in shapes]
            with self._lock:
                out = (self.net.output(*xs) if len(xs) > 1
                       else self.net.output(xs[0]))
                for o in (out if isinstance(out, (list, tuple))
                          else [out]):
                    np.asarray(o)            # block: compile now
            done.append(b)
            b <<= 1
        self._warmed_buckets = done
        return done

    # ------------------------------------------------------------------
    def _check_available(self):
        if self._shutdown:
            raise ShutdownError("ParallelInference is shut down")
        if self._failure is not None:
            raise InferenceUnavailableError(
                f"batcher thread died: {self._failure!r}")
        if self.mode == InferenceMode.BATCHED and self._threads_dead():
            raise InferenceUnavailableError("batcher thread is not running")

    def _threads_dead(self) -> bool:
        if self._worker is None or not self._worker.is_alive():
            return True
        return (self._completer is not None
                and not self._completer.is_alive())

    def output(self, *xs, timeout_s: Optional[float] = None,
               priority: Optional[str] = None):
        """Run inference; raises OverloadedError when the bounded queue
        is full (shed load, don't queue unbounded latency) and
        DeadlineExceededError / InferenceUnavailableError instead of
        hanging when the pipeline stalls or dies.

        `priority` ("high"/"normal"/"low", default normal — the
        admission layer passes the tenant's class): admitted requests
        DEQUEUE high-before-normal-before-low under a deep queue, FIFO
        within a class; admission sheds by class before the queue,
        this orders within it.

        Multi-input graphs pass one array per network input
        (`pi.output(x_a, x_b)`), all sharing the batch dim — the
        streams coalesce through the same pooled-bucket path, one
        bucket buffer per input. Multi-output models resolve to a list
        of arrays (single-output stays a bare array)."""
        xs = tuple(np.asarray(x) for x in xs)
        if not xs:
            raise ValueError("output() needs at least one input array")
        if any(x.shape[0] != xs[0].shape[0] for x in xs[1:]):
            raise ValueError(
                "all inputs must share the batch dim: "
                f"{[x.shape[0] for x in xs]}")
        if timeout_s is None:
            timeout_s = self.default_timeout_s
        if self.mode == InferenceMode.SEQUENTIAL:
            self._check_available()
            with self._lock:
                out = self.net.output(*xs)
                return ([np.asarray(o) for o in out]
                        if isinstance(out, (list, tuple))
                        else np.asarray(out))
        self._check_available()
        p = _Pending(xs, priority_idx=_PRIORITY_IDX.get(priority, 1))
        if self.tracer is not None:
            try:
                p.span = self.tracer.begin(
                    "request", cat="serving",
                    args={"rows": int(xs[0].shape[0])})
            except Exception:   # noqa: BLE001 - telemetry best-effort
                p.span = None
        try:
            self._queue.put_nowait(p)
        except queue.Full:
            if p.span is not None:
                p.span.end(error="OverloadedError")
            raise OverloadedError(
                f"inference queue full ({self._queue.maxsize} waiting); "
                "retry later") from None
        deadline = time.monotonic() + timeout_s
        # poll in slices: a pipeline thread that dies *after* the put but
        # before its own drain would otherwise strand this waiter
        while not p.event.wait(timeout=min(
                0.05, max(0.0, deadline - time.monotonic()))):
            if p.event.is_set():
                break
            if (self._failure is not None or self._shutdown
                    or self._threads_dead()):
                self._drain(self._unavailable_error())
                if not p.event.is_set():
                    p.resolve(self._unavailable_error())
            elif time.monotonic() >= deadline:
                raise DeadlineExceededError(
                    f"inference did not complete within {timeout_s}s")
        if isinstance(p.result, Exception):
            raise p.result
        return p.result

    def _unavailable_error(self) -> Exception:
        if self._shutdown and self._failure is None:
            return ShutdownError(
                "ParallelInference shut down with requests in flight")
        return InferenceUnavailableError(
            f"batcher thread died: {self._failure!r}")

    def shutdown(self):
        """Fail fast: stop both pipeline stages, then signal every
        queued / in-flight request with ShutdownError so no caller is
        left hanging."""
        self._shutdown = True
        self._stop.set()
        if self._worker is not None:
            self._worker.join(timeout=2.0)
        for t in self._completers:
            t.join(timeout=2.0)
        err = ShutdownError(
            "ParallelInference shut down with requests in flight")
        self._drain(err)
        self._drain_inflight(err)

    def _drain(self, error: Exception):
        """Signal everything still queued (and any carried split
        request) with `error`."""
        carry = self._carry
        self._carry = None
        if carry is not None and not carry[0].event.is_set():
            carry[0].resolve(error)
        while True:
            try:
                p = self._queue.get_nowait()
            except queue.Empty:
                return
            if not p.event.is_set():
                p.resolve(error)

    def _drain_inflight(self, error: Exception):
        if self._inflight is None:
            return
        while True:
            try:
                _, slots, keys, bufs, _ = self._inflight.get_nowait()
            except queue.Empty:
                return
            with self._count_lock:
                self._inflight_n -= 1
            for p, _, _ in slots:
                p.resolve(error)
            self._put_buffers(keys, bufs)

    # ------------------------------------------------------------------
    @staticmethod
    def _bucket(n: int) -> int:
        b = 1
        while b < n:
            b <<= 1
        return b

    # ------------------------------------------------------- bucket pool
    def _get_buffer(self, key: tuple) -> np.ndarray:
        pool = self._buf_pool.get(key)
        if pool:
            return pool.pop()
        bucket, tail, dtype_str = key
        return np.zeros((bucket,) + tail, np.dtype(dtype_str))

    def _put_buffer(self, key: tuple, buf: np.ndarray):
        # bounded: at most window+1 buffers alive per bucket shape
        pool = self._buf_pool.setdefault(key, [])
        if len(pool) <= self.pipeline_depth:
            pool.append(buf)

    def _put_buffers(self, keys: List[tuple], bufs: List[np.ndarray]):
        for key, buf in zip(keys, bufs):
            self._put_buffer(key, buf)

    # --------------------------------------------------- adaptive wait
    def _current_wait_s(self) -> float:
        if not self.adaptive_wait:
            return self.max_wait_ms / 1000.0
        if self._queue.qsize() >= self.batch_limit:
            return 0.0   # a full batch is already waiting
        return self._wait_ms / 1000.0

    def _adapt_wait(self, rows: int):
        if not self.adaptive_wait:
            return
        if rows >= self.batch_limit:
            # deep queue: batches fill instantly — waiting only adds
            # latency, so shrink toward min_wait_ms
            self._wait_ms = max(self.min_wait_ms, self._wait_ms * 0.5)
        elif self._queue.qsize() == 0:
            # idle: grow back toward max_wait_ms so sparse traffic still
            # coalesces into full tiles
            self._wait_ms = min(self.max_wait_ms,
                                self._wait_ms * 1.5 + 0.05)

    # ------------------------------------------------------- assembler
    def _collect(self) -> Tuple[List[_Slot], int]:
        """Gather up to batch_limit rows: the carried remainder of a
        split request first, then queued requests. A request that would
        push past batch_limit is split — its overflow rows carry into
        the NEXT batch, so no bucket ever exceeds the cap."""
        slots: List[_Slot] = []
        rows = 0
        limit = self.batch_limit
        if self._carry is not None:
            p, src = self._carry
            self._carry = None
            take = min(p.rows - src, limit)
            slots.append((p, src, take))
            rows += take
            if src + take < p.rows:
                self._carry = (p, src + take)
                return slots, rows
        else:
            try:
                first = self._queue.get(timeout=0.05)
            except queue.Empty:
                return slots, 0
            take = min(first.rows, limit)
            slots.append((first, 0, take))
            rows += take
            if take < first.rows:
                self._carry = (first, take)
                _obs.count("dl4j_serving_bucket_splits_total")
                return slots, rows
        wait_s = self._current_wait_s()
        t0 = time.monotonic()
        while rows < limit:
            # while the in-flight window is full the device is the
            # bottleneck — dispatching a partial batch now would only
            # shrink coalescing, so keep collecting until a slot frees
            window_full = (self._inflight is not None
                           and self._inflight_n >= self.pipeline_depth)
            if window_full:
                try:
                    p = self._queue.get_nowait()
                except queue.Empty:
                    if self._stop.is_set() or self._failure is not None:
                        break
                    self._slot_free.clear()
                    if self._inflight_n >= self.pipeline_depth:
                        self._slot_free.wait(timeout=0.05)
                    continue
            else:
                remaining = wait_s - (time.monotonic() - t0)
                if remaining <= 0 and self._queue.empty():
                    break
                try:
                    p = self._queue.get(timeout=max(0.0, remaining))
                except queue.Empty:
                    break
            take = min(p.rows, limit - rows)
            slots.append((p, 0, take))
            rows += take
            if take < p.rows:
                self._carry = (p, take)
                _obs.count("dl4j_serving_bucket_splits_total")
                break
        return slots, rows

    def _assemble(self, slots: List[_Slot], rows: int):
        """Coalesce request rows directly into pooled padded bucket
        buffers — ONE copy per input stream, no intermediate
        concatenate allocations. Multi-input requests fill one buffer
        per network input; every request in the batch must carry the
        same input arity."""
        n_inputs = len(slots[0][0].xs)
        if any(len(p.xs) != n_inputs for p, _, _ in slots):
            raise ValueError(
                "mixed input arity in one batch: all requests to a "
                f"model must carry {n_inputs} input(s)")
        bucket = self._bucket(rows)
        keys: List[tuple] = []
        bufs: List[np.ndarray] = []
        for i in range(n_inputs):
            x0 = slots[0][0].xs[i]
            tail = x0.shape[1:]
            dtype = np.result_type(*[p.xs[i].dtype
                                     for p, _, _ in slots]) \
                if len(slots) > 1 else x0.dtype
            key = (bucket, tail, np.dtype(dtype).str)
            buf = self._get_buffer(key)
            ofs = 0
            for p, src, n in slots:
                buf[ofs:ofs + n] = p.xs[i][src:src + n]
                ofs += n
            if bucket > rows:
                buf[rows:bucket] = 0   # pooled buffers carry stale rows
            keys.append(key)
            bufs.append(buf)
        return keys, bufs

    def _batch_loop(self):
        try:
            while not self._stop.is_set() and self._failure is None:
                # chaos hook: a 'raise' here kills the assembler thread —
                # the graceful-degradation drill for the serving path
                _fire("inference.batch")
                slots, rows = self._collect()
                if not slots:
                    continue
                # assembler-stage span: explicitly parented to the
                # FIRST request's span — the request started on a
                # caller thread, this stage runs on the batcher thread
                dspan = None
                if self.tracer is not None:
                    try:
                        dspan = self.tracer.begin(
                            "assemble_dispatch", cat="serving",
                            parent=slots[0][0].span,
                            args={"rows": rows, "slots": len(slots)})
                    except Exception:   # noqa: BLE001 - telemetry
                        dspan = None
                try:
                    keys, bufs = self._assemble(slots, rows)
                except Exception as e:   # per-batch: propagate to callers
                    for p, _, _ in slots:
                        p.resolve(e)
                    if dspan is not None:
                        dspan.end(error=type(e).__name__)
                    continue
                try:
                    with self._lock:
                        # async dispatch: hand the in-flight device value
                        # to the completion stage; do NOT block on the
                        # host fetch here
                        out = self.net.output(
                            *[jnp.asarray(b) for b in bufs])
                except Exception as e:   # per-batch: propagate to callers
                    for p, _, _ in slots:
                        p.resolve(e)
                    self._put_buffers(keys, bufs)
                    if dspan is not None:
                        dspan.end(error=type(e).__name__)
                    continue
                self._batches_dispatched += 1
                agg = self._bucket_fill.setdefault(keys[0][0], [0, 0])
                agg[0] += 1
                agg[1] += rows
                _obs.count_observe(
                    "dl4j_serving_batches_total",
                    "dl4j_serving_batch_occupancy", rows,
                    buckets=COUNT_BUCKETS)
                _obs.set_gauge("dl4j_serving_queue_depth",
                               self._queue.qsize())
                if dspan is not None:
                    dspan.end()
                self._adapt_wait(rows)
                if self._completer is None:
                    self._complete_batch(out, slots, keys, bufs, dspan)
                else:
                    self._submit_inflight((out, slots, keys, bufs, dspan))
        except BaseException as e:   # noqa: BLE001 - loop-level death
            # assembler death is a degradation event, not a hang: record
            # it (flips `healthy` and /healthz), then fail every waiter
            self._failure = e
        finally:
            if self._failure is not None:
                self._drain(self._unavailable_error())
            elif self._stop.is_set():
                self._drain(ShutdownError(
                    "ParallelInference shut down with requests in flight"))

    def _submit_inflight(self, item):
        """Bounded in-flight window: block until the completion stage
        frees a slot (backpressure), never past stop/death."""
        while True:
            if self._stop.is_set() or self._failure is not None or any(
                    not t.is_alive() for t in self._completers):
                _, slots, keys, bufs, _ = item
                err = self._unavailable_error() \
                    if not self._stop.is_set() else ShutdownError(
                        "ParallelInference shut down with requests "
                        "in flight")
                for p, _, _ in slots:
                    p.resolve(err)
                self._put_buffers(keys, bufs)
                return
            if self._inflight_n >= self.pipeline_depth:
                self._slot_free.clear()
                if self._inflight_n >= self.pipeline_depth:
                    self._slot_free.wait(timeout=0.05)
                continue
            with self._count_lock:
                self._inflight_n += 1
            _obs.set_gauge("dl4j_serving_inflight_batches",
                           self._inflight_n)
            self._inflight.put(item)
            return

    # ------------------------------------------------------- completion
    def _complete_batch(self, out, slots: List[_Slot], keys, bufs,
                        dspan=None):
        # completion-stage span: parented to the assembler's dispatch
        # span — a cross-THREAD edge when the completer is running
        cspan = None
        if self.tracer is not None and dspan is not None:
            try:
                cspan = self.tracer.begin(
                    "complete_deliver", cat="serving", parent=dspan,
                    args={"slots": len(slots)})
            except Exception:   # noqa: BLE001 - telemetry best-effort
                cspan = None
        multi = isinstance(out, (list, tuple))
        outs = list(out) if multi else [out]
        hosts: List[np.ndarray] = []
        try:
            for o in outs:
                hosts.append(np.asarray(o))  # host fetch: blocks here
        except Exception as e:   # per-batch: propagate to callers
            for p, _, _ in slots:
                p.resolve(e)
            self._put_buffers(keys, bufs)
            if cspan is not None:
                cspan.end(error=type(e).__name__)
            return
        for i, h in enumerate(hosts):
            if any(np.may_share_memory(h, b) for b in bufs):
                # jnp.asarray can zero-copy-alias the staging buffer on
                # CPU and identity-ish models can echo it back: never
                # hand callers views into a buffer the pool will
                # overwrite
                hosts[i] = h.copy()
        self._put_buffers(keys, bufs)   # compute done: buffers reusable
        ofs = 0
        done = 0
        for p, src, n in slots:
            if p.deliver(src, [h[ofs:ofs + n] for h in hosts], multi):
                done += 1
            ofs += n
        if done:
            with self._count_lock:   # k completers share this counter
                self._requests_completed += done
        if cspan is not None:
            cspan.end()

    def _completion_loop(self):
        try:
            while not self._stop.is_set() and self._failure is None:
                # chaos hook: completion-stage death must degrade as
                # gracefully as assembler death
                _fire("inference.complete")
                try:
                    item = self._inflight.get(timeout=0.05)
                except queue.Empty:
                    continue
                try:
                    self._complete_batch(*item)
                finally:
                    with self._count_lock:
                        self._inflight_n -= 1
                    _obs.set_gauge("dl4j_serving_inflight_batches",
                                   self._inflight_n)
                    self._slot_free.set()
        except BaseException as e:   # noqa: BLE001 - loop-level death
            self._failure = e
        finally:
            if self._failure is not None:
                self._drain_inflight(self._unavailable_error())
                self._drain(self._unavailable_error())
            elif self._stop.is_set():
                self._drain_inflight(ShutdownError(
                    "ParallelInference shut down with requests in flight"))
