"""ParallelInference: high-throughput inference serving.

Parity: deeplearning4j-scaleout-parallelwrapper/.../ParallelInference.java
(380 LoC; InferenceMode.java:7-8 SEQUENTIAL/BATCHED, dynamic batching via
observable queue in observers/BatchedInferenceObservable.java).

TPU-native design: the reference round-robins requests over per-device
model replicas. On TPU one compiled program already uses every chip in
the mesh, so SEQUENTIAL degenerates to direct calls; the valuable part is
BATCHED mode — coalescing concurrent small requests into one padded
batch so the MXU runs full tiles. Batch sizes are bucketed to powers of
two to bound XLA recompilation.

Graceful degradation (resilience subsystem): the request queue is
bounded and `output()` sheds load with OverloadedError instead of
blocking when it fills; every wait carries a deadline so a dead batcher
thread surfaces as InferenceUnavailableError rather than a hang;
`shutdown()` fails fast — queued and pending requests are signaled with
ShutdownError, and the front-end reports itself unhealthy via
`healthy` (the /healthz source of truth in serving.py).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.resilience.errors import (
    DeadlineExceededError,
    InferenceUnavailableError,
    OverloadedError,
    ShutdownError,
)
from deeplearning4j_tpu.resilience.faults import fire as _fire


class InferenceMode:
    SEQUENTIAL = "sequential"
    BATCHED = "batched"


class _Pending:
    __slots__ = ("x", "event", "result")

    def __init__(self, x):
        self.x = x
        self.event = threading.Event()
        self.result = None

    def resolve(self, result):
        self.result = result
        self.event.set()


class ParallelInference:
    """Thread-safe inference front-end over a trained network.

    Builder parity: workers ~ mesh size (implicit), batch_limit,
    queue_limit. `default_timeout_s` bounds every `output()` call
    (per-call override via the `timeout_s` kwarg)."""

    def __init__(self, net, inference_mode: str = InferenceMode.BATCHED,
                 batch_limit: int = 32, queue_limit: int = 64,
                 max_wait_ms: float = 2.0,
                 default_timeout_s: float = 30.0):
        self.net = net
        self.mode = inference_mode
        self.batch_limit = batch_limit
        self.max_wait_ms = max_wait_ms
        self.default_timeout_s = default_timeout_s
        self._queue: "queue.Queue[_Pending]" = queue.Queue(maxsize=queue_limit)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._shutdown = False
        self._failure: Optional[BaseException] = None
        self._worker: Optional[threading.Thread] = None
        if self.mode == InferenceMode.BATCHED:
            self._worker = threading.Thread(
                target=self._batch_loop, daemon=True,
                name="ParallelInference-batcher")
            self._worker.start()

    # ------------------------------------------------------------------
    @property
    def healthy(self) -> bool:
        """False once shut down or the batcher thread has died."""
        if self._shutdown or self._failure is not None:
            return False
        if self.mode == InferenceMode.BATCHED:
            return self._worker is not None and self._worker.is_alive()
        return True

    def queue_depth(self) -> int:
        return self._queue.qsize()

    def _check_available(self):
        if self._shutdown:
            raise ShutdownError("ParallelInference is shut down")
        if self._failure is not None:
            raise InferenceUnavailableError(
                f"batcher thread died: {self._failure!r}")
        if (self.mode == InferenceMode.BATCHED
                and (self._worker is None or not self._worker.is_alive())):
            raise InferenceUnavailableError("batcher thread is not running")

    def output(self, x, timeout_s: Optional[float] = None) -> np.ndarray:
        """Run inference; raises OverloadedError when the bounded queue
        is full (shed load, don't queue unbounded latency) and
        DeadlineExceededError / InferenceUnavailableError instead of
        hanging when the batcher stalls or dies."""
        x = np.asarray(x)
        if timeout_s is None:
            timeout_s = self.default_timeout_s
        if self.mode == InferenceMode.SEQUENTIAL:
            self._check_available()
            with self._lock:
                return np.asarray(self.net.output(x))
        self._check_available()
        p = _Pending(x)
        try:
            self._queue.put_nowait(p)
        except queue.Full:
            raise OverloadedError(
                f"inference queue full ({self._queue.maxsize} waiting); "
                "retry later") from None
        deadline = time.monotonic() + timeout_s
        # poll in slices: a batcher that dies *after* the put but before
        # its own drain would otherwise strand this waiter
        while not p.event.wait(timeout=min(
                0.05, max(0.0, deadline - time.monotonic()))):
            if p.event.is_set():
                break
            if self._failure is not None or self._shutdown or (
                    self._worker is not None
                    and not self._worker.is_alive()):
                self._drain(self._unavailable_error())
                if not p.event.is_set():
                    p.resolve(self._unavailable_error())
            elif time.monotonic() >= deadline:
                raise DeadlineExceededError(
                    f"inference did not complete within {timeout_s}s")
        if isinstance(p.result, Exception):
            raise p.result
        return p.result

    def _unavailable_error(self) -> Exception:
        if self._shutdown and self._failure is None:
            return ShutdownError(
                "ParallelInference shut down with requests in flight")
        return InferenceUnavailableError(
            f"batcher thread died: {self._failure!r}")

    def shutdown(self):
        """Fail fast: stop the batcher, then signal every queued request
        with ShutdownError so no caller is left hanging."""
        self._shutdown = True
        self._stop.set()
        if self._worker is not None:
            self._worker.join(timeout=2.0)
        self._drain(ShutdownError(
            "ParallelInference shut down with requests in flight"))

    def _drain(self, error: Exception):
        """Signal everything still queued with `error`."""
        while True:
            try:
                p = self._queue.get_nowait()
            except queue.Empty:
                return
            if not p.event.is_set():
                p.resolve(error)

    # ------------------------------------------------------------------
    @staticmethod
    def _bucket(n: int) -> int:
        b = 1
        while b < n:
            b <<= 1
        return b

    def _batch_loop(self):
        try:
            while not self._stop.is_set():
                # chaos hook: a 'raise' here kills the batcher thread —
                # the graceful-degradation drill for the serving path
                _fire("inference.batch")
                try:
                    first = self._queue.get(timeout=0.05)
                except queue.Empty:
                    continue
                pending: List[_Pending] = [first]
                rows = first.x.shape[0]
                deadline = self.max_wait_ms / 1000.0
                t0 = time.monotonic()
                while rows < self.batch_limit:
                    remaining = deadline - (time.monotonic() - t0)
                    if remaining <= 0:
                        break
                    try:
                        p = self._queue.get(timeout=remaining)
                    except queue.Empty:
                        break
                    pending.append(p)
                    rows += p.x.shape[0]
                try:
                    big = np.concatenate([p.x for p in pending], axis=0)
                    bucket = self._bucket(big.shape[0])
                    if bucket > big.shape[0]:
                        pad = np.zeros(
                            (bucket - big.shape[0],) + big.shape[1:],
                            big.dtype)
                        big = np.concatenate([big, pad], axis=0)
                    with self._lock:
                        out = np.asarray(self.net.output(jnp.asarray(big)))
                    ofs = 0
                    for p in pending:
                        n = p.x.shape[0]
                        p.resolve(out[ofs:ofs + n])
                        ofs += n
                except Exception as e:  # per-batch: propagate to callers
                    for p in pending:
                        p.resolve(e)
        except BaseException as e:   # noqa: BLE001 - loop-level death
            # batcher death is a degradation event, not a hang: record
            # it (flips `healthy` and /healthz), then fail every waiter
            self._failure = e
        finally:
            if self._failure is not None:
                self._drain(self._unavailable_error())
            elif self._stop.is_set():
                self._drain(ShutdownError(
                    "ParallelInference shut down with requests in flight"))
