"""Partitioners for balancing examples across workers/hosts.

Parity: the Spark module's repartitioners —
spark/impl/common/repartition/BalancedPartitioner.java:17-35 (equal
partition sizes with the remainder spread over the first partitions)
and HashingBalancedPartitioner.java (deterministic key-hash assignment
that stays balanced per class). Here they drive `batch_fn`-style host
partitions for TrainingMaster instead of Spark RDD shuffles.
"""

from __future__ import annotations

import zlib
from typing import Sequence

import numpy as np


class BalancedPartitioner:
    """Split n_elements into n_partitions of equal size, the remainder
    going one-each to the first partitions
    (BalancedPartitioner.java:23-35)."""

    def __init__(self, n_partitions: int, n_elements: int):
        if n_partitions < 1:
            raise ValueError(f"n_partitions must be >= 1: {n_partitions}")
        self.n_partitions = n_partitions
        self.n_elements = n_elements
        base = n_elements // n_partitions
        rem = n_elements % n_partitions
        self.sizes = [base + (1 if i < rem else 0)
                      for i in range(n_partitions)]
        self._starts = np.cumsum([0] + self.sizes)

    def partition_of(self, index: int) -> int:
        """Partition id owning element `index` (getPartition role)."""
        if not 0 <= index < self.n_elements:
            raise IndexError(index)
        return int(np.searchsorted(self._starts, index, "right") - 1)

    def bounds(self, partition: int):
        """(start, end) element range of `partition` — the slice a host
        feeds its batch_fn from."""
        return int(self._starts[partition]), \
            int(self._starts[partition + 1])


class HashingBalancedPartitioner:
    """Deterministic key->partition assignment that balances within
    each key class (HashingBalancedPartitioner.java role): the i-th
    element of a class lands on (hash(class) + i) % n, so every
    partition sees ~class-proportional data. STATELESS: the same key
    sequence always produces the same assignment."""

    def __init__(self, n_partitions: int):
        if n_partitions < 1:
            raise ValueError(f"n_partitions must be >= 1: {n_partitions}")
        self.n_partitions = n_partitions

    def partition_of(self, key, occurrence: int = 0) -> int:
        """Partition of the `occurrence`-th element of `key`'s class
        (pure function of its arguments)."""
        cls = key if not isinstance(key, (tuple, list)) else key[0]
        h = zlib.crc32(str(cls).encode())
        return (h + occurrence) % self.n_partitions

    def assign(self, keys: Sequence) -> np.ndarray:
        """Assignment for a key sequence; per class the assignment
        round-robins, so class balance holds per partition.
        Deterministic in the sequence alone."""
        seen: dict = {}
        out = []
        for k in keys:
            cls = k if not isinstance(k, (tuple, list)) else k[0]
            c = seen.get(cls, 0)
            seen[cls] = c + 1
            out.append(self.partition_of(k, c))
        return np.asarray(out)
