"""Parallelism: TPU-native replacement for the reference's scaleout stack.

The reference implements data parallelism four ways (in-process parameter
averaging / shared gradients via ParallelWrapper, Spark BSP parameter
averaging, Spark async gradient sharing over Aeron — ref:
deeplearning4j-scaleout/.../ParallelWrapper.java:54,
spark/impl/paramavg/ParameterAveragingTrainingMaster.java:80,
parameterserver/training/SharedTrainingMaster.java:72). On TPU all four
collapse into one mechanism: a `jax.sharding.Mesh` over the chips and a
single jit-compiled train step whose gradient reduction is an XLA
all-reduce riding the ICI fabric. Tensor/sequence parallelism (absent from
the reference) are first-class here via the same mesh axes.
"""

from deeplearning4j_tpu.parallel.mesh import make_mesh, MeshSpec  # noqa: F401
from deeplearning4j_tpu.parallel.sharding import (  # noqa: F401
    batch_sharding,
    param_shardings,
    replicated,
)
from deeplearning4j_tpu.parallel.wrapper import (  # noqa: F401
    LocalStepTrainer,
    ParallelWrapper,
    StaleGradientTrainer,
)
from deeplearning4j_tpu.parallel.inference import ParallelInference  # noqa: F401
from deeplearning4j_tpu.parallel.serving import (  # noqa: F401
    ModelClient,
    ModelServer,
)
from deeplearning4j_tpu.parallel.dcn_model import (  # noqa: F401
    DcnLink,
    allreduce_ms,
    crossover_report,
    sweep as dcn_sweep,
)
from deeplearning4j_tpu.parallel.repartition import (  # noqa: F401
    BalancedPartitioner,
    HashingBalancedPartitioner,
)
