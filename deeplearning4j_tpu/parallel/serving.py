"""Network-facing model serving over ParallelInference.

Parity: dl4j-streaming's Camel serve route
(streaming/routes/DL4jServeRouteBuilder.java — accept a record over
the wire, run `model.output`, hand the result to a post-processor) and
the ModelServer role around ParallelInference. Kafka/Camel transports
stay out of scope (VERDICT r4); the serving surface itself is plain
HTTP+JSON like the nearest-neighbor microservice
(clustering/server.py), so the round-trip is testable anywhere.

Routes (single-model compatibility surface — routes to the registry's
default model):
  POST /predict  {"inputs": [[...], ...]}          -> {"outputs": [...]}
  POST /predict  {"inputs": ..., "decode_top": 5}  -> adds "decoded"
                 (requires an ImageNetLabels source; zoo/util/imagenet)
  GET  /status   -> model + queue + telemetry facts (uptime_s,
                 monotonic request/error counters from the registry)
  GET  /metrics  -> Prometheus text exposition of the global
                 MetricsRegistry (training, serving, checkpoint, and
                 resilience domains — one scrape covers the process)
  GET  /healthz  -> liveness: 200 while every active model's batcher is
                 alive, 503 after one dies or the server shuts down
  GET  /readyz   -> readiness: 200 only while accepting traffic

Multi-model control plane (serving/ModelRegistry behind the same
server — every model × version has its own warmed ParallelInference):
  POST   /v1/models/<name>/predict      predict on the ACTIVE version;
                 body may carry {"tenant": ...} (or X-Tenant header)
                 for admission, and "inputs" may be a dict of named
                 input streams for multi-input graphs
  POST   /v1/models/<name>/generate     continuous-batched
                 autoregressive generation (serving/continuous.py
                 DecodeEngine attached via `decode_engine=` /
                 `attach_decode_engine`): {"prompt": [ids...],
                 "max_new_tokens": n, "eos_id": id?} -> {"tokens":
                 [...], "finish_reason": "eos"|"length"}. Speaks the
                 npz wire too (prompt as an int array entry; the
                 VARIABLE-LENGTH token output rides back as a raw
                 int32 array). 429 + Retry-After on slot exhaustion
  GET    /v1/models                     catalog: every model, version,
                 lifecycle state, active/previous pointers
  GET    /v1/models/<name>/status       per-model pipeline/trace facts
  PUT    /v1/models/<name>/versions/<v> {"path": zip, "activate": true}
                 load a model zip through the integrity-checked
                 serializer (corrupted uploads are REJECTED, 409) and
                 hot-swap with zero downtime
  POST   /v1/models/<name>/swap         {"version": v} activate a
                 loaded standby version
  POST   /v1/models/<name>/rollback     one-call flip to the previous
                 (still-warm) version
  DELETE /v1/models/<name>/versions/<v> retire a non-active version
  DELETE /v1/models/<name>              remove the model entirely

Failure taxonomy (resilience subsystem) instead of blanket 400:
  404 unknown route / unknown model or version
  400 malformed payload / client error
  429 + Retry-After tenant quota exhausted or priority class shed
  409 lifecycle conflict (delete active, swap to retired) or a
      corrupted upload failing integrity checks
  503 + Retry-After overload, shutdown, or dead batcher
  500 model/handler crash
Every error body is {"error": msg, "error_class": ExceptionName}.

Requests are funneled through each model's ParallelInference in
BATCHED mode, so concurrent small clients coalesce into full MXU tiles
(the reference's BatchedInferenceObservable role); the tenant
AdmissionController (serving/admission.py) sheds the lowest priority
class first before the bounded queue fills.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Optional

import numpy as np

from deeplearning4j_tpu.observability import metrics as _obs
from deeplearning4j_tpu.observability.metrics import (
    get_registry,
    parse_prometheus,
)
from deeplearning4j_tpu.parallel.inference import (
    InferenceMode,
    ParallelInference,
)
from deeplearning4j_tpu.resilience.errors import (
    CheckpointIntegrityError,
    CircuitOpenError,
    DeadlineExceededError,
    InferenceUnavailableError,
    ModelNotFoundError,
    OverloadedError,
    QuotaExceededError,
    RetriesExhaustedError,
    ServingError,
    ShutdownError,
)
from deeplearning4j_tpu.resilience.faults import fire as _fire
from deeplearning4j_tpu.resilience.retry import CircuitBreaker, Retry

# NOTE: the control-plane classes (ModelRegistry, AdmissionController)
# are imported lazily inside ModelServer.__init__ — serving/registry.py
# imports the parallel package, so a module-level import here would be
# circular from either entry point.

# errors that mean "back off and retry": surfaced as 503 + Retry-After
_UNAVAILABLE = (OverloadedError, ShutdownError, InferenceUnavailableError,
                DeadlineExceededError)


class _ClientError(ValueError):
    """Request was malformed — maps to HTTP 400."""


# ---------------------------------------------------- binary wire format
# npz-over-HTTP: input arrays ride as raw .npz bytes (one zip entry per
# input stream, `__meta__` a JSON string entry for the scalar fields)
# instead of JSON-encoded nested lists — no .tolist() host
# materialization on either side and ~4x fewer bytes for float32.
# ModelClient speaks it by default and falls back to JSON once per
# client when the server predates the format.
NPZ_CONTENT_TYPE = "application/x-npz"


def _npz_bytes(arrays: dict, meta: dict) -> bytes:
    import io

    buf = io.BytesIO()
    np.savez(buf, __meta__=np.asarray(json.dumps(meta)), **arrays)
    return buf.getvalue()


def encode_npz_request(inputs, meta: dict) -> bytes:
    """`inputs`: one array, or {name: array} for multi-input graphs."""
    if isinstance(inputs, dict):
        arrays = {f"input:{k}": np.asarray(v) for k, v in inputs.items()}
    else:
        arrays = {"input": np.asarray(inputs)}
    return _npz_bytes(arrays, meta)


def decode_npz_request(raw: bytes) -> dict:
    """Parse an npz request body into the same dict shape the JSON
    route produces (inputs as arrays instead of nested lists)."""
    import io

    try:
        with np.load(io.BytesIO(raw), allow_pickle=False) as z:
            meta = (json.loads(str(z["__meta__"]))
                    if "__meta__" in z.files else {})
            named = {k[len("input:"):]: z[k]
                     for k in z.files if k.startswith("input:")}
            inputs = named if named else (
                z["input"] if "input" in z.files else None)
    except (OSError, ValueError, KeyError) as e:
        raise _ClientError(f"malformed npz body: {e}") from None
    if inputs is None:
        raise _ClientError("npz body carries no 'input' entry")
    if not isinstance(meta, dict):
        raise _ClientError("npz __meta__ must be a JSON object")
    return {"inputs": inputs, **meta}


def encode_npz_response(outputs, meta: dict) -> bytes:
    if isinstance(outputs, list):
        arrays = {f"output:{i}": np.asarray(o)
                  for i, o in enumerate(outputs)}
    else:
        arrays = {"output": np.asarray(outputs)}
    return _npz_bytes(arrays, meta)


def decode_npz_response(raw: bytes) -> dict:
    """Client-side parse: the response dict with `outputs` as host
    numpy array(s) — never round-tripped through JSON lists."""
    import io

    with np.load(io.BytesIO(raw), allow_pickle=False) as z:
        resp = (json.loads(str(z["__meta__"]))
                if "__meta__" in z.files else {})
        multi = sorted((k for k in z.files if k.startswith("output:")),
                       key=lambda k: int(k.split(":", 1)[1]))
        if multi:
            resp["outputs"] = [z[k] for k in multi]
        elif "output" in z.files:
            resp["outputs"] = z["output"]
    return resp


class ModelServer:
    """Serve trained MultiLayerNetwork/ComputationGraph models over
    HTTP.

    Single-model compatibility: `ModelServer(net)` registers `net` as
    the registry's default model and every PR 1-5 route (/predict,
    /status, probes) behaves exactly as before. Multi-model: pass
    `registry=` (a serving.ModelRegistry) or keep registering models on
    `server.registry` — each model × version gets its own warmed
    ParallelInference and the /v1/models routes drive the lifecycle.

    `tenants` ({name: {"rate": ..., "burst": ..., "priority": ...}} or
    {name: TenantConfig}) arms the admission layer: per-tenant token
    buckets and priority classes, lowest class shed first under queue
    pressure. `labels` (optional ImageNetLabels) enables decoded top-k
    responses — the user-facing half of the zoo
    (`decode_predictions`)."""

    def __init__(self, net=None, port: int = 0, host: str = "127.0.0.1",
                 inference_mode: str = InferenceMode.BATCHED,
                 batch_limit: int = 32, labels=None,
                 output_activation: bool = True,
                 pipeline_depth: int = 2, warmup: bool = True,
                 max_wait_ms: float = 2.0, adaptive_wait: bool = True,
                 tracer=None, registry=None, admission=None,
                 tenants=None, model_name: str = "default",
                 queue_limit: int = 64, decode_engine=None,
                 decode_engines=None, journal_dir: Optional[str] = None):
        from deeplearning4j_tpu.serving.admission import (
            AdmissionController,
            TenantConfig,
        )
        from deeplearning4j_tpu.serving.registry import ModelRegistry

        self._owns_registry = registry is None
        self.registry = registry if registry is not None else \
            ModelRegistry(inference_mode=inference_mode,
                          batch_limit=batch_limit,
                          queue_limit=queue_limit,
                          pipeline_depth=pipeline_depth,
                          warmup=warmup, max_wait_ms=max_wait_ms,
                          adaptive_wait=adaptive_wait, tracer=tracer)
        if net is not None:
            self.registry.register(model_name, net)
        if admission is not None:
            self.admission = admission
        elif tenants:
            self.admission = AdmissionController(
                {n: (t if isinstance(t, TenantConfig)
                     else TenantConfig.from_dict(n, t))
                 for n, t in tenants.items()})
        else:
            self.admission = None
        # continuous-batching decode engines, keyed by model name
        # (serving/continuous.py — the /v1/models/<m>/generate route)
        self.decode_engines = dict(decode_engines or {})
        if decode_engine is not None:
            self.decode_engines.setdefault(model_name, decode_engine)
        # durable serving: one write-ahead generation journal per
        # model-version under `journal_dir` (serving/journal.py).
        # Attaching RECOVERS — a server constructed on the journal dir
        # a crashed process left behind re-admits every in-flight
        # generation (resume_tokens replay) before it serves a request
        self.journal_dir = journal_dir
        self._journals = {}
        for name, engine in self.decode_engines.items():
            self._attach_journal(name, engine)
        self.tracer = tracer if tracer is not None \
            else getattr(self._default_pi(), "tracer", None)
        # engines without their own tracer inherit the server's: the
        # server-side rpc.generate span and the engine's generation
        # span tree land in ONE buffer (one export) per process
        if self.tracer is not None:
            for engine in self.decode_engines.values():
                if getattr(engine, "tracer", None) is None:
                    engine.tracer = self.tracer
        self.labels = labels
        self.host = host
        self.port = port
        self._httpd = None
        self._thread: Optional[threading.Thread] = None
        self._served = 0
        self._served_lock = threading.Lock()
        self._ready = False
        self._started_engines = set()
        self._t0 = time.monotonic()

    # --------------------------------------------------------- plumbing
    @property
    def pi(self):
        """The default model's ACTIVE ParallelInference (the PR 1-5
        single-model surface)."""
        return self._default_pi()

    def _default_pi(self):
        try:
            e = self.registry.default_entry()
            with e._lock:
                return e.versions[e.active].pi if e.active else None
        except ModelNotFoundError:
            return None

    def _healthy(self) -> bool:
        return self.registry.healthy()

    # ------------------------------------------------------------ handlers
    @staticmethod
    def _request_arrays(req: dict, pi) -> list:
        """The request's input arrays: a bare array for single-input
        models, or a dict of named streams ordered by the graph's
        network_inputs for multi-input graphs."""
        try:
            inputs = req["inputs"]
        except KeyError:
            raise _ClientError("missing required field 'inputs'") from None
        try:
            if isinstance(inputs, dict):
                names = getattr(getattr(pi.net, "conf", None),
                                "network_inputs", None) or \
                    sorted(inputs)
                missing = [n for n in names if n not in inputs]
                if missing:
                    raise _ClientError(
                        f"missing named inputs {missing} "
                        f"(model wants {list(names)})")
                xs = [np.asarray(inputs[n], np.float32) for n in names]
            else:
                xs = [np.asarray(inputs, np.float32)]
        except _ClientError:
            raise
        except (TypeError, ValueError) as e:
            raise _ClientError(f"bad 'inputs': {e}") from None
        if req.get("single", False):
            xs = [x[None, ...] for x in xs]   # one unbatched example
        return xs

    def _handle_predict(self, req: dict, model: Optional[str] = None,
                        tenant: Optional[str] = None,
                        binary: bool = False) -> dict:
        entry = (self.registry.entry(model) if model is not None
                 else self.registry.default_entry())
        tenant = tenant or req.get("tenant")
        top = int(req.get("decode_top", 0))
        if top > 0 and self.labels is None:
            raise _ClientError(
                "server started without labels; decode_top unavailable")
        # the lease pins ONE (version, pi) pair: a hot-swap between
        # admission and response is invisible to this request
        with entry.lease() as (version, pi):
            priority = None
            if self.admission is not None:
                cfg = self.admission.admit(tenant, entry.name,
                                           pi.queue_depth(),
                                           pi.queue_limit)
                # admitted requests also DEQUEUE in class order:
                # high-before-normal-before-low inside the bounded queue
                priority = cfg.priority
            xs = self._request_arrays(req, pi)
            out = pi.output(*xs, priority=priority)
            _obs.count("dl4j_serving_model_requests_total",
                       labels={"model": entry.name, "version": version})
        with self._served_lock:
            self._served += xs[0].shape[0]
        multi = isinstance(out, list)
        # binary wire: outputs stay host numpy arrays (the handler npz-
        # encodes them straight from these buffers); JSON wire converts
        # to nested lists — the completion stage already paid the
        # device fetch either way, so both are host-side copies
        if binary:
            outputs = ([np.asarray(o) for o in out] if multi
                       else np.asarray(out))
        else:
            outputs = (
                [np.asarray(o).tolist() for o in out]  # analyze: allow=jit-host-sync
                if multi else np.asarray(out).tolist())
        resp = {
            "outputs": outputs,
            "model": entry.name,
            "version": version,
        }
        if multi:
            resp["multi_output"] = True
        if top > 0 and not multi:
            out = np.asarray(out)
            resp["decoded"] = [
                [{"class": c, "wnid": w, "label": l, "probability": p}
                 for (c, w, l, p) in row]
                for row in self.labels.decode_predictions(out, top=top)]
        return resp

    # --------------------------------------------------------- generate
    def attach_decode_engine(self, name: str, engine) -> "ModelServer":
        """Attach a continuous-batching DecodeEngine to model `name`
        (the /v1/models/<name>/generate route). With `journal_dir`
        set, the engine also gets its per-model-version write-ahead
        journal (recovery included)."""
        self.decode_engines[name] = engine
        if self.tracer is not None \
                and getattr(engine, "tracer", None) is None:
            engine.tracer = self.tracer
        self._attach_journal(name, engine)
        return self

    def _attach_journal(self, name: str, engine) -> None:
        """Open (or recover) model `name`'s journal and arm the
        engine with it. Engines that already carry a journal keep it
        (the caller-owned rule)."""
        if self.journal_dir is None \
                or getattr(engine, "_journal", None) is not None:
            return
        from deeplearning4j_tpu.serving.journal import GenerationJournal

        try:
            version = self.registry.entry(name).active or "v0"
        except ModelNotFoundError:
            version = "v0"
        journal = GenerationJournal(
            os.path.join(self.journal_dir, f"{name}@{version}"))
        self._journals[name] = journal
        engine.attach_journal(journal, recover=True)

    def _handle_generate(self, req: dict, model: Optional[str],
                         tenant: Optional[str] = None) -> dict:
        name = model or self.registry.default_model
        engine = self.decode_engines.get(name)
        if engine is None:
            raise ModelNotFoundError(
                f"model {name!r} has no decode engine attached")
        # npz wire reuses the generic 'inputs' array entry as the
        # prompt; JSON spells it 'prompt'
        prompt = req.get("prompt", req.get("inputs"))
        if prompt is None:
            raise _ClientError("missing required field 'prompt'")
        try:
            prompt = [int(t) for t in np.asarray(prompt).ravel()]
        except (TypeError, ValueError) as e:
            raise _ClientError(f"bad 'prompt': {e}") from None
        try:
            max_new = int(req.get("max_new_tokens", 16))
            eos_id = req.get("eos_id")
            eos_id = None if eos_id is None else int(eos_id)
            timeout_s = float(req.get("timeout_s", 60.0))
            deadline_s = req.get("deadline_s")
            deadline_s = None if deadline_s is None else float(deadline_s)
            resume = req.get("resume_tokens")
            if resume is not None:
                resume = [int(t) for t in np.asarray(resume).ravel()]
            rid = req.get("request_id")
            rid = None if rid is None else str(rid)
            trace = req.get("trace")
            trace = None if trace is None else str(trace)
        except (TypeError, ValueError) as e:
            raise _ClientError(f"bad generate parameters: {e}") \
                from None
        tenant = tenant or req.get("tenant")
        if self.tracer is None:
            return self._run_generation(
                engine, name, prompt, max_new, eos_id, timeout_s,
                deadline_s, resume, rid, tenant, trace)
        if trace is None:
            from deeplearning4j_tpu.observability.tracing import (
                new_trace_id,
            )

            trace = new_trace_id()
        # the replica-side request span: the engine's "generate" root
        # span (opened by submit on this thread) nests under it via the
        # tracer's implicit stack, so one process's leg is one subtree
        with self.tracer.span("rpc.generate", cat="serving",
                              args={"trace": trace, "model": name,
                                    "request_id": rid or ""}):
            return self._run_generation(
                engine, name, prompt, max_new, eos_id, timeout_s,
                deadline_s, resume, rid, tenant, trace)

    def _run_generation(self, engine, name, prompt, max_new, eos_id,
                        timeout_s, deadline_s, resume, rid, tenant,
                        trace) -> dict:
        if not engine.running:
            if not self._ready:
                # retiring replica: never restart a decode loop the
                # shutdown path already tore down — tell the caller to
                # take its generation elsewhere instead
                raise ShutdownError("server stopping; replica retiring")
            # lazily start the decode loop; stop() tears down only
            # loops this server started (caller-owned engines keep
            # running — the caller-owned ParallelInference rule)
            engine.ensure_started()
            self._started_engines.add(name)
        try:
            handle = engine.submit(prompt, max_new, eos_id=eos_id,
                                   tenant=tenant, deadline_s=deadline_s,
                                   resume_tokens=resume,
                                   request_id=rid, trace=trace)
        except ValueError as e:
            raise _ClientError(str(e)) from None
        try:
            handle.result(timeout_s=timeout_s)
        except ShutdownError as e:
            # replica retiring mid-generation: the 503 body carries the
            # tokens decoded so far plus a `resumable` marker, so the
            # caller (ModelClient / ReplicaRouter) can re-dispatch the
            # request to a healthy replica as a continuation instead of
            # losing the work (the trace id rides along, so the next
            # leg joins the same timeline)
            e.partial = {"tokens": handle.tokens_so_far(),
                         "finish_reason": "migrated",
                         "model": name, "resumable": True,
                         "trace": handle.trace}
            raise
        except TimeoutError:
            # transport-level wait budget, distinct from the engine's
            # own deadline sweep: free the slot and surface a resumable
            # 503 with whatever was decoded (same continuation contract)
            handle.cancel()
            err = DeadlineExceededError(
                f"generation exceeded timeout_s={timeout_s}")
            err.partial = {"tokens": handle.tokens_so_far(),
                           "finish_reason": "timeout",
                           "model": name, "resumable": True,
                           "trace": handle.trace}
            raise err from None
        return {
            "tokens": handle.tokens_so_far(),
            "model": name,
            "finish_reason": handle.finish_reason,
            "evictions": handle.evictions,
            "replays": handle.replays,
            "request_id": handle.request_id,
            "trace": handle.trace,
        }

    # ------------------------------------------------- lifecycle routes
    def _handle_put_version(self, model: str, version: str,
                            req: dict) -> dict:
        path = req.get("path")
        if not path or not isinstance(path, str):
            raise _ClientError(
                "body must carry 'path': a server-readable model zip")
        self.registry.load_version(
            model, version, path,
            model_type=req.get("model_type", "auto"),
            activate=bool(req.get("activate", True)),
            warmup_inputs=req.get("warmup_inputs"))
        return {"model": model, "version": version,
                "active": self.registry.entry(model).active}

    def _handle_model_command(self, model: str, command: str,
                              req: dict) -> dict:
        if command == "rollback":
            version = self.registry.rollback(model)
        elif command == "swap":
            version = req.get("version")
            if not version:
                raise _ClientError("swap needs 'version' in the body")
            self.registry.swap(model, version)
        else:
            raise ModelNotFoundError(f"no model command {command!r}")
        return {"model": model,
                "active": self.registry.entry(model).active,
                "previous": self.registry.entry(model).previous}

    # ----------------------------------------------------------- status
    def _status_facts(self) -> dict:
        pi = self._default_pi()
        entry = None
        try:
            entry = self.registry.default_entry()
        except ModelNotFoundError:
            pass
        facts = {
            "model": (type(pi.net).__name__ if pi is not None
                      else None),
            "default_model": self.registry.default_model,
            "version": (entry.active if entry is not None else None),
            "models": self.registry.model_names(),
            "inference_mode": (pi.mode if pi is not None else None),
            "batch_limit": (pi.batch_limit if pi is not None else None),
            "served": self._served,
            "queue_depth": (pi.queue_depth() if pi is not None else 0),
            "healthy": self._healthy(),
            "ready": self._ready and self._healthy(),
            "has_labels": self.labels is not None}
        # pipelined data-plane + compile-once guard facts: bucket
        # warmup, trace/recompile counters, adaptive-wait state
        if pi is not None:
            facts["pipeline"] = pi.stats()
            trace = pi.trace_stats()
            facts["trace_counts"] = trace.get("trace_counts", {})
            facts["total_traces"] = trace.get("total_traces", 0)
            # recompile forensics: "why did that request take 8s" —
            # the signature/duration/cost ring of recent new traces
            facts["recompiles"] = {
                "total": trace.get("compiles_total", 0),
                "recent": trace.get("compile_events", []),
            }
        if self.admission is not None:
            facts["admission"] = self.admission.stats()
        # continuous-batching decode engines: slot occupancy, token
        # throughput, eviction/prefill counters, compile-trace pins
        if self.decode_engines:
            facts["decode"] = {name: engine.stats()
                               for name, engine
                               in self.decode_engines.items()}
        # durable serving: per-model journal occupancy (live WAL
        # entries, torn tails truncated, compactions, disk bytes)
        if self._journals:
            facts["journal"] = {name: j.stats()
                                for name, j in self._journals.items()}
        # telemetry facts (observability/): uptime + the registry's
        # monotonic request/error counters (process-wide, survive
        # across this server's construction), plus span-buffer facts
        # when a tracer is attached
        reg = get_registry()
        facts["uptime_s"] = round(time.monotonic() - self._t0, 3)
        facts["requests_total"] = int(reg.counter_value(
            "dl4j_serving_requests_total"))
        facts["errors_total"] = int(reg.counter_value(
            "dl4j_serving_errors_total"))
        facts["telemetry"] = {
            "enabled": _obs.telemetry_enabled(),
            "dropped_emissions": reg.dropped,
            "spans": (self.tracer.stats()
                      if self.tracer is not None else None),
        }
        return facts

    def _metrics_text(self) -> str:
        """The GET /metrics body: refresh the pull-style gauges from
        the live front-end, then render the whole registry."""
        pi = self._default_pi()
        if pi is not None:
            _obs.set_gauge("dl4j_serving_queue_depth",
                           pi.queue_depth())
            trace = pi.trace_stats()
            _obs.set_gauge("dl4j_jit_traces_total",
                           trace.get("total_traces", 0))
        return get_registry().prometheus_text()

    # --------------------------------------------------------------- start
    def start(self) -> "ModelServer":
        import http.server
        import socketserver

        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _send(self, code, obj, headers=()):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _send_text(self, code, text, content_type):
                self._send_bytes(code, text.encode(), content_type)

            def _send_bytes(self, code, body, content_type):
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_error(self, code, exc, headers=()):
                _obs.count("dl4j_serving_errors_total",
                           labels={"code": str(code)})
                body = {"error": str(exc),
                        "error_class": type(exc).__name__}
                # a retiring replica attaches the partial generation
                # (tokens so far + resumable marker) to the exception;
                # ship it in the error body so the caller can migrate
                # the request instead of restarting from scratch
                partial = getattr(exc, "partial", None)
                if isinstance(partial, dict):
                    body.update(partial)
                self._send(code, body, headers)

            def _send_404(self):
                self._send(404, {"error": f"no route {self.path}",
                                 "error_class": "NotFound"})

            def _read_raw(self) -> bytes:
                n = int(self.headers.get("Content-Length", 0))
                return self.rfile.read(n) if n else b""

            @staticmethod
            def _parse_json(raw: bytes) -> dict:
                try:
                    req = json.loads(raw.decode() or "{}")
                except (ValueError, UnicodeDecodeError) as e:
                    raise _ClientError(f"malformed JSON body: {e}") \
                        from None
                if not isinstance(req, dict):
                    raise _ClientError("body must be a JSON object")
                return req

            def _read_body(self) -> dict:
                return self._parse_json(self._read_raw())

            @staticmethod
            def _model_route(path):
                """('name', 'cmd', 'ver') from /v1/models/... paths;
                None when the path is not under /v1/models."""
                parts = [p for p in path.split("/") if p]
                if len(parts) < 2 or parts[0] != "v1" \
                        or parts[1] != "models":
                    return None
                name = parts[2] if len(parts) > 2 else None
                cmd = parts[3] if len(parts) > 3 else None
                ver = parts[4] if len(parts) > 4 else None
                return name, cmd, ver

            def _guarded(self, fn, value_error_code=400):
                """Run a handler under the full error taxonomy.
                `value_error_code` routes bare ValueErrors: 400 on data
                routes (bad request payloads), 409 on lifecycle routes
                (swap/delete conflicts)."""
                try:
                    return fn()
                except _ClientError as e:
                    self._send_error(400, e)
                except ModelNotFoundError as e:
                    self._send_error(404, e)
                except QuotaExceededError as e:
                    retry_after = getattr(e, "retry_after_s", 1.0) or 1.0
                    self._send_error(
                        429, e,
                        [("Retry-After", f"{max(1, int(retry_after))}")])
                except CheckpointIntegrityError as e:
                    # rejected corrupt/torn uploads
                    self._send_error(409, e)
                except ValueError as e:
                    self._send_error(value_error_code, e)
                except _UNAVAILABLE as e:
                    retry_after = getattr(e, "retry_after_s", 1.0) or 1.0
                    self._send_error(
                        503, e,
                        [("Retry-After", f"{max(1, int(retry_after))}")])
                except Exception as e:   # noqa: BLE001 - HTTP boundary
                    self._send_error(500, e)

            def do_GET(self):
                path = self.path.rstrip("/")
                route = self._model_route(path)
                if path == "/status":
                    self._send(200, server._status_facts())
                elif path == "/metrics":
                    # Prometheus text exposition (scrape target)
                    self._send_text(
                        200, server._metrics_text(),
                        "text/plain; version=0.0.4; charset=utf-8")
                elif path == "/healthz":
                    if server._healthy():
                        self._send(200, {"status": "ok"})
                    else:
                        self._send(503, {"status": "unhealthy",
                                         "healthy": False},
                                   [("Retry-After", "1")])
                elif path == "/readyz":
                    if server._ready and server._healthy():
                        self._send(200, {"status": "ready"})
                    else:
                        self._send(503, {"status": "not ready"},
                                   [("Retry-After", "1")])
                elif route is not None:
                    name, cmd, _ = route
                    if name is None:
                        self._send(200, server.registry.models_status())
                    elif cmd == "status":
                        self._guarded(lambda: self._send(
                            200, server.registry.entry(name).status()))
                    else:
                        self._send_404()
                else:
                    self._send_404()

            def _predict(self, model):
                _obs.count("dl4j_serving_requests_total")
                t0 = time.perf_counter()

                def _run():
                    _fire("serve.request")
                    # chaos drill: an armed `rollout.canary_poison`
                    # degrades THIS replica's serving — mode=delay adds
                    # latency, mode=raise turns the request into a 500;
                    # the FleetController's canary SLO watch must catch
                    # either shape and auto-roll the canary back
                    _fire("rollout.canary_poison")
                    binary = NPZ_CONTENT_TYPE in (
                        self.headers.get("Content-Type") or "")
                    req = (decode_npz_request(self._read_raw())
                           if binary else self._read_body())
                    resp = server._handle_predict(
                        req, model=model,
                        tenant=self.headers.get("X-Tenant"),
                        binary=binary)
                    _obs.observe("dl4j_serving_request_seconds",
                                 time.perf_counter() - t0)
                    if binary:
                        outputs = resp.pop("outputs")
                        self._send_bytes(
                            200, encode_npz_response(outputs, resp),
                            NPZ_CONTENT_TYPE)
                    else:
                        self._send(200, resp)

                self._guarded(_run)

            def _generate(self, model):
                _obs.count("dl4j_serving_requests_total")
                t0 = time.perf_counter()

                def _run():
                    _fire("serve.request")
                    binary = NPZ_CONTENT_TYPE in (
                        self.headers.get("Content-Type") or "")
                    req = (decode_npz_request(self._read_raw())
                           if binary else self._read_body())
                    resp = server._handle_generate(
                        req, model=model,
                        tenant=self.headers.get("X-Tenant"))
                    _obs.observe("dl4j_serving_request_seconds",
                                 time.perf_counter() - t0)
                    if resp.get("finish_reason") == "deadline":
                        # request deadline expired mid-generation: 504
                        # with the partial stream in a JSON body (both
                        # wires — the client reads HTTP error bodies as
                        # JSON, so npz framing would hide the tokens)
                        self._send(504, resp)
                    elif binary:
                        # the VARIABLE-LENGTH token output rides as a
                        # raw int32 array entry, length set by this
                        # request's generation alone
                        tokens = np.asarray(resp.pop("tokens"),
                                            np.int32)
                        self._send_bytes(
                            200, encode_npz_response(tokens, resp),
                            NPZ_CONTENT_TYPE)
                    else:
                        self._send(200, resp)

                self._guarded(_run)

            def do_POST(self):
                path = self.path.rstrip("/")
                route = self._model_route(path)
                if path == "/predict":
                    self._predict(None)
                elif route is not None and route[1] == "predict":
                    self._predict(route[0])
                elif route is not None and route[1] == "generate":
                    self._generate(route[0])
                elif route is not None and route[1] in ("rollback",
                                                        "swap"):
                    name, cmd, _ = route
                    self._guarded(lambda: self._send(
                        200, server._handle_model_command(
                            name, cmd, self._read_body())),
                        value_error_code=409)
                else:
                    self._send_404()

            def do_PUT(self):
                route = self._model_route(self.path.rstrip("/"))
                if route is None or route[1] != "versions" \
                        or route[2] is None:
                    self._send_404()
                    return
                name, _, ver = route
                self._guarded(lambda: self._send(
                    200, server._handle_put_version(
                        name, ver, self._read_body())),
                    value_error_code=409)

            def do_DELETE(self):
                route = self._model_route(self.path.rstrip("/"))
                if route is None or route[0] is None:
                    self._send_404()
                    return
                name, cmd, ver = route

                def _run():
                    if cmd == "versions" and ver is not None:
                        server.registry.delete_version(name, ver)
                        self._send(200, {"model": name, "deleted": ver})
                    elif cmd is None:
                        server.registry.remove(name)
                        self._send(200, {"deleted": name})
                    else:
                        self._send_404()

                self._guarded(_run, value_error_code=409)

            def log_message(self, *a):
                pass

        class _Server(socketserver.ThreadingMixIn, http.server.HTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._httpd = _Server((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="ModelServer-http")
        self._thread.start()
        self._ready = True
        return self

    def stop(self):
        self._ready = False   # flip /readyz before tearing anything down
        # stop decode-engine loops BEFORE the HTTP listener: in-flight
        # generate handlers unblock with ShutdownError and answer 503
        # with their partial streams over still-open connections — the
        # migration handoff — instead of dying with the socket. Only
        # loops THIS server started are stopped; caller-started engines
        # keep running (the PI ownership rule).
        for name in sorted(self._started_engines):
            engine = self.decode_engines.get(name)
            if engine is not None:
                engine.stop()
        self._started_engines.clear()
        # close journals AFTER the engines stop appending. Closing is
        # not completion: requests the shutdown interrupted stay live
        # on disk for the next process to recover
        for journal in self._journals.values():
            journal.close()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if self._owns_registry:
            # the registry shuts down only the ParallelInference
            # front-ends it built — never a caller-supplied one
            self.registry.shutdown()


_DEFAULT_BREAKER = object()   # sentinel: "construct the default breaker"


class ModelClient:
    """Client for ModelServer (the serve-route consumer).

    HTTP errors surface as typed ServingError carrying the status code
    and the server's JSON {error, error_class} payload (no more
    swallowed bodies). Idempotent calls (/predict, /status, probes)
    retry on connection errors and 503 per `retry` — pass
    `retry=Retry(max_attempts=1)` to disable.

    A CircuitBreaker guards every request BY DEFAULT: repeated
    unavailability (503s, connection errors, retry exhaustion) opens
    the circuit and subsequent calls fail fast with CircuitOpenError —
    letting a drowning server breathe instead of hammering it — until
    the cooldown lets one probe through (half-open). Any response from
    the server, even a 4xx/500, proves liveness and closes the circuit.
    Pass `breaker=None` to disable, or your own CircuitBreaker to tune
    thresholds. Health probes (`healthz`/`readyz`) bypass the breaker:
    a probe must see the instantaneous truth."""

    def __init__(self, url: str, timeout: float = 30.0,
                 retry: Optional[Retry] = None,
                 breaker=_DEFAULT_BREAKER, wire: str = "auto"):
        """`wire`: "auto" (default) speaks the binary npz format and
        permanently falls back to JSON the first time the server turns
        out to predate it; "npz" never falls back; "json" never tries
        binary (byte-compatible with PR 1-9 clients)."""
        if wire not in ("auto", "npz", "json"):
            raise ValueError(f"wire must be auto|npz|json: {wire!r}")
        self.url = url.rstrip("/")
        self.timeout = timeout
        self.wire = wire
        self._npz_ok = wire != "json"
        self.retry = retry if retry is not None else Retry(
            max_attempts=3, initial_backoff_s=0.05, max_backoff_s=1.0,
            retryable=self._retryable)
        self.breaker: Optional[CircuitBreaker] = (
            CircuitBreaker(failure_threshold=5, reset_timeout_s=5.0)
            if breaker is _DEFAULT_BREAKER else breaker)

    @staticmethod
    def _retryable(exc: Exception) -> bool:
        if isinstance(exc, ServingError):
            return exc.retryable
        return isinstance(exc, (ConnectionError, OSError, TimeoutError))

    @staticmethod
    def _breaker_counted(exc: Exception) -> bool:
        """Failures that indicate an UNAVAILABLE dependency (and should
        trip the breaker) vs. responses that merely report an error."""
        if isinstance(exc, ServingError):
            return exc.retryable         # 503/429: back off
        if isinstance(exc, RetriesExhaustedError):
            return True
        return isinstance(exc, (ConnectionError, OSError, TimeoutError))

    def _call_guarded(self, fn):
        """Run `fn` under the circuit breaker (when enabled). Counted
        failures open it; any server response — success OR typed
        4xx/500 error — records success (the dependency is alive)."""
        if self.breaker is None:
            return fn()

        def _probe_once():
            try:
                return True, fn(), None
            except Exception as e:   # noqa: BLE001 - breaker boundary
                if self._breaker_counted(e):
                    raise             # breaker records the failure
                return False, None, e  # alive: breaker records success

        ok, result, exc = self.breaker.call(_probe_once)
        if not ok:
            raise exc
        return result

    def _request(self, route: str, payload: Optional[dict] = None,
                 method: Optional[str] = None) -> dict:
        import urllib.error
        import urllib.request

        def _once():
            data = (json.dumps(payload).encode()
                    if payload is not None else None)
            req = urllib.request.Request(
                self.url + route, data=data, method=method,
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req,
                                            timeout=self.timeout) as r:
                    return json.loads(r.read().decode())
            except urllib.error.HTTPError as e:
                raise self._serving_error(e) from None

        return self._call_guarded(lambda: self.retry.call(_once))

    @staticmethod
    def _serving_error(e) -> ServingError:
        """Parse the server's JSON error payload out of an HTTPError."""
        try:
            body = json.loads(e.read().decode())
        except Exception:   # noqa: BLE001 - body may be anything
            body = {}
        retry_after = e.headers.get("Retry-After") if e.headers else None
        return ServingError(
            status=e.code,
            message=body.get("error", str(e)),
            error_class=body.get("error_class", ""),
            body=body,
            retry_after_s=float(retry_after) if retry_after else None)

    def _post(self, route: str, payload: dict) -> dict:
        return self._request(route, payload)

    def _request_bytes(self, route: str, data: bytes,
                       content_type: str) -> dict:
        """POST raw bytes; parse the response by ITS content type
        (npz responses come back with `outputs` as host numpy arrays,
        JSON responses exactly as before). Same retry + breaker
        discipline as `_request`."""
        import urllib.error
        import urllib.request

        def _once():
            req = urllib.request.Request(
                self.url + route, data=data,
                headers={"Content-Type": content_type})
            try:
                with urllib.request.urlopen(req,
                                            timeout=self.timeout) as r:
                    body = r.read()
                    if NPZ_CONTENT_TYPE in (
                            r.headers.get("Content-Type") or ""):
                        return decode_npz_response(body)
                    return json.loads(body.decode())
            except urllib.error.HTTPError as e:
                raise self._serving_error(e) from None

        return self._call_guarded(lambda: self.retry.call(_once))

    @staticmethod
    def _old_server_error(e: ServingError) -> bool:
        """True when an npz POST bounced off a server that predates
        the binary wire: its JSON-only route 400s with 'malformed JSON
        body' (binary bytes that happen to decode) or 500s on the
        UnicodeDecodeError. Genuine application errors (bad shapes,
        missing labels, quota, overload) pass through untouched."""
        if e.status == 415:
            return True
        if e.status == 400 and "malformed JSON body" in (str(e) or ""):
            return True
        return e.status == 500 and e.error_class == "UnicodeDecodeError"

    def predict(self, inputs, decode_top: int = 0,
                model: Optional[str] = None,
                tenant: Optional[str] = None) -> dict:
        """POST /predict, or /v1/models/<model>/predict when `model`
        is given. `inputs` may be an array or (for multi-input graphs)
        a dict of named input streams; `tenant` rides in the body for
        the server's admission layer.

        Wire format: binary npz by default — inputs ship as raw array
        bytes and `outputs` come back as host numpy array(s), never
        round-tripped through JSON nested lists. The first response
        proving the server predates the format flips this client to
        the legacy JSON wire permanently (`wire="json"` forces it;
        JSON responses keep the historical list-shaped outputs)."""
        route = (f"/v1/models/{model}/predict" if model is not None
                 else "/predict")
        meta = {}
        if decode_top:
            meta["decode_top"] = decode_top
        if tenant is not None:
            meta["tenant"] = tenant
        if self._npz_ok:
            try:
                return self._request_bytes(
                    route, encode_npz_request(inputs, meta),
                    NPZ_CONTENT_TYPE)
            except ServingError as e:
                if self.wire == "npz" or not self._old_server_error(e):
                    raise
                self._npz_ok = False   # old server: JSON from here on
        if isinstance(inputs, dict):
            payload = {"inputs": {
                k: np.asarray(v).tolist()   # analyze: allow=jit-host-sync — legacy JSON wire fallback, host-side data
                for k, v in inputs.items()}}
        else:
            payload = {
                "inputs": np.asarray(inputs).tolist()}   # analyze: allow=jit-host-sync — legacy JSON wire fallback, host-side data
        payload.update(meta)
        return self._request(route, payload)

    def generate(self, prompt, max_new_tokens: int = 16,
                 eos_id: Optional[int] = None,
                 model: Optional[str] = None,
                 tenant: Optional[str] = None,
                 timeout_s: Optional[float] = None,
                 deadline_s: Optional[float] = None,
                 resume_tokens=None,
                 max_resumes: int = 3,
                 request_id: Optional[str] = None,
                 trace: Optional[str] = None) -> dict:
        """POST /v1/models/<model>/generate — continuous-batched
        autoregressive generation. Returns {"tokens": [int, ...],
        "finish_reason": "eos"|"length"|"deadline", ...}; the token
        list length varies per request (eos can cut it short). Binary
        npz wire by default: the prompt ships as a raw int array and
        the variable-length output comes back as one — same
        fall-back-to-JSON discipline as `predict`. Slot exhaustion
        surfaces as a 429 ServingError with Retry-After.

        Generation durability: a replica that retires mid-generation
        answers 503 with the tokens decoded so far and a `resumable`
        marker; this client re-issues the request as a CONTINUATION
        carrying those tokens (`resume_tokens` on the wire, up to
        `max_resumes` times), so the final stream is byte-identical to
        an uninterrupted call — greedy decode replay, not re-sampling.
        `deadline_s` rides to the engine's deadline sweep; an expired
        deadline comes back as HTTP 504 whose partial stream is
        returned here as a normal dict with finish_reason="deadline".

        `request_id` is the idempotency key (client-generated here
        when not supplied): it is STABLE across every resume retry of
        this logical call, so a retry after an ambiguous disconnect —
        the response lost, the server's fate unknown — joins the
        original journaled stream instead of double-executing."""
        resume = ([int(t) for t in np.asarray(resume_tokens).ravel()]
                  if resume_tokens is not None else [])
        rid = str(request_id) if request_id else uuid.uuid4().hex
        trace = str(trace) if trace else None
        last: Optional[Exception] = None
        for _ in range(max(0, int(max_resumes)) + 1):
            try:
                return self._generate_once(
                    prompt, max_new_tokens, eos_id=eos_id, model=model,
                    tenant=tenant, timeout_s=timeout_s,
                    deadline_s=deadline_s,
                    resume_tokens=resume or None, request_id=rid,
                    trace=trace)
            except (ServingError, RetriesExhaustedError) as e:
                partial = self._resumable_partial(e)
                if partial is None:
                    raise
                # re-raised on budget exhaustion: the LAST resumable
                # failure still carries its partial body, so an outer
                # router can keep migrating where this client stopped
                last = e
                got = partial.get("tokens") or []
                if len(got) > len(resume):
                    resume = [int(t) for t in got]
                # a server that minted the trace id reports it in the
                # partial body — carry it into the next leg so the
                # continuation joins the same timeline
                if trace is None and partial.get("trace"):
                    trace = str(partial["trace"])
        raise last

    @staticmethod
    def _resumable_partial(e: Exception) -> Optional[dict]:
        """The server's resumable-partial body out of a generate
        failure, or None when the failure carries no continuation
        (connection refused, plain 503, 4xx...)."""
        if isinstance(e, RetriesExhaustedError):
            e = e.cause
        if not isinstance(e, ServingError):
            return None
        body = e.body or {}
        if body.get("resumable") and body.get("tokens") is not None:
            return body
        return None

    def _generate_once(self, prompt, max_new_tokens: int,
                       eos_id: Optional[int], model: Optional[str],
                       tenant: Optional[str],
                       timeout_s: Optional[float],
                       deadline_s: Optional[float],
                       resume_tokens: Optional[list],
                       request_id: Optional[str] = None,
                       trace: Optional[str] = None) -> dict:
        model = model or "default"
        route = f"/v1/models/{model}/generate"
        meta = {"max_new_tokens": int(max_new_tokens)}
        if request_id is not None:
            meta["request_id"] = str(request_id)
        if trace is not None:
            meta["trace"] = str(trace)
        if eos_id is not None:
            meta["eos_id"] = int(eos_id)
        if tenant is not None:
            meta["tenant"] = tenant
        if timeout_s is not None:
            meta["timeout_s"] = float(timeout_s)
        if deadline_s is not None:
            meta["deadline_s"] = float(deadline_s)
        if resume_tokens:
            meta["resume_tokens"] = [int(t) for t in resume_tokens]
        try:
            if self._npz_ok:
                try:
                    resp = self._request_bytes(
                        route,
                        encode_npz_request(
                            np.asarray(prompt, np.int32), meta),
                        NPZ_CONTENT_TYPE)
                    out = resp.pop("outputs", None)
                    if out is not None and "tokens" not in resp:
                        resp["tokens"] = [int(t) for t in
                                          np.asarray(out).ravel()]
                    return resp
                except ServingError as e:
                    if self.wire == "npz" \
                            or not self._old_server_error(e):
                        raise
                    self._npz_ok = False   # old server: JSON now on
            payload = {"prompt": [int(t) for t in
                                  np.asarray(prompt).ravel()]}
            payload.update(meta)
            return self._request(route, payload)
        except ServingError as e:
            if e.status == 504 and e.body.get("tokens") is not None:
                # deadline expired server-side: the 504 body IS the
                # partial result — surface it as one
                return dict(e.body)
            raise

    def status(self, model: Optional[str] = None) -> dict:
        if model is not None:
            return self._request(f"/v1/models/{model}/status")
        return self._request("/status")

    # --------------------------------------------- model lifecycle
    def models(self) -> dict:
        """GET /v1/models — the registry catalog."""
        return self._request("/v1/models")

    def put_version(self, model: str, version: str, path: str,
                    activate: bool = True, model_type: str = "auto",
                    warmup_inputs=None) -> dict:
        """PUT /v1/models/<model>/versions/<version> — load a model
        zip (server-side path) through the integrity-checked
        serializer and optionally hot-swap to it."""
        payload = {"path": path, "activate": activate,
                   "model_type": model_type}
        if warmup_inputs is not None:
            payload["warmup_inputs"] = [list(s) for s in warmup_inputs]
        return self._request(
            f"/v1/models/{model}/versions/{version}", payload,
            method="PUT")

    def swap(self, model: str, version: str) -> dict:
        return self._request(f"/v1/models/{model}/swap",
                             {"version": version})

    def rollback(self, model: str) -> dict:
        return self._request(f"/v1/models/{model}/rollback", {})

    def delete_version(self, model: str, version: str) -> dict:
        return self._request(
            f"/v1/models/{model}/versions/{version}", method="DELETE")

    def delete_model(self, model: str) -> dict:
        return self._request(f"/v1/models/{model}", method="DELETE")

    def metrics(self) -> dict:
        """GET /metrics parsed into {sample_name[{labels}]: value} —
        the test-friendly view of the Prometheus exposition (raw text
        via `metrics_text()`)."""
        return parse_prometheus(self.metrics_text())

    def metrics_text(self) -> str:
        import urllib.error
        import urllib.request

        req = urllib.request.Request(self.url + "/metrics")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return r.read().decode()
        except urllib.error.HTTPError as e:
            raise self._serving_error(e) from None

    def healthz(self) -> bool:
        """True iff the server reports itself live (no retry — a probe
        must see the instantaneous truth)."""
        try:
            self._probe("/healthz")
            return True
        except ServingError as e:
            if e.status == 503:
                return False
            raise

    def readyz(self) -> bool:
        try:
            self._probe("/readyz")
            return True
        except ServingError as e:
            if e.status == 503:
                return False
            raise

    def _probe(self, route: str) -> dict:
        import urllib.error
        import urllib.request

        req = urllib.request.Request(self.url + route)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return json.loads(r.read().decode())
        except urllib.error.HTTPError as e:
            raise self._serving_error(e) from None
