"""Network-facing model serving over ParallelInference.

Parity: dl4j-streaming's Camel serve route
(streaming/routes/DL4jServeRouteBuilder.java — accept a record over
the wire, run `model.output`, hand the result to a post-processor) and
the ModelServer role around ParallelInference. Kafka/Camel transports
stay out of scope (VERDICT r4); the serving surface itself is plain
HTTP+JSON like the nearest-neighbor microservice
(clustering/server.py), so the round-trip is testable anywhere.

Routes:
  POST /predict  {"inputs": [[...], ...]}          -> {"outputs": [...]}
  POST /predict  {"inputs": ..., "decode_top": 5}  -> adds "decoded"
                 (requires an ImageNetLabels source; zoo/util/imagenet)
  GET  /status   -> model + queue + telemetry facts (uptime_s,
                 monotonic request/error counters from the registry)
  GET  /metrics  -> Prometheus text exposition of the global
                 MetricsRegistry (training, serving, checkpoint, and
                 resilience domains — one scrape covers the process)
  GET  /healthz  -> liveness: 200 while the batcher is alive, 503 after
                 it dies or the server shuts down
  GET  /readyz   -> readiness: 200 only while accepting traffic

Failure taxonomy (resilience subsystem) instead of blanket 400:
  404 unknown route - 400 malformed payload / client error
  503 + Retry-After overload, shutdown, or dead batcher
  500 model/handler crash
Every error body is {"error": msg, "error_class": ExceptionName}.

Requests are funneled through ParallelInference in BATCHED mode, so
concurrent small clients coalesce into full MXU tiles (the reference's
BatchedInferenceObservable role).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional

import numpy as np

from deeplearning4j_tpu.observability import metrics as _obs
from deeplearning4j_tpu.observability.metrics import (
    get_registry,
    parse_prometheus,
)
from deeplearning4j_tpu.parallel.inference import (
    InferenceMode,
    ParallelInference,
)
from deeplearning4j_tpu.resilience.errors import (
    CircuitOpenError,
    DeadlineExceededError,
    InferenceUnavailableError,
    OverloadedError,
    RetriesExhaustedError,
    ServingError,
    ShutdownError,
)
from deeplearning4j_tpu.resilience.faults import fire as _fire
from deeplearning4j_tpu.resilience.retry import CircuitBreaker, Retry

# errors that mean "back off and retry": surfaced as 503 + Retry-After
_UNAVAILABLE = (OverloadedError, ShutdownError, InferenceUnavailableError,
                DeadlineExceededError)


class _ClientError(ValueError):
    """Request was malformed — maps to HTTP 400."""


class ModelServer:
    """Serve a trained MultiLayerNetwork/ComputationGraph over HTTP.

    `labels` (optional ImageNetLabels) enables decoded top-k responses
    — the user-facing half of the zoo (`decode_predictions`)."""

    def __init__(self, net, port: int = 0, host: str = "127.0.0.1",
                 inference_mode: str = InferenceMode.BATCHED,
                 batch_limit: int = 32, labels=None,
                 output_activation: bool = True,
                 pipeline_depth: int = 2, warmup: bool = True,
                 max_wait_ms: float = 2.0, adaptive_wait: bool = True,
                 tracer=None):
        self._owns_pi = not isinstance(net, ParallelInference)
        self.pi = (net if not self._owns_pi
                   else ParallelInference(net, inference_mode,
                                          batch_limit=batch_limit,
                                          pipeline_depth=pipeline_depth,
                                          warmup=warmup,
                                          max_wait_ms=max_wait_ms,
                                          adaptive_wait=adaptive_wait,
                                          tracer=tracer))
        self.tracer = tracer if tracer is not None \
            else getattr(self.pi, "tracer", None)
        self.labels = labels
        self.host = host
        self.port = port
        self._httpd = None
        self._thread: Optional[threading.Thread] = None
        self._served = 0
        self._served_lock = threading.Lock()
        self._ready = False
        self._t0 = time.monotonic()

    # ------------------------------------------------------------ handlers
    def _handle_predict(self, req: dict) -> dict:
        try:
            x = np.asarray(req["inputs"], np.float32)
        except KeyError:
            raise _ClientError("missing required field 'inputs'") from None
        except (TypeError, ValueError) as e:
            raise _ClientError(f"bad 'inputs': {e}") from None
        if req.get("single", False):
            x = x[None, ...]   # one unbatched example
        top = int(req.get("decode_top", 0))
        if top > 0 and self.labels is None:
            raise _ClientError(
                "server started without labels; decode_top unavailable")
        out = np.asarray(self.pi.output(x))
        with self._served_lock:
            self._served += x.shape[0]
        resp = {"outputs": out.tolist()}
        if top > 0:
            resp["decoded"] = [
                [{"class": c, "wnid": w, "label": l, "probability": p}
                 for (c, w, l, p) in row]
                for row in self.labels.decode_predictions(out, top=top)]
        return resp

    def _status_facts(self) -> dict:
        facts = {
            "model": type(self.pi.net).__name__,
            "inference_mode": self.pi.mode,
            "batch_limit": self.pi.batch_limit,
            "served": self._served,
            "queue_depth": self.pi.queue_depth(),
            "healthy": self.pi.healthy,
            "ready": self._ready and self.pi.healthy,
            "has_labels": self.labels is not None}
        # pipelined data-plane + compile-once guard facts: bucket
        # warmup, trace/recompile counters, adaptive-wait state
        facts["pipeline"] = self.pi.stats()
        trace = self.pi.trace_stats()
        facts["trace_counts"] = trace.get("trace_counts", {})
        facts["total_traces"] = trace.get("total_traces", 0)
        # telemetry facts (observability/): uptime + the registry's
        # monotonic request/error counters (process-wide, survive
        # across this server's construction), plus span-buffer facts
        # when a tracer is attached
        reg = get_registry()
        facts["uptime_s"] = round(time.monotonic() - self._t0, 3)
        facts["requests_total"] = int(reg.counter_value(
            "dl4j_serving_requests_total"))
        facts["errors_total"] = int(reg.counter_value(
            "dl4j_serving_errors_total"))
        facts["telemetry"] = {
            "enabled": _obs.telemetry_enabled(),
            "dropped_emissions": reg.dropped,
            "spans": (self.tracer.stats()
                      if self.tracer is not None else None),
        }
        return facts

    def _metrics_text(self) -> str:
        """The GET /metrics body: refresh the pull-style gauges from
        the live front-end, then render the whole registry."""
        _obs.set_gauge("dl4j_serving_queue_depth",
                       self.pi.queue_depth())
        trace = self.pi.trace_stats()
        _obs.set_gauge("dl4j_jit_traces_total",
                       trace.get("total_traces", 0))
        return get_registry().prometheus_text()

    # --------------------------------------------------------------- start
    def start(self) -> "ModelServer":
        import http.server
        import socketserver

        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _send(self, code, obj, headers=()):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _send_text(self, code, text, content_type):
                body = text.encode()
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_error(self, code, exc, headers=()):
                _obs.count("dl4j_serving_errors_total",
                           labels={"code": str(code)})
                self._send(code, {"error": str(exc),
                                  "error_class": type(exc).__name__},
                           headers)

            def do_GET(self):
                path = self.path.rstrip("/")
                if path == "/status":
                    self._send(200, server._status_facts())
                elif path == "/metrics":
                    # Prometheus text exposition (scrape target)
                    self._send_text(
                        200, server._metrics_text(),
                        "text/plain; version=0.0.4; charset=utf-8")
                elif path == "/healthz":
                    if server.pi.healthy:
                        self._send(200, {"status": "ok"})
                    else:
                        self._send(503, {"status": "unhealthy",
                                         "healthy": False},
                                   [("Retry-After", "1")])
                elif path == "/readyz":
                    if server._ready and server.pi.healthy:
                        self._send(200, {"status": "ready"})
                    else:
                        self._send(503, {"status": "not ready"},
                                   [("Retry-After", "1")])
                else:
                    self._send(404, {"error": f"no route {self.path}",
                                     "error_class": "NotFound"})

            def do_POST(self):
                path = self.path.rstrip("/")
                if path != "/predict":
                    self._send(404, {"error": f"no route {self.path}",
                                     "error_class": "NotFound"})
                    return
                _obs.count("dl4j_serving_requests_total")
                t0 = time.perf_counter()
                try:
                    _fire("serve.request")
                    n = int(self.headers.get("Content-Length", 0))
                    try:
                        req = json.loads(self.rfile.read(n).decode())
                    except ValueError as e:
                        raise _ClientError(f"malformed JSON body: {e}") \
                            from None
                    if not isinstance(req, dict):
                        raise _ClientError("body must be a JSON object")
                    resp = server._handle_predict(req)
                    _obs.observe("dl4j_serving_request_seconds",
                                 time.perf_counter() - t0)
                    self._send(200, resp)
                except _ClientError as e:
                    self._send_error(400, e)
                except _UNAVAILABLE as e:
                    retry_after = getattr(e, "retry_after_s", 1.0) or 1.0
                    self._send_error(
                        503, e,
                        [("Retry-After", f"{max(1, int(retry_after))}")])
                except Exception as e:   # noqa: BLE001 - HTTP boundary
                    self._send_error(500, e)

            def log_message(self, *a):
                pass

        class _Server(socketserver.ThreadingMixIn, http.server.HTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._httpd = _Server((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        self._ready = True
        return self

    def stop(self):
        self._ready = False   # flip /readyz before tearing anything down
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._owns_pi:   # never kill a caller-supplied front-end
            self.pi.shutdown()


_DEFAULT_BREAKER = object()   # sentinel: "construct the default breaker"


class ModelClient:
    """Client for ModelServer (the serve-route consumer).

    HTTP errors surface as typed ServingError carrying the status code
    and the server's JSON {error, error_class} payload (no more
    swallowed bodies). Idempotent calls (/predict, /status, probes)
    retry on connection errors and 503 per `retry` — pass
    `retry=Retry(max_attempts=1)` to disable.

    A CircuitBreaker guards every request BY DEFAULT: repeated
    unavailability (503s, connection errors, retry exhaustion) opens
    the circuit and subsequent calls fail fast with CircuitOpenError —
    letting a drowning server breathe instead of hammering it — until
    the cooldown lets one probe through (half-open). Any response from
    the server, even a 4xx/500, proves liveness and closes the circuit.
    Pass `breaker=None` to disable, or your own CircuitBreaker to tune
    thresholds. Health probes (`healthz`/`readyz`) bypass the breaker:
    a probe must see the instantaneous truth."""

    def __init__(self, url: str, timeout: float = 30.0,
                 retry: Optional[Retry] = None,
                 breaker=_DEFAULT_BREAKER):
        self.url = url.rstrip("/")
        self.timeout = timeout
        self.retry = retry if retry is not None else Retry(
            max_attempts=3, initial_backoff_s=0.05, max_backoff_s=1.0,
            retryable=self._retryable)
        self.breaker: Optional[CircuitBreaker] = (
            CircuitBreaker(failure_threshold=5, reset_timeout_s=5.0)
            if breaker is _DEFAULT_BREAKER else breaker)

    @staticmethod
    def _retryable(exc: Exception) -> bool:
        if isinstance(exc, ServingError):
            return exc.retryable
        return isinstance(exc, (ConnectionError, OSError, TimeoutError))

    @staticmethod
    def _breaker_counted(exc: Exception) -> bool:
        """Failures that indicate an UNAVAILABLE dependency (and should
        trip the breaker) vs. responses that merely report an error."""
        if isinstance(exc, ServingError):
            return exc.retryable         # 503/429: back off
        if isinstance(exc, RetriesExhaustedError):
            return True
        return isinstance(exc, (ConnectionError, OSError, TimeoutError))

    def _call_guarded(self, fn):
        """Run `fn` under the circuit breaker (when enabled). Counted
        failures open it; any server response — success OR typed
        4xx/500 error — records success (the dependency is alive)."""
        if self.breaker is None:
            return fn()

        def _probe_once():
            try:
                return True, fn(), None
            except Exception as e:   # noqa: BLE001 - breaker boundary
                if self._breaker_counted(e):
                    raise             # breaker records the failure
                return False, None, e  # alive: breaker records success

        ok, result, exc = self.breaker.call(_probe_once)
        if not ok:
            raise exc
        return result

    def _request(self, route: str, payload: Optional[dict] = None) -> dict:
        import urllib.error
        import urllib.request

        def _once():
            data = (json.dumps(payload).encode()
                    if payload is not None else None)
            req = urllib.request.Request(
                self.url + route, data=data,
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req,
                                            timeout=self.timeout) as r:
                    return json.loads(r.read().decode())
            except urllib.error.HTTPError as e:
                raise self._serving_error(e) from None

        return self._call_guarded(lambda: self.retry.call(_once))

    @staticmethod
    def _serving_error(e) -> ServingError:
        """Parse the server's JSON error payload out of an HTTPError."""
        try:
            body = json.loads(e.read().decode())
        except Exception:   # noqa: BLE001 - body may be anything
            body = {}
        retry_after = e.headers.get("Retry-After") if e.headers else None
        return ServingError(
            status=e.code,
            message=body.get("error", str(e)),
            error_class=body.get("error_class", ""),
            body=body,
            retry_after_s=float(retry_after) if retry_after else None)

    def _post(self, route: str, payload: dict) -> dict:
        return self._request(route, payload)

    def predict(self, inputs, decode_top: int = 0) -> dict:
        payload = {"inputs": np.asarray(inputs).tolist()}
        if decode_top:
            payload["decode_top"] = decode_top
        return self._request("/predict", payload)

    def status(self) -> dict:
        return self._request("/status")

    def metrics(self) -> dict:
        """GET /metrics parsed into {sample_name[{labels}]: value} —
        the test-friendly view of the Prometheus exposition (raw text
        via `metrics_text()`)."""
        return parse_prometheus(self.metrics_text())

    def metrics_text(self) -> str:
        import urllib.error
        import urllib.request

        req = urllib.request.Request(self.url + "/metrics")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return r.read().decode()
        except urllib.error.HTTPError as e:
            raise self._serving_error(e) from None

    def healthz(self) -> bool:
        """True iff the server reports itself live (no retry — a probe
        must see the instantaneous truth)."""
        try:
            self._probe("/healthz")
            return True
        except ServingError as e:
            if e.status == 503:
                return False
            raise

    def readyz(self) -> bool:
        try:
            self._probe("/readyz")
            return True
        except ServingError as e:
            if e.status == 503:
                return False
            raise

    def _probe(self, route: str) -> dict:
        import urllib.error
        import urllib.request

        req = urllib.request.Request(self.url + route)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return json.loads(r.read().decode())
        except urllib.error.HTTPError as e:
            raise self._serving_error(e) from None
