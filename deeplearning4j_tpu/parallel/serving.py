"""Network-facing model serving over ParallelInference.

Parity: dl4j-streaming's Camel serve route
(streaming/routes/DL4jServeRouteBuilder.java — accept a record over
the wire, run `model.output`, hand the result to a post-processor) and
the ModelServer role around ParallelInference. Kafka/Camel transports
stay out of scope (VERDICT r4); the serving surface itself is plain
HTTP+JSON like the nearest-neighbor microservice
(clustering/server.py), so the round-trip is testable anywhere.

Routes:
  POST /predict  {"inputs": [[...], ...]}          -> {"outputs": [...]}
  POST /predict  {"inputs": ..., "decode_top": 5}  -> adds "decoded"
                 (requires an ImageNetLabels source; zoo/util/imagenet)
  GET  /status   -> model + queue facts

Requests are funneled through ParallelInference in BATCHED mode, so
concurrent small clients coalesce into full MXU tiles (the reference's
BatchedInferenceObservable role).
"""

from __future__ import annotations

import json
import threading
from typing import Optional

import numpy as np

from deeplearning4j_tpu.parallel.inference import (
    InferenceMode,
    ParallelInference,
)


class ModelServer:
    """Serve a trained MultiLayerNetwork/ComputationGraph over HTTP.

    `labels` (optional ImageNetLabels) enables decoded top-k responses
    — the user-facing half of the zoo (`decode_predictions`)."""

    def __init__(self, net, port: int = 0, host: str = "127.0.0.1",
                 inference_mode: str = InferenceMode.BATCHED,
                 batch_limit: int = 32, labels=None,
                 output_activation: bool = True):
        self._owns_pi = not isinstance(net, ParallelInference)
        self.pi = (net if not self._owns_pi
                   else ParallelInference(net, inference_mode,
                                          batch_limit=batch_limit))
        self.labels = labels
        self.host = host
        self.port = port
        self._httpd = None
        self._thread: Optional[threading.Thread] = None
        self._served = 0
        self._served_lock = threading.Lock()

    def start(self) -> "ModelServer":
        import http.server
        import socketserver

        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _send(self, code, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path.rstrip("/") == "/status":
                    self._send(200, {
                        "model": type(server.pi.net).__name__,
                        "inference_mode": server.pi.mode,
                        "batch_limit": server.pi.batch_limit,
                        "served": server._served,
                        "has_labels": server.labels is not None})
                else:
                    self._send(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                try:
                    if self.path.rstrip("/") != "/predict":
                        raise ValueError(f"no route {self.path}")
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n).decode())
                    x = np.asarray(req["inputs"], np.float32)
                    if req.get("single", False):
                        x = x[None, ...]   # one unbatched example
                    out = np.asarray(server.pi.output(x))
                    with server._served_lock:
                        server._served += x.shape[0]
                    resp = {"outputs": out.tolist()}
                    top = int(req.get("decode_top", 0))
                    if top > 0:
                        if server.labels is None:
                            raise ValueError(
                                "server started without labels; "
                                "decode_top unavailable")
                        resp["decoded"] = [
                            [{"class": c, "wnid": w, "label": l,
                              "probability": p}
                             for (c, w, l, p) in row]
                            for row in server.labels.decode_predictions(
                                out, top=top)]
                    self._send(200, resp)
                except Exception as e:   # noqa: BLE001 - HTTP boundary
                    self._send(400, {"error": str(e)})

            def log_message(self, *a):
                pass

        class _Server(socketserver.ThreadingMixIn, http.server.HTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._httpd = _Server((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._owns_pi:   # never kill a caller-supplied front-end
            self.pi.shutdown()


class ModelClient:
    """Minimal client for ModelServer (the serve-route consumer)."""

    def __init__(self, url: str, timeout: float = 30.0):
        self.url = url.rstrip("/")
        self.timeout = timeout

    def _post(self, route: str, payload: dict) -> dict:
        import urllib.request

        req = urllib.request.Request(
            self.url + route, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read().decode())

    def predict(self, inputs, decode_top: int = 0) -> dict:
        payload = {"inputs": np.asarray(inputs).tolist()}
        if decode_top:
            payload["decode_top"] = decode_top
        return self._post("/predict", payload)

    def status(self) -> dict:
        import urllib.request

        with urllib.request.urlopen(self.url + "/status",
                                    timeout=self.timeout) as r:
            return json.loads(r.read().decode())
