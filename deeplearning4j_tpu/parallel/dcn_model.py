"""DCN scaling model: when does synchronous training stop scaling, and
which knob restores it.

Round-3's verdict accepted replacing the reference's async parameter
server (SharedTrainingMaster.java:72) with synchronous SPMD
collectives "only while single-slice sync scaling stays efficient —
nothing in-repo measures when sync-over-DCN stops scaling". This
module is that measurement: an analytical ring-all-reduce cost model
(the standard alpha-beta model, the same arithmetic the scaling
playbooks use) evaluated against a measured single-slice step time,
comparing the four strategies this package implements:

- sync: per-step gradient all-reduce over DCN (TrainingMaster default)
- local_sgd(k): one parameter average every k steps
  (averaging_frequency=k)
- local_sgd(k) + threshold compression: the k-step delta shrinks by
  the measured wire ratio (threshold_compression=t; feed
  LocalStepTrainer.wire_stats()['compression_ratio'])
- stale: 1-step-delayed application (StaleGradientTrainer) — the
  exchange overlaps the next step's compute, costing only what
  exceeds one step time

All times in milliseconds, sizes in bytes, bandwidth in GB/s.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional


@dataclass
class DcnLink:
    """Cross-slice interconnect spec. Defaults are a typical
    data-center NIC: 25 gigaBYTES/s effective per host, ~0.1 ms
    latency. (Field is GB/s, not Gbps — divide a NIC's line rate in
    gigabits by 8.)"""

    bandwidth_GBps: float = 25.0    # gigaBYTES per second
    latency_ms: float = 0.1


def allreduce_ms(nbytes: float, n_slices: int, link: DcnLink) -> float:
    """Ring all-reduce cost (alpha-beta model): 2(n-1)/n * bytes / BW
    + 2(n-1) * alpha."""
    if n_slices <= 1:
        return 0.0
    bw = link.bandwidth_GBps * 1e9 / 1e3          # bytes per ms
    return (2.0 * (n_slices - 1) / n_slices * nbytes / bw
            + 2.0 * (n_slices - 1) * link.latency_ms)


def efficiency(step_ms: float, exchange_ms: float,
               period_steps: int = 1, overlap_ms: float = 0.0) -> float:
    """Fraction of wall time spent computing: period_steps of compute
    against one exchange, of which overlap_ms hides under compute."""
    exposed = max(exchange_ms - overlap_ms, 0.0)
    compute = step_ms * period_steps
    return compute / (compute + exposed)


def crossover_report(param_bytes: float, step_ms: float,
                     n_slices: int, link: Optional[DcnLink] = None,
                     k: int = 8,
                     compression_ratio: float = 0.25,
                     target_efficiency: float = 0.9) -> Dict:
    """Evaluate the four strategies at one operating point and find the
    smallest local-SGD k that reaches `target_efficiency`.

    `compression_ratio` should come from a measured
    LocalStepTrainer.wire_stats()['compression_ratio'].
    """
    link = link or DcnLink()
    ex = allreduce_ms(param_bytes, n_slices, link)

    sync_eff = efficiency(step_ms, ex)
    local_eff = efficiency(step_ms, ex, period_steps=k)
    comp_eff = efficiency(
        step_ms,
        allreduce_ms(param_bytes * compression_ratio, n_slices, link),
        period_steps=k)
    stale_eff = efficiency(step_ms, ex, overlap_ms=step_ms)

    k_needed = 1
    while (efficiency(step_ms, ex, period_steps=k_needed)
           < target_efficiency and k_needed < 4096):
        k_needed += 1
    target_reachable = (efficiency(step_ms, ex, period_steps=k_needed)
                        >= target_efficiency)

    return {
        "exchange_ms": ex,
        "step_ms": step_ms,
        "n_slices": n_slices,
        "sync_efficiency": sync_eff,
        "sync_scales": sync_eff >= target_efficiency,
        "local_sgd_k": k,
        "local_sgd_efficiency": local_eff,
        "local_sgd_compressed_efficiency": comp_eff,
        "stale_overlap_efficiency": stale_eff,
        "k_for_target": k_needed if target_reachable else None,
        "target_reachable": target_reachable,
        "target_efficiency": target_efficiency,
    }


def sweep(param_bytes: float, step_ms: float, slice_counts,
          link: Optional[DcnLink] = None, **kw):
    """crossover_report at several slice counts — the scaling curve.
    The first entry with sync_scales == False is the crossover."""
    return [crossover_report(param_bytes, step_ms, n, link, **kw)
            for n in slice_counts]
