"""Sharding rules: map a network's param/batch pytrees onto mesh axes.

The reference has no notion of parameter sharding (params are replicated
per device thread, ParallelWrapper.java:122); tensor parallelism here is a
new first-class capability. Rules are deliberately simple and GSPMD-
friendly: annotate the big matmul weights, let XLA propagate the rest.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, ndim: int = None, axis: str = "dp",
                   seq_axis: Optional[str] = None) -> NamedSharding:
    """Shard the leading (batch) dim over `axis`; optionally the second
    (time) dim over `seq_axis` for sequence parallelism."""
    spec = [axis]
    if seq_axis is not None:
        spec.append(seq_axis)
    return NamedSharding(mesh, P(*spec))


def _tp_spec(path_str: str, leaf, mesh: Mesh, tp_axis: str) -> P:
    """Tensor-parallel partition rule for one param leaf.

    Megatron-style: shard the output-features dim of weight matrices over
    tp when divisible; biases/gains follow their matrix's output dim;
    scalars and small vectors replicate. Conv kernels [kh,kw,cin,cout]
    shard cout. Embedding tables [vocab, dim] shard vocab (row-sharded so
    lookups psum).
    """
    tp = mesh.shape[tp_axis]
    if tp == 1 or leaf.ndim == 0:
        return P()
    shape = leaf.shape
    if leaf.ndim >= 2:
        # weight-like: shard the trailing (out-features) dim
        if shape[-1] % tp == 0:
            return P(*([None] * (leaf.ndim - 1) + [tp_axis]))
        if shape[0] % tp == 0:
            return P(*([tp_axis] + [None] * (leaf.ndim - 1)))
        return P()
    # 1-D: bias/gamma/beta — shard if divisible (matches out-dim sharding)
    if shape[0] % tp == 0 and shape[0] >= tp * 8:
        return P(tp_axis)
    return P()


def param_shardings(mesh: Mesh, params: Any, tp_axis: str = "tp") -> Any:
    """NamedSharding pytree for a params pytree under the tp rule."""
    def rule(path, leaf):
        pstr = jax.tree_util.keystr(path)
        return NamedSharding(mesh, _tp_spec(pstr, leaf, mesh, tp_axis))
    return jax.tree_util.tree_map_with_path(rule, params)


def shard_params(mesh: Mesh, params: Any, tp_axis: str = "tp") -> Any:
    """device_put a params pytree with the tp rule applied."""
    shardings = param_shardings(mesh, params, tp_axis)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(jnp.asarray(x), s), params, shardings)


def shard_batch(mesh: Mesh, batch: Any, axis: str = "dp",
                seq_axis: Optional[str] = None) -> Any:
    """device_put batch arrays sharded over the dp (and optionally sp) axis."""
    def put(x):
        if x is None:
            return None
        x = jnp.asarray(x)
        spec = [axis] + ([seq_axis] if seq_axis and x.ndim > 1 else [])
        spec = spec[: x.ndim]
        return jax.device_put(x, NamedSharding(mesh, P(*spec)))
    return jax.tree_util.tree_map(put, batch)
