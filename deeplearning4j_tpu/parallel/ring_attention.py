"""Ring attention: sequence-parallel exact attention over the `sp` mesh
axis.

No reference counterpart — the reference's only long-sequence mechanism
is truncated BPTT (SURVEY §5.7); this is the first-class TPU-native
long-context component the survey calls for: the sequence axis is
sharded over `sp`, each shard holds its Q/K/V block, and K/V blocks
rotate around the ring via `lax.ppermute` (one ICI hop per step) while
each shard folds the incoming block into a numerically-stable online
softmax (the blockwise/flash formulation). Peak memory per chip is
O(T_local^2) instead of O(T^2), and the N-1 permutes overlap with the
block matmuls under XLA's scheduler.

Entry points:
- ring_self_attention(q, k, v, mesh, ...): global [B, T, H, D] arrays
  (T divisible by sp); shards, runs the ring, returns global output.
- _ring_attention_block: the per-shard body, usable inside a larger
  shard_map'd step.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _ring_attention_block(q, k, v, *, axis_name: str, causal: bool,
                          scale: float):
    """Per-shard ring attention. q/k/v: [B, Tl, H, D] local blocks.

    Online-softmax accumulation per incoming K/V block; K/V rotate
    shard i -> shard (i+1) % n each step, so after t steps shard i
    holds the block that originated at shard (i - t) mod n."""
    n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    B, Tl, H, D = q.shape
    q_ = jnp.swapaxes(q, 1, 2)          # [B, H, Tq, D]
    neg = jnp.finfo(jnp.float32).min

    def fold(carry, t):
        m_prev, l_prev, o_prev, k_cur, v_cur = carry
        origin = (my - t) % n            # which shard this K/V came from
        k_ = jnp.swapaxes(k_cur, 1, 2)   # [B, H, Tk, D]
        v_ = jnp.swapaxes(v_cur, 1, 2)
        s = jnp.einsum("bhqd,bhkd->bhqk", q_, k_,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = my * Tl + jnp.arange(Tl)          # global q indices
            k_pos = origin * Tl + jnp.arange(Tl)      # global k indices
            mask = q_pos[:, None] >= k_pos[None, :]   # [Tq, Tk]
            s = jnp.where(mask[None, None], s, neg)
        m_blk = jnp.max(s, axis=-1)                   # [B,H,Tq]
        m_new = jnp.maximum(m_prev, m_blk)
        # fully-masked rows keep m = -inf; guard the exp shift
        shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - shift[..., None])
        if causal:
            p = jnp.where(mask[None, None], p, 0.0)
        corr = jnp.where(jnp.isfinite(m_prev),
                         jnp.exp(m_prev - shift), 0.0)
        l_new = l_prev * corr + jnp.sum(p, axis=-1)
        o_new = (o_prev * corr[..., None]
                 + jnp.einsum("bhqk,bhkd->bhqd", p,
                              v_.astype(jnp.float32)))
        # rotate K/V one hop around the ring (skip after the last fold)
        k_nxt, v_nxt = jax.lax.cond(
            t < n - 1,
            lambda kv: jax.lax.ppermute(
                kv, axis_name,
                perm=[(i, (i + 1) % n) for i in range(n)]),
            lambda kv: kv,
            (k_cur, v_cur))
        return (m_new, l_new, o_new, k_nxt, v_nxt), None

    m0 = jnp.full((B, H, Tl), neg, jnp.float32)
    l0 = jnp.zeros((B, H, Tl), jnp.float32)
    o0 = jnp.zeros((B, H, Tl, D), jnp.float32)
    (m, l, o, _, _), _ = jax.lax.scan(
        fold, (m0, l0, o0, k, v), jnp.arange(n))
    out = o / jnp.maximum(l[..., None], 1e-30)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)   # [B, Tq, H, D]


def ring_self_attention(q, k, v, mesh: Mesh, *, axis_name: str = "sp",
                        causal: bool = False,
                        scale: Optional[float] = None):
    """Exact multi-head attention with the sequence dim sharded over
    `axis_name`. q/k/v: [B, T, H, D] with T % mesh.shape[axis_name] == 0.
    Matches dense softmax(QK^T/sqrt(D))V to float32 accuracy."""
    if axis_name not in mesh.shape:
        raise ValueError(f"mesh has no axis '{axis_name}' "
                         f"(axes: {dict(mesh.shape)})")
    n = mesh.shape[axis_name]
    B, T, H, D = q.shape
    if T % n:
        raise ValueError(f"sequence length {T} not divisible by "
                         f"{axis_name}={n}")
    if scale is None:
        scale = 1.0 / float(np.sqrt(D))
    spec = P(None, axis_name, None, None)
    fn = jax.jit(jax.shard_map(
        partial(_ring_attention_block, axis_name=axis_name,
                causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False))
    sh = NamedSharding(mesh, spec)
    put = lambda a: jax.device_put(a, sh)
    return fn(put(q), put(k), put(v))
