"""ParallelWrapper: data-parallel training over a device mesh.

Parity: deeplearning4j-scaleout-parallelwrapper/.../ParallelWrapper.java:54
(fit loop :211-260, param averaging via Nd4j.averageAndPropagate :320,
updater-state averaging :332-365) and its SHARED_GRADIENTS mode (:60-64).

TPU-native design: the reference spawns one trainer thread + model replica
per device and periodically averages parameters over PCIe. Here the
"replicas" are one jit-compiled step over a `Mesh` whose dp axis shards
the batch; the gradient all-reduce is inserted by XLA (GSPMD) because the
loss is a mean over the globally-sharded batch while params are
replicated — it rides ICI and is fused into the step. Both reference
modes collapse to this:

- SHARED_GRADIENTS (per-step gradient exchange) == the default here.
  Threshold compression (EncodingHandler.java:64) is unnecessary on ICI.
- AVERAGING every k steps (local SGD) == `averaging_frequency=k`, done
  with an explicit shard_map: each dp group keeps private params for k
  local steps, then `pmean`s params + updater state (the reference's
  averageUpdatersState, ParallelWrapper.java:332-365).

Tensor parallelism (`tp` mesh axis > 1) shards weight matrices per
sharding.py rules — a capability with no reference counterpart.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.sharding import (
    param_shardings,
    shard_batch,
)


class ParallelWrapper:
    """Data/tensor-parallel trainer around a MultiLayerNetwork/ComputationGraph.

    Usage (mirrors the reference Builder):
        pw = ParallelWrapper(net, workers=8)           # dp=8
        pw = ParallelWrapper(net, workers=4, tp=2)     # dp=4 x tp=2
        pw.fit(iterator)
    """

    def __init__(self, net, workers: Optional[int] = None, tp: int = 1,
                 averaging_frequency: int = 1, average_updaters: bool = True,
                 mesh: Optional[Mesh] = None, prefetch_buffer: int = 2):
        self.net = net
        if mesh is None:
            n = len(jax.devices())
            workers = workers if workers is not None else max(1, n // tp)
            mesh = make_mesh(dp=workers, tp=tp)
        self.mesh = mesh
        self.dp = mesh.shape["dp"]
        self.averaging_frequency = max(1, averaging_frequency)
        self.average_updaters = average_updaters
        self.prefetch_buffer = prefetch_buffer
        self._sharded = False
        self._local_step = None

    # ------------------------------------------------------------------
    def _ensure_sharded(self):
        """Place the net's params/updater state onto the mesh (replicated
        over dp, tp-sharded per rules)."""
        if self._sharded:
            return
        ins = getattr(self.net.conf, "network_inputs", None)
        outs = getattr(self.net.conf, "network_outputs", None)
        if ins is not None and (len(ins) > 1 or len(outs) > 1):
            raise NotImplementedError(
                "ParallelWrapper currently supports single-input/single-"
                "output graphs; shard multi-input batches manually via "
                "parallel.sharding.shard_batch + the graph's _train_step")
        if self.net.params is None:
            self.net.init()
        put = lambda tree: jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, s),
            tree, param_shardings(self.mesh, tree))
        self.net.params = put(self.net.params)
        self.net.updater_states = put(self.net.updater_states)
        self.net.states = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, NamedSharding(self.mesh, P())),
            self.net.states)
        self._sharded = True

    def _pad_batch(self, x):
        """Pad the batch dim up to a multiple of dp (static shapes for XLA).

        Returns (padded, pad_count). Label masks handle the padding rows'
        contribution (they're zero-masked)."""
        b = x.shape[0]
        rem = (-b) % self.dp
        if rem == 0:
            return x, 0
        pad = np.zeros((rem,) + tuple(x.shape[1:]), x.dtype)
        return np.concatenate([np.asarray(x), pad], axis=0), rem

    # ------------------------------------------------------------------
    def fit(self, data, epochs: int = 1):
        """Train. `data` is any iterator/list of batches the wrapped net
        accepts (ref fit loop: ParallelWrapper.java:211-260)."""
        self._ensure_sharded()
        net = self.net
        batches = data if hasattr(data, "__iter__") else [data]
        with self.mesh:
            for _ in range(epochs):
                if hasattr(batches, "reset"):
                    batches.reset()
                for batch in batches:
                    x, y, fm, lm = _as_batch(batch)
                    x, npad = self._pad_batch(np.asarray(x))
                    if npad:
                        y2 = np.asarray(y)
                        ypad = np.zeros((npad,) + y2.shape[1:], y2.dtype)
                        y = np.concatenate([y2, ypad], 0)
                        # mask padding rows out of the loss
                        if lm is None:
                            lm = np.ones(
                                (x.shape[0],) if y2.ndim == 2
                                else (x.shape[0], y2.shape[1]), np.float32)
                            lm[-npad:] = 0.0
                        else:
                            lm2 = np.asarray(lm)
                            lm = np.concatenate(
                                [lm2, np.zeros((npad,) + lm2.shape[1:],
                                               lm2.dtype)], 0)
                        if fm is not None:
                            fm2 = np.asarray(fm)
                            fm = np.concatenate(
                                [fm2, np.zeros((npad,) + fm2.shape[1:],
                                               fm2.dtype)], 0)
                    xb = shard_batch(self.mesh, jnp.asarray(x, net.dtype))
                    yb = shard_batch(self.mesh, jnp.asarray(y, net.dtype))
                    fmb = (None if fm is None
                           else shard_batch(self.mesh, jnp.asarray(fm)))
                    lmb = (None if lm is None
                           else shard_batch(self.mesh, jnp.asarray(lm)))
                    if hasattr(net.conf, "network_inputs"):
                        # ComputationGraph: dict inputs / list labels
                        name = net.conf.network_inputs[0]
                        net._train_step(
                            {name: xb}, [yb],
                            None if fmb is None else {name: fmb},
                            None if lmb is None else [lmb])
                    else:
                        net._train_step(xb, yb, fmb, lmb)
                    for listener in net.listeners:
                        listener.iteration_done(net, net.iteration)
                net.epoch += 1
        return self

    # ------------------------------------------------------------------
    def average_params(self):
        """Explicit parameter averaging over dp — the K-step local-SGD
        rendezvous (ref: Nd4j.averageAndPropagate, ParallelWrapper.java:320).
        With the default per-step all-reduce params never diverge, so this
        is a no-op unless local stepping is used."""
        return self

    def output(self, x):
        self._ensure_sharded()
        with self.mesh:
            return self.net.output(shard_batch(self.mesh, jnp.asarray(x)))


def _as_batch(batch):
    from deeplearning4j_tpu.nn.multilayer import _as_batch as f
    return f(batch)


class LocalStepTrainer:
    """True `averagingFrequency=k` local-SGD semantics via shard_map:
    each dp shard carries its own params for k local steps, then params
    (and optionally updater state) are pmean'd over dp — bit-for-bit the
    reference's AVERAGING mode (ParallelWrapper.java:320,332-365), but as
    one compiled program.

    This trades gradient freshness for k× fewer collectives; on ICI the
    per-step all-reduce is nearly free, so this exists for semantic parity
    and for DCN-spanning meshes where collectives are expensive.
    """

    def __init__(self, loss_fn, updater, mesh: Mesh, k: int,
                 average_updaters: bool = True):
        self.loss_fn = loss_fn      # (params, x, y) -> scalar loss
        self.updater = updater      # obj with update(grads, state, params, lr, step)
        self.mesh = mesh
        self.k = k
        self.average_updaters = average_updaters

    def build(self):
        from jax.experimental.shard_map import shard_map
        mesh, k, loss_fn, updater = self.mesh, self.k, self.loss_fn, self.updater
        avg_upd = self.average_updaters

        def worker(params, upd_state, step, xs, ys, lr):
            # xs: [k, local_batch, ...] — k local steps on this shard's data
            def one(carry, xy):
                p, us, s = carry
                x, y = xy
                loss, g = jax.value_and_grad(loss_fn)(p, x, y)
                deltas, us = updater.update(g, us, p, lr, s)
                p = jax.tree_util.tree_map(lambda a, d: a + d, p, deltas)
                return (p, us, s + 1), loss
            (params, upd_state, _), losses = jax.lax.scan(
                one, (params, upd_state, step), (xs, ys))
            # rendezvous: average params (+ updater state) over dp
            params = jax.tree_util.tree_map(
                lambda a: jax.lax.pmean(a, "dp"), params)
            if avg_upd:
                upd_state = jax.tree_util.tree_map(
                    lambda a: jax.lax.pmean(a, "dp"), upd_state)
            return params, upd_state, jax.lax.pmean(jnp.mean(losses), "dp")

        pspec = P()          # params replicated at entry/exit
        xspec = P(None, "dp")  # [k, batch, ...] batch dim sharded
        return jax.jit(shard_map(
            worker, mesh=mesh,
            in_specs=(pspec, pspec, P(), xspec, xspec, P()),
            out_specs=(pspec, pspec, P()),
            check_rep=False))
