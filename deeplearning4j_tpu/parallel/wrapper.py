"""ParallelWrapper: data-parallel training over a device mesh.

Parity: deeplearning4j-scaleout-parallelwrapper/.../ParallelWrapper.java:54
(fit loop :211-260, param averaging via Nd4j.averageAndPropagate :320,
updater-state averaging :332-365) and its SHARED_GRADIENTS mode (:60-64).

TPU-native design: the reference spawns one trainer thread + model replica
per device and periodically averages parameters over PCIe. Here the
"replicas" are one jit-compiled step over a `Mesh` whose dp axis shards
the batch; the gradient all-reduce is inserted by XLA (GSPMD) because the
loss is a mean over the globally-sharded batch while params are
replicated — it rides ICI and is fused into the step. Both reference
modes collapse to this:

- SHARED_GRADIENTS (per-step gradient exchange) == the default here.
  Threshold compression (EncodingHandler.java:64) is unnecessary on ICI.
- AVERAGING every k steps (local SGD) == `averaging_frequency=k`, done
  with an explicit shard_map: each dp group keeps private params for k
  local steps, then `pmean`s params + updater state (the reference's
  averageUpdatersState, ParallelWrapper.java:332-365).

Tensor parallelism (`tp` mesh axis > 1) shards weight matrices per
sharding.py rules — a capability with no reference counterpart.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.engine import StepHarness, make_loss_and_apply
from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.sharding import (
    param_shardings,
    shard_batch,
)


def _require_local_sgd(averaging_frequency: int, threshold: float):
    """Shared validation: threshold compression only exists at the
    local-SGD rendezvous."""
    if threshold > 0.0 and max(1, averaging_frequency) <= 1:
        raise ValueError(
            "threshold_compression requires averaging_frequency > 1 "
            "(it encodes the k-step delta at the local-SGD rendezvous; "
            "the per-step GSPMD all-reduce path has no host-visible "
            "exchange to encode)")


def _disable_flat_chain(net):
    """The grad-over-flat carry (updater/flat_chain.py) concatenates
    every parameter into ONE flat vector — under a tp-sharded or
    GSPMD-driven net that forces a full all-gather of the model each
    step (it deadlocked the virtual-mesh dryrun); mesh-driven training
    always uses the per-layer tree path."""
    if hasattr(net, "_flat_chain"):
        net._materialize_flat()
        net._flat_chain = None


class ParallelWrapper:
    """Data/tensor-parallel trainer around a MultiLayerNetwork/ComputationGraph.

    Usage (mirrors the reference Builder):
        pw = ParallelWrapper(net, workers=8)           # dp=8
        pw = ParallelWrapper(net, workers=4, tp=2)     # dp=4 x tp=2
        pw.fit(iterator)
    """

    def __init__(self, net, workers: Optional[int] = None, tp: int = 1,
                 averaging_frequency: int = 1, average_updaters: bool = True,
                 mesh: Optional[Mesh] = None, prefetch_buffer: int = 2,
                 threshold_compression: float = 0.0,
                 guard=None, watchdog=None, snapshot_every: int = 0,
                 phase_profiler=None,
                 steps_per_dispatch: int = 1,
                 pipeline: Optional[bool] = None,
                 sharding: Optional[str] = None):
        """`guard`/`watchdog` (resilience/supervisor.py) give fit() the
        same self-healing hooks as TrainingMaster: the NonFiniteGuard
        checks loss+params after (sampled) steps and skips or aborts on
        non-finite state; the StepWatchdog heartbeats per batch and
        escalates a hung step/collective. `rollback` policy needs a
        rollback target: pass `snapshot_every=N` and an in-memory
        device snapshot of the pre-step state is refreshed every N
        guarded steps (resilience.PeriodicSnapshotter) — a poisoned
        step rewinds to the newest snapshot, losing at most N-1 good
        steps (no checkpoint directory required)."""
        self.net = net
        self.threshold_compression = float(threshold_compression)
        _require_local_sgd(averaging_frequency,
                           self.threshold_compression)
        self._snapshotter = None
        if guard is not None and guard.policy == "rollback":
            if snapshot_every <= 0:
                raise ValueError(
                    "NonFiniteGuard(policy='rollback') under "
                    "ParallelWrapper needs snapshot_every=N > 0 (an "
                    "in-memory rollback target; TrainingMaster uses "
                    "checkpoints instead)")
            from deeplearning4j_tpu.resilience.supervisor import (
                PeriodicSnapshotter,
            )

            self._snapshotter = PeriodicSnapshotter(
                guard, every=snapshot_every)
        if mesh is None:
            n = len(jax.devices())
            workers = workers if workers is not None else max(1, n // tp)
            mesh = make_mesh(dp=workers, tp=tp)
        self.mesh = mesh
        self.dp = mesh.shape["dp"]
        self.averaging_frequency = max(1, averaging_frequency)
        self.average_updaters = average_updaters
        self.prefetch_buffer = prefetch_buffer
        # `steps_per_dispatch=k > 1`: batches (MASKS INCLUDED — the
        # PR 9 gap that forced fm/lm nets onto the k=1 path) group into
        # k-windows run through the engine's lax.scan group program in
        # ONE dispatch; byte-identical to k sequential steps.
        self.steps_per_dispatch = max(1, int(steps_per_dispatch))
        if self.steps_per_dispatch > 1 and self.averaging_frequency > 1:
            raise ValueError(
                "steps_per_dispatch > 1 and averaging_frequency > 1 "
                "are mutually exclusive groupings (the local-SGD "
                "rendezvous already scans its k steps in one dispatch)")
        # harness-owned input pipeline (engine/pipeline.py): async ETL
        # + device staging ahead of the compute. Default (None): ON for
        # single-process jobs; pipeline=False opts out.
        self.pipeline = pipeline
        self._sharded = False
        self._local_step = None
        # ONE supervisor (engine/): guard-verdict dispatch, watchdog
        # lifecycle, the StepAccumulator per-step telemetry batches
        # through, and the phase profiler (every step funnels through
        # _run_guarded, so dispatch/host_sync phases land there;
        # data_wait/h2d are not visible at this altitude)
        self._harness = StepHarness(
            net, guard=guard, watchdog=watchdog,
            snapshotter=self._snapshotter,
            phase_profiler=phase_profiler)
        self.guard = self._harness.guard
        self.watchdog = self._harness.watchdog
        self._obs_acc = self._harness.acc
        self.phase_profiler = self._harness.phase_profiler
        # ZeRO-1 (engine/sharding.py): optimizer state sharded over
        # this wrapper's dp axis, update reduce-scattered/shard-local/
        # all-gathered inside the one compiled step — byte-identical
        # to the replicated program (pinned in test_mesh.py)
        if sharding not in (None, "replicated", "zero1"):
            raise ValueError(
                f"sharding must be None|'replicated'|'zero1': {sharding}")
        self.zero1 = sharding == "zero1"
        self._mesh_mgr = None
        if self.zero1:
            if self.mesh.shape["tp"] != 1:
                raise NotImplementedError(
                    "sharding='zero1' requires tp == 1 (the ZeRO "
                    "update shards the dp axis of replicated params)")
            if self.averaging_frequency > 1:
                raise ValueError(
                    "sharding='zero1' and averaging_frequency > 1 are "
                    "incompatible (local SGD keeps per-shard params)")
            from deeplearning4j_tpu.engine.mesh import MeshManager

            self._mesh_mgr = MeshManager(mesh=self.mesh)
            self._harness.program.attach_mesh(self._mesh_mgr)

    # ------------------------------------------------------------------
    def _ensure_sharded(self):
        """Place the net's params/updater state onto the mesh (replicated
        over dp, tp-sharded per rules)."""
        if self._sharded:
            return
        ins = getattr(self.net.conf, "network_inputs", None)
        outs = getattr(self.net.conf, "network_outputs", None)
        self._multi_io = ins is not None and (len(ins) > 1 or len(outs) > 1)
        if self._multi_io and self.averaging_frequency > 1:
            raise NotImplementedError(
                "averaging_frequency > 1 supports single-input/single-"
                "output graphs only")
        if self.net.params is None:
            self.net.init()
        _disable_flat_chain(self.net)
        put = lambda tree: jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, s),
            tree, param_shardings(self.mesh, tree))
        self.net.params = put(self.net.params)
        if self._mesh_mgr is not None:
            # ZeRO-1: optimizer state placed SHARDED over dp (1/n per
            # replica) instead of replicated
            self.net.updater_states = self._mesh_mgr.shard_tree(
                jax.tree_util.tree_map(np.asarray,
                                       self.net.updater_states))
        else:
            self.net.updater_states = put(self.net.updater_states)
        self.net.states = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, NamedSharding(self.mesh, P())),
            self.net.states)
        self._sharded = True

    def _pad_batch(self, x):
        b = np.asarray(x).shape[0]
        rem = (-b) % self.dp
        if rem == 0:
            return np.asarray(x), 0
        pad = np.zeros((rem,) + tuple(x.shape[1:]), x.dtype)
        return np.concatenate([np.asarray(x), pad], axis=0), rem

    def _pad_with_masks(self, x, y, fm, lm):
        return _pad_batch_with_masks(self.dp, x, y, fm, lm)

    def _run_guarded(self, thunk) -> bool:
        """Run one training step/group under the shared harness's
        guard dispatch (engine.StepHarness.guarded); False means the
        step was rejected and the pre-step (skip_step) or
        newest-snapshot (rollback) state restored (callers skip
        listeners for rejected steps). Every ParallelWrapper
        step/group funnels through here: the one emission site covers
        single-step, local-SGD, and multi-io paths alike (batched;
        fit() flushes at loop end)."""
        return self._harness.guarded(thunk, context="detected")

    # ------------------------------------------------------------------
    def fit(self, data, epochs: int = 1):
        """Train. `data` is any iterator/list of batches the wrapped net
        accepts (ref fit loop: ParallelWrapper.java:211-260).

        averaging_frequency == 1 (default): one GSPMD step per batch,
        per-step gradient all-reduce (SHARED_GRADIENTS semantics).
        averaging_frequency == k > 1: batches are grouped k at a time and
        run through LocalStepTrainer — each dp shard takes k local SGD
        steps on its own data, then params (+ updater state) are pmean'd
        (AVERAGING semantics, ParallelWrapper.java:320,332-365).
        """
        self._ensure_sharded()
        net = self.net
        batches = data if hasattr(data, "__iter__") else [data]
        k = self.averaging_frequency
        if k > 1 and self._local_step is None:
            self._local_step = LocalStepTrainer(
                net, self.mesh, average_updaters=self.average_updaters,
                threshold=self.threshold_compression)
        # harness-owned input pipeline: AsyncDataSetIterator ->
        # DevicePrefetchIterator staging (pad + dp-shard on the way
        # through), so data_wait/h2d overlap device_compute. The
        # local-SGD and multi-io paths restack on host, so they take
        # the async ETL overlap only (host_only).
        pre_staged = False
        if self._pipeline_enabled():
            # zero1 stages on the consumer thread (host_only): staging
            # batch k+1 while a donated SHARDED-state execution is in
            # flight corrupts the heap in this jaxlib's CPU runtime
            # (reproducibly, only with a warm persistent compile
            # cache); the async-ETL overlap is kept, the device copy
            # moves next to the dispatch
            host_only = (k > 1 or getattr(self, "_multi_io", False)
                         or self.zero1)
            batches = self._harness.build_iterator_pipeline(
                batches, depth=self.prefetch_buffer,
                stage=None if host_only else self._stage_batch,
                host_only=host_only,
                meta={"mesh": dict(self.mesh.shape)})
            pre_staged = not host_only
        else:
            # one shared session lifecycle (engine/): watchdog
            # start/stop, accumulator flush, attached-iterator close
            self._harness.attach_data(batches)
        with self._harness.session():
            self._fit_loop(batches, epochs, k, self.watchdog,
                           pre_staged)
        return self

    def _pipeline_enabled(self) -> bool:
        if self.pipeline is not None:
            return bool(self.pipeline)
        return jax.process_count() == 1

    def _stage_batch(self, batch):
        """Pipeline staging for ONE batch: pad + dp-shard exactly as
        the synchronous loop would, so the consumer receives
        (x, y, fm, lm) device arrays in the same layout and the
        compiled step's byte-level evolution is unchanged."""
        net = self.net
        x, y, fm, lm = self._pad_with_masks(*_as_batch(batch))
        return (shard_batch(self.mesh, jnp.asarray(x, net.dtype)),
                shard_batch(self.mesh, jnp.asarray(y, net.dtype)),
                None if fm is None
                else shard_batch(self.mesh, jnp.asarray(fm)),
                None if lm is None
                else shard_batch(self.mesh, jnp.asarray(lm)))

    def _fit_loop(self, batches, epochs, k, wd, pre_staged=False):
        net = self.net
        k2 = self.steps_per_dispatch
        with self.mesh:
            for _ in range(epochs):
                if hasattr(batches, "reset"):
                    batches.reset()
                group = []      # local-SGD rendezvous window (host)
                window = []     # run_group k-window (staged or host)
                for batch in batches:
                    if wd is not None:
                        wd.beat("batch")
                    if getattr(self, "_multi_io", False):
                        if self._run_guarded(
                                lambda b=batch: self._fit_multi_io(b)):
                            for listener in net.listeners:
                                listener.iteration_done(net,
                                                        net.iteration)
                        continue
                    if pre_staged:
                        # the pipeline already padded + dp-sharded
                        x, y, fm, lm = batch
                    else:
                        x, y, fm, lm = self._pad_with_masks(
                            *_as_batch(batch))
                    if k > 1:
                        group.append((x, y, fm, lm))
                        if len(group) == k:
                            g = group
                            group = []
                            self._run_guarded(
                                lambda: self._local_step.run(g))
                        continue
                    if k2 > 1:
                        entry = (x, y, fm, lm)
                        if window and not _window_compatible(
                                window[-1], entry):
                            # shape break: dispatch the shorter window
                            # (compiled once per distinct k)
                            self._run_window(window)
                            window = []
                        window.append(entry)
                        if len(window) == k2:
                            self._run_window(window)
                            window = []
                        continue
                    if pre_staged:
                        xb, yb, fmb, lmb = x, y, fm, lm
                    else:
                        xb = shard_batch(self.mesh,
                                         jnp.asarray(x, net.dtype))
                        yb = shard_batch(self.mesh,
                                         jnp.asarray(y, net.dtype))
                        fmb = (None if fm is None else
                               shard_batch(self.mesh, jnp.asarray(fm)))
                        lmb = (None if lm is None else
                               shard_batch(self.mesh, jnp.asarray(lm)))
                    program = self._harness.program
                    program.require_sgd("ParallelWrapper")

                    def one_step(xb=xb, yb=yb, fmb=fmb, lmb=lmb):
                        # the shared StepProgram owns the graph-input /
                        # TBPTT dispatch; the sharded batch dim flows
                        # through unchanged (GSPMD inserts the grad
                        # all-reduce into the same compiled step)
                        program.run(xb, yb, fmb, lmb)

                    if self._run_guarded(one_step):
                        for listener in net.listeners:
                            listener.iteration_done(net, net.iteration)
                if group:
                    # trailing group smaller than k: run it as a shorter
                    # local-step stack (compiled once per distinct size)
                    g = group
                    self._run_guarded(lambda: self._local_step.run(g))
                if window:
                    self._run_window(window)
                net.epoch += 1

    def _run_window(self, window) -> bool:
        """One `run_group` dispatch over a k-window, MASKS STACKED
        ALONGSIDE FEATURES — the carried-forward PR 9 gap: fm/lm
        batches previously had no grouped path in ParallelWrapper.
        Mask-less batches sharing a window with masked ones get
        all-ones masks (exactly LocalStepTrainer.run's equalization),
        and the stack is staged [k, ...] with the step dim replicated
        and the batch dim dp-sharded. run_group(k) is byte-identical
        to k sequential steps (pinned in test_pipeline.py for a masked
        net)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        net = self.net
        program = self._harness.program
        program.require_sgd("ParallelWrapper")
        any_fm = any(w[2] is not None for w in window)
        any_lm = any(w[3] is not None for w in window)
        xs, ys, fms, lms = [], [], [], []
        for x, y, fm, lm in window:
            x = jnp.asarray(x, net.dtype)
            y = jnp.asarray(y, net.dtype)
            if any_fm and fm is None:
                fm = jnp.ones((x.shape[0],) + (() if x.ndim == 2
                                               else (x.shape[1],)),
                              jnp.float32)
            if any_lm and lm is None:
                lm = jnp.ones((x.shape[0],) if y.ndim == 2
                              else (x.shape[0], y.shape[1]),
                              jnp.float32)
            xs.append(x)
            ys.append(y)
            if any_fm:
                fms.append(jnp.asarray(fm))
            if any_lm:
                lms.append(jnp.asarray(lm))

        def stack(parts):
            # device-side stack when the pipeline pre-staged the
            # batches (no host np.stack copy of the k-window)
            out = jnp.stack(parts)
            return jax.device_put(
                out, NamedSharding(
                    self.mesh, P(*([None, "dp"][:min(2, out.ndim)]))))

        xs = stack(xs)
        ys = stack(ys)
        fms = stack(fms) if any_fm else None
        lms = stack(lms) if any_lm else None
        ok = self._run_guarded(
            lambda: program.run_group(xs, ys, fms, lms))
        if ok:
            for listener in net.listeners:
                listener.iteration_done(net, net.iteration)
        return ok

    def _fit_multi_io(self, batch):
        """Multi-input/multi-output graph batch: shard every input,
        label, and mask over dp (batch must be dp-divisible — ragged
        padding is only automated on the single-io path)."""
        from deeplearning4j_tpu.nn.graph import _as_multi

        net = self.net
        ins, labs, fms, lms = _as_multi(batch)
        b = np.asarray(ins[0]).shape[0]
        if b % self.dp:
            raise ValueError(
                f"multi-input batch size {b} must be divisible by "
                f"dp={self.dp} (pad the batch or mask rows yourself)")
        names = net.conf.network_inputs
        sb = lambda a: shard_batch(self.mesh, jnp.asarray(a, net.dtype))
        inputs = {n: sb(x) for n, x in zip(names, ins)}
        labels = [sb(y) for y in labs]
        fmasks = None
        if fms is not None:
            fmasks = {n: (None if m is None else sb(m))
                      for n, m in zip(names, fms)}
        lmasks = None
        if lms is not None:
            lmasks = [None if m is None else sb(m) for m in lms]
        net._train_step(inputs, labels, fmasks, lmasks)

    def output(self, x):
        self._ensure_sharded()
        with self.mesh:
            return self.net.output(shard_batch(self.mesh, jnp.asarray(x)))


def _as_batch(batch):
    from deeplearning4j_tpu.nn.multilayer import _as_batch as f
    return f(batch)


def _window_compatible(a, b) -> bool:
    """Two batches may share a run_group k-window when their feature/
    label shapes match (the scan stacks them) and any masks BOTH carry
    agree in shape (a missing mask is synthesized as ones)."""
    for i in (0, 1):
        if tuple(np.shape(a[i])) != tuple(np.shape(b[i])):
            return False
    for i in (2, 3):
        if a[i] is not None and b[i] is not None \
                and tuple(np.shape(a[i])) != tuple(np.shape(b[i])):
            return False
    return True


def _pad_batch_with_masks(dp, x, y, fm, lm):
    """Pad one batch's leading dim to a dp multiple (static shapes for
    XLA), masking padded rows out of the loss. Returns (x, y, fm, lm).
    Shared by ParallelWrapper and StaleGradientTrainer."""
    x = np.asarray(x)
    npad = (-x.shape[0]) % dp
    if npad:
        x = np.concatenate(
            [x, np.zeros((npad,) + x.shape[1:], x.dtype)], 0)
        y2 = np.asarray(y)
        y = np.concatenate(
            [y2, np.zeros((npad,) + y2.shape[1:], y2.dtype)], 0)
        if lm is None:
            lm = np.ones(
                (x.shape[0],) if y2.ndim == 2
                else (x.shape[0], y2.shape[1]), np.float32)
            lm[-npad:] = 0.0
        else:
            lm2 = np.asarray(lm)
            lm = np.concatenate(
                [lm2, np.zeros((npad,) + lm2.shape[1:], lm2.dtype)], 0)
        if fm is not None:
            fm2 = np.asarray(fm)
            fm = np.concatenate(
                [fm2, np.zeros((npad,) + fm2.shape[1:], fm2.dtype)], 0)
    return x, y, fm, lm


# the step math lives with the engine now (ONE source for the single
# step, the k-step group, and both shard_map trainers below); the old
# private name stays importable for downstream callers
_make_loss_and_apply = make_loss_and_apply


class LocalStepTrainer:
    """True `averagingFrequency=k` local-SGD semantics via shard_map:
    each dp shard carries its own params for k local steps (gradients of
    its LOCAL minibatch only — no cross-shard gradient exchange), then
    params (and optionally updater state + BN running stats) are pmean'd
    over dp — the reference's AVERAGING mode
    (ParallelWrapper.java:320, averageUpdatersState :332-365), compiled
    as one XLA program per group size.

    This trades gradient freshness for k× fewer collectives; on ICI the
    per-step all-reduce is nearly free, so this exists for semantic
    parity and for DCN-spanning meshes where collectives are expensive.

    Constraints: tp must be 1 (params are replicated inside the shard_map)
    and the wrapped net must not be in TBPTT carry mode.
    """

    def __init__(self, net, mesh: Mesh, average_updaters: bool = True,
                 threshold: float = 0.0, per_step_losses: bool = False,
                 program=None):
        """`threshold > 0` enables threshold compression of the k-step
        parameter delta at each rendezvous (the reference's
        EncodingHandler.java:57-73 role, composed with local SGD): each
        shard sends sign(delta+residual)*threshold only where
        |delta+residual| >= threshold and keeps the remainder in a
        per-shard residual accumulator, so successive rendezvous
        eventually deliver everything. `wire_stats()` reports the
        resulting bytes-on-wire vs a dense exchange. The residual is
        in-memory state: a killed-and-resumed job loses its pending
        (sub-threshold) delta mass, exactly like the reference's
        in-memory residual accumulator — checkpoints capture the
        delivered params only."""
        if mesh.shape["tp"] != 1:
            raise NotImplementedError(
                "averaging_frequency > 1 requires tp == 1 (local-SGD "
                "shards carry full param replicas)")
        if getattr(net.conf, "backprop_type", None) == "truncated_bptt":
            raise NotImplementedError(
                "averaging_frequency > 1 does not support truncated "
                "BPTT (the local-step scan carries no RNN state); use "
                "averaging_frequency=1")
        self.net = net
        self.mesh = mesh
        self.average_updaters = average_updaters
        self.threshold = float(threshold)
        # per_step_losses=True compiles the group program to ALSO
        # return the k dp-averaged inner-step losses (read back via
        # `last_step_losses`) so a guard can localize a poisoned inner
        # step; off by default — the compiled program is unchanged
        self.per_step_losses = bool(per_step_losses)
        self.last_step_losses = None
        # compilation is ENGINE-owned (PR 9 follow-on): the shard_map
        # programs live in the net's JitCache through
        # StepProgram.trainer_program — recompile forensics, precision
        # policy registration, and the mesh arc see one owner
        from deeplearning4j_tpu.engine import StepProgram

        self._program = program or StepProgram(net)
        self._residual = None
        self._sent_nnz = []          # per-rendezvous device scalars
        self._param_entries = None
        self._n_rendezvous = 0

    # -------------------------------------------------------------- build
    def _build(self, k: int, with_fm: bool, with_lm: bool,
               trace_key: str = "local_sgd"):
        from deeplearning4j_tpu.nn.updater import schedule_lr

        net = self.net
        conf = net.conf
        avg_upd = self.average_updaters
        loss_for_grad, apply_updates = _make_loss_and_apply(net)

        thr = self.threshold

        def worker(params, upd_states, states, residual, step0, xs, ys,
                   fms, lms, rng, lr_scale):
            net._jit_cache.record_trace(trace_key)
            # decorrelate dropout across shards
            rng = jax.random.fold_in(rng, jax.lax.axis_index("dp"))
            keys = jax.random.split(rng, k)

            def one(carry, sl):
                params, upd_states, states, step = carry
                x, y, fm, lm, key = sl
                (loss, new_states), grads = jax.value_and_grad(
                    loss_for_grad, has_aux=True)(
                        params, states, x, y, key, fm, lm)
                grads = net._clip_grads(grads)
                lr = schedule_lr(conf, step) * lr_scale
                params, upd_states = apply_updates(
                    params, upd_states, grads, lr, step)
                return (params, upd_states, new_states, step + 1), loss

            params0 = params
            (params, upd_states, states, _), losses = jax.lax.scan(
                one, (params, upd_states, states, step0),
                (xs, ys, fms, lms, keys))
            # rendezvous: average over dp
            pmean = lambda t: jax.tree_util.tree_map(
                lambda a: jax.lax.pmean(a, "dp"), t)
            if thr > 0.0:
                # threshold-encode the k-step delta with residual carry
                # (EncodingHandler.java:57-73 role): only +-thr spikes
                # cross the wire; the remainder waits in `residual`
                def encode(p0, p1, res):
                    acc = (p1 - p0) + res[0]
                    send = jnp.where(jnp.abs(acc) >= thr,
                                     jnp.sign(acc) * thr, 0.0)
                    return send, (acc - send)[None]
                flat0, treedef = jax.tree_util.tree_flatten(params0)
                flat1 = jax.tree_util.tree_leaves(params)
                flatr = jax.tree_util.tree_leaves(residual)
                sends, new_res = [], []
                nnz = jnp.zeros((), jnp.float32)
                for p0, p1, res in zip(flat0, flat1, flatr):
                    send, r = encode(p0, p1, res)
                    sends.append(send)
                    new_res.append(r)
                    nnz = nnz + jnp.count_nonzero(
                        send).astype(jnp.float32)
                avg = [jax.lax.pmean(sv, "dp") for sv in sends]
                params = jax.tree_util.tree_unflatten(
                    treedef, [p0 + a for p0, a in zip(flat0, avg)])
                residual = jax.tree_util.tree_unflatten(
                    treedef, new_res)
                nnz = jax.lax.psum(nnz, "dp")
            else:
                params = pmean(params)
                nnz = jnp.zeros((), jnp.float32)
            states = pmean(states)
            if avg_upd:
                upd_states = pmean(upd_states)
            out = (params, upd_states, states,
                   jax.lax.pmean(jnp.mean(losses), "dp"),
                   residual, nnz)
            if step_losses:
                # [k] dp-averaged inner-step losses: a NaN shard
                # propagates through the pmean, so the host can point
                # at the exact poisoned inner step
                out += (jax.lax.pmean(losses, "dp"),)
            return out

        step_losses = self.per_step_losses
        rep = P()             # replicated at entry/exit
        xspec = P(None, "dp")  # [k, batch, ...]: batch dim sharded
        fspec = xspec if with_fm else rep
        lspec = xspec if with_lm else rep
        rspec = P("dp")       # per-shard residual, [dp, ...] outside
        outs = (rep, rep, rep, rep, rspec, rep)
        if step_losses:
            outs += (rep,)
        return jax.jit(jax.shard_map(
            worker, mesh=self.mesh,
            in_specs=(rep, rep, rep, rspec, rep, xspec, xspec, fspec,
                      lspec, rep, rep),
            out_specs=outs,
            check_vma=False),
            donate_argnums=(0, 1, 2, 3))

    def _init_residual(self):
        """Per-shard residual accumulators, zero-initialized with a
        [dp, ...] layout sharded over dp (each shard owns its own)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        if self.threshold <= 0.0:
            return {}      # no compression: no residual state to carry
        dp = self.mesh.shape["dp"]
        params = self.net.params
        if self._param_entries is None:
            self._param_entries = sum(
                int(np.prod(a.shape))
                for a in jax.tree_util.tree_leaves(params))
        sh = NamedSharding(self.mesh, P("dp"))

        def zeros():
            return jax.tree_util.tree_map(
                lambda a: jnp.zeros((dp,) + a.shape, a.dtype), params)

        return jax.jit(zeros, out_shardings=sh)()

    def wire_stats(self):
        """Bytes-on-wire accounting for the rendezvous exchanges (the
        WiredEncodingHandler.java:40-57 role): dense = full param
        all-reduce per rendezvous; compressed = 4 bytes per threshold
        spike (the reference's integer wire format encodes sign in the
        index). The updater-state and BN-state averages stay DENSE in
        both modes and are counted in both totals, so the ratio
        reflects the whole rendezvous, not just the params."""
        n = self._n_rendezvous
        if self._param_entries is None or self.threshold <= 0.0 or not n:
            return {"threshold": self.threshold, "rendezvous": n,
                    "bytes_dense": None, "bytes_compressed": None,
                    "compression_ratio": None}
        aux_entries = sum(
            int(np.prod(a.shape))
            for a in jax.tree_util.tree_leaves(self.net.states))
        if self.average_updaters:
            aux_entries += sum(
                int(np.prod(a.shape))
                for a in jax.tree_util.tree_leaves(
                    self.net.updater_states))
        dp = self.mesh.shape["dp"]
        sent = float(sum(float(v) for v in self._sent_nnz))
        dense_params = float(self._param_entries) * 4.0 * n * dp
        aux = float(aux_entries) * 4.0 * n * dp
        comp = sent * 4.0 + aux
        dense = dense_params + aux
        return {"threshold": self.threshold, "rendezvous": n,
                "bytes_dense": dense, "bytes_compressed": comp,
                "compression_ratio": comp / dense if dense else None}

    # ---------------------------------------------------------------- run
    def run(self, group):
        """Run one k-step local-SGD group. `group` is a list of
        (x, y, fm, lm) host batches (batch dims already dp-padded)."""
        net = self.net
        k = len(group)
        # equalize batch sizes across the group (fully-masked pad rows)
        bmax = max(np.asarray(g[0]).shape[0] for g in group)
        any_fm = any(g[2] is not None for g in group)
        any_lm = any(g[3] is not None for g in group)
        xs, ys, fms, lms = [], [], [], []
        for x, y, fm, lm in group:
            x, y = np.asarray(x), np.asarray(y)
            if any_lm and lm is None:
                lm = np.ones((x.shape[0],) if y.ndim == 2
                             else (x.shape[0], y.shape[1]), np.float32)
            if any_fm and fm is None:
                fm = np.ones((x.shape[0],) + (() if x.ndim == 2
                                              else (x.shape[1],)),
                             np.float32)
            n = bmax - x.shape[0]
            if n:
                pad = lambda a: np.concatenate(
                    [a, np.zeros((n,) + a.shape[1:], a.dtype)], 0)
                x, y = pad(x), pad(y)
                if lm is None:
                    lm = np.ones((x.shape[0],) if y.ndim == 2
                                 else (x.shape[0], y.shape[1]), np.float32)
                    lm[-n:] = 0.0
                else:
                    lm = pad(lm)
                if fm is not None:
                    fm = pad(fm)
            xs.append(x); ys.append(y); fms.append(fm); lms.append(lm)
        # equalization padding may have created masks for only some
        # batches; fill the rest with ones so stacking is uniform
        if any(m is not None for m in lms):
            lms = [np.ones((x.shape[0],) if y.ndim == 2
                           else (x.shape[0], y.shape[1]), np.float32)
                   if lm is None else lm
                   for x, y, lm in zip(xs, ys, lms)]
        any_lm = any(m is not None for m in lms)
        xs = jnp.asarray(np.stack(xs), net.dtype)
        ys = jnp.asarray(np.stack(ys), net.dtype)
        fms = jnp.asarray(np.stack(fms)) if any_fm else None
        lms = jnp.asarray(np.stack(lms)) if any_lm else None

        is_graph = hasattr(net.conf, "network_inputs")
        if is_graph:
            name = net.conf.network_inputs[0]
            xs_in = {name: xs}
            ys_in = [ys]
            fms_in = None if fms is None else {name: fms}
            lms_in = None if lms is None else [lms]
        else:
            xs_in, ys_in, fms_in, lms_in = xs, ys, fms, lms
        return self.run_arrays(xs_in, ys_in, fms_in, lms_in, k=k)

    def run_arrays(self, xs_in, ys_in, fms_in=None, lms_in=None, k=None):
        """Run one k-step local-SGD group on pre-staged arrays with a
        leading [k, ...] step dim. Device-resident arrays can be passed
        repeatedly without re-staging — this is how the bench amortizes
        host->device transfer and per-dispatch latency over k steps."""
        net = self.net
        is_graph = hasattr(net.conf, "network_inputs")
        if k is None:
            lead = (next(iter(xs_in.values())) if is_graph else xs_in)
            k = int(lead.shape[0])

        # engine-owned compilation: the JitCache key carries the
        # frozen signature (freeze/unfreeze between fits takes effect)
        # and the program registers its precision policy + forensics
        # trace like every other engine program
        with_fm = fms_in is not None
        with_lm = lms_in is not None
        fn = self._program.trainer_program(
            "engine_local_sgd",
            lambda tk: self._build(k, with_fm, with_lm, tk),
            k, with_fm, with_lm, self.per_step_losses,
            self.threshold > 0.0)
        net._rng, sub = jax.random.split(net._rng)
        if self._residual is None:
            self._residual = self._init_residual()
        out = fn(
                net.params, net.updater_states, net.states,
                self._residual,
                jnp.asarray(net.iteration, jnp.int32),
                xs_in, ys_in, fms_in, lms_in, sub,
                jnp.asarray(net._lr_score_factor, jnp.float32))
        if self.per_step_losses:
            (net.params, net.updater_states, net.states, loss,
             self._residual, nnz, self.last_step_losses) = out
        else:
            (net.params, net.updater_states, net.states, loss,
             self._residual, nnz) = out
        if self.threshold > 0.0:
            # keep per-rendezvous device scalars; summed (in f64-safe
            # host arithmetic) only when wire_stats() is read, so the
            # hot loop never syncs
            self._sent_nnz.append(nnz)
            self._n_rendezvous += 1
        net.iteration += k
        net._score = loss
        net._apply_score_decay(loss)
        for listener in net.listeners:
            listener.iteration_done(net, net.iteration)
        return loss


class StaleGradientTrainer:
    """DP-4's async training DYNAMICS, TPU-natively (parity role:
    SharedTrainingMaster.java:72 / SharedTrainingWrapper.java:196-240 —
    workers there train on gradients that arrive late through the Aeron
    parameter server).

    SPMD redesign: bounded 1-step staleness instead of unbounded async.
    Step t computes this batch's globally-averaged gradient g_t but
    APPLIES g_{t-1}: the cross-slice all-reduce of g_t therefore sits
    on the program's critical path BEHIND the next step's compute, so
    XLA's async collectives can overlap it with forward/backward work —
    the latency-hiding role of the reference's parameter server with a
    hard staleness bound (and none of its lost-update races, SURVEY
    §5.2). fit() flushes the final pending gradient so no update is
    dropped; updater state (momentum etc.) advances with the DELAYED
    gradient stream, matching how the reference's workers consume late
    updates.

    Constraints: tp == 1 (params replicated inside the shard_map), no
    truncated BPTT.
    """

    def __init__(self, net, mesh: Mesh, program=None):
        if mesh.shape["tp"] != 1:
            raise NotImplementedError(
                "StaleGradientTrainer requires tp == 1")
        if getattr(net.conf, "backprop_type", None) == "truncated_bptt":
            raise NotImplementedError(
                "StaleGradientTrainer does not support truncated BPTT")
        from deeplearning4j_tpu.engine import StepProgram

        self.net = net
        self.mesh = mesh
        # compilation is engine-owned (StepProgram.trainer_program):
        # the delayed-gradient programs live in the net's JitCache
        # with forensics + precision-policy registration
        self._program = program or StepProgram(net)
        self._pending = None     # g_{t-1}: replicated averaged gradient

    def _build(self, with_fm: bool, with_lm: bool, flush: bool,
               trace_key: str = "stale_grad"):
        from deeplearning4j_tpu.nn.updater import schedule_lr

        net = self.net
        conf = net.conf
        # rebuilt per cache entry: the frozen set is baked into these
        # closures (cache is keyed on frozen_sig for that reason)
        loss_for_grad, apply_updates = _make_loss_and_apply(net)

        def worker(params, upd_states, states, prev_g, step, x, y, fm,
                   lm, rng, lr_scale):
            net._jit_cache.record_trace(trace_key)
            lr = schedule_lr(conf, step) * lr_scale
            if flush:
                # terminal half-step: apply the last pending gradient
                params, upd_states = apply_updates(
                    params, upd_states, prev_g, lr, step)
                return (params, upd_states, states, prev_g,
                        jnp.zeros(()))
            rng = jax.random.fold_in(rng, jax.lax.axis_index("dp"))
            (loss, new_states), grads = jax.value_and_grad(
                loss_for_grad, has_aux=True)(
                    params, states, x, y, rng, fm, lm)
            grads = net._clip_grads(grads)
            pmean = lambda t: jax.tree_util.tree_map(
                lambda a: jax.lax.pmean(a, "dp"), t)
            g_avg = pmean(grads)
            # per-shard BN running stats must agree before the
            # replicated out_spec (same contract as LocalStepTrainer)
            new_states = pmean(new_states)
            # apply the PREVIOUS step's gradient (1-step staleness)
            params, upd_states = apply_updates(
                params, upd_states, prev_g, lr, step)
            return (params, upd_states, new_states, g_avg,
                    jax.lax.pmean(loss, "dp"))

        rep = P()
        xspec = P("dp")
        fspec = xspec if with_fm else rep
        lspec = xspec if with_lm else rep
        return jax.jit(jax.shard_map(
            worker, mesh=self.mesh,
            in_specs=(rep, rep, rep, rep, rep, xspec, xspec, fspec,
                      lspec, rep, rep),
            out_specs=(rep, rep, rep, rep, rep),
            check_vma=False),
            donate_argnums=(0, 1, 2, 3))

    def _zero_grads(self):
        return jax.tree_util.tree_map(jnp.zeros_like, self.net.params)

    def step(self, x, y, fm=None, lm=None):
        net = self.net
        if self._pending is None:
            self._pending = self._zero_grads()
        with_fm, with_lm = fm is not None, lm is not None
        fn = self._program.trainer_program(
            "engine_stale",
            lambda tk: self._build(with_fm, with_lm, False, tk),
            with_fm, with_lm)
        net._rng, sub = jax.random.split(net._rng)
        (net.params, net.updater_states, net.states, self._pending,
         loss) = fn(
            net.params, net.updater_states, net.states, self._pending,
            jnp.asarray(net.iteration, jnp.int32), x, y, fm, lm, sub,
            jnp.asarray(net._lr_score_factor, jnp.float32))
        net.iteration += 1
        net._score = loss
        net._apply_score_decay(loss)
        for listener in net.listeners:
            listener.iteration_done(net, net.iteration)
        return loss

    def flush(self):
        """Apply the final pending gradient (call at end of fit)."""
        net = self.net
        if self._pending is None:
            return
        fn = self._program.trainer_program(
            "engine_stale_flush",
            lambda tk: self._build(False, False, True, tk))
        dummy = jnp.zeros((self.mesh.shape["dp"], 1), net.dtype)
        (net.params, net.updater_states, net.states, self._pending,
         _) = fn(
            net.params, net.updater_states, net.states, self._pending,
            jnp.asarray(net.iteration, jnp.int32), dummy, dummy, None,
            None, jax.random.PRNGKey(0),
            jnp.asarray(net._lr_score_factor, jnp.float32))
        self._pending = None

    def fit(self, batches):
        """Train over an iterable of batches in any _as_batch shape
        ((x, y), (x, y, fm, lm), DataSet, ...), flushing the last
        pending gradient at the end. Leading dims are padded to a dp
        multiple with loss-masked rows."""
        net = self.net
        dp = self.mesh.shape["dp"]
        with self.mesh:
            for batch in batches:
                x, y, fm, lm = _as_batch(batch)
                x, y, fm, lm = _pad_batch_with_masks(
                    dp, np.asarray(x), np.asarray(y), fm, lm)
                x = jnp.asarray(x, net.dtype)
                y = jnp.asarray(y, net.dtype)
                fm = None if fm is None else jnp.asarray(fm)
                lm = None if lm is None else jnp.asarray(lm)
                is_graph = hasattr(net.conf, "network_inputs")
                if is_graph:
                    name = net.conf.network_inputs[0]
                    self.step({name: x}, [y],
                              None if fm is None else {name: fm},
                              None if lm is None else [lm])
                else:
                    self.step(x, y, fm, lm)
            self.flush()
        return self
