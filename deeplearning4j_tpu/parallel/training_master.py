"""TrainingMaster: multi-host (DCN) data-parallel training orchestration.

Parity: the Spark training stack's role —
spark/api/TrainingMaster.java (SPI: executeTraining, worker config,
result aggregation), ParameterAveragingTrainingMaster.java:326 (BSP
splits + aggregate), ExecuteWorkerFlatMap.java (per-worker data
partition), SharedTrainingMaster.java:72 (the async gradient mesh).

TPU-native design: instead of Spark shipping serialized models to
executors and tree-aggregating parameters, every host runs THIS same
program under `jax.distributed`; the per-host input partition (the
RDD-partition role) is assembled into one global device array
(`jax.make_array_from_process_local_data`), and the gradient exchange
is the XLA all-reduce GSPMD inserts into the SAME compiled train step
used on one chip — collectives ride ICI within a slice and DCN across
slices, replacing both the Aeron parameter server and Spark
treeAggregate (SURVEY §2.4, §5.8).

Fault tolerance (SURVEY §5.3): step-granular checkpoints
{params, updater state, BN states, iteration, rng} written by process 0
(shared filesystem assumption, like Spark's checkpoint dir); a killed
job relaunches with the same arguments and resumes from the latest
checkpoint — the reference's "stateless per split, re-fit from last
broadcast" recovery, made explicit.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import time
from functools import partial
from typing import Callable, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.observability import metrics as _obs
from deeplearning4j_tpu.resilience import checkpoint_integrity as _ci
from deeplearning4j_tpu.resilience.errors import (
    FaultInjectedError,
    NonFiniteLossError,
    PreemptedError,
    StepHangError,
)
from deeplearning4j_tpu.resilience.faults import fire as _fire
from deeplearning4j_tpu.resilience.retry import Retry
from deeplearning4j_tpu.resilience.supervisor import (
    NonFiniteGuard,
    PreemptionHandler,
    StepWatchdog,
    Supervisor,
    fire_hang_hard,
)

logger = logging.getLogger("deeplearning4j_tpu")


class TrainingMaster:
    """Orchestrates SPMD data-parallel training of one net across all
    processes in a `jax.distributed` job (or a single process).

    Every process must construct the SAME net (same config + seed) and
    call the same TrainingMaster methods in the same order — standard
    SPMD discipline (the reference instead broadcasts the model; with
    identical seeds the construction IS the broadcast)."""

    def __init__(self, net, checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 0, mesh=None,
                 averaging_frequency: int = 1,
                 threshold_compression: float = 0.0,
                 checkpoint_format: str = "npz",
                 keep_last: int = 0,
                 checkpoint_retry: Optional[Retry] = None,
                 guard: Optional[NonFiniteGuard] = None,
                 watchdog: Optional[StepWatchdog] = None,
                 preemption=False,
                 data_retry: Optional[Retry] = None,
                 skip_bad_batches: bool = False,
                 supervisor: Optional[Supervisor] = None,
                 guard_inner_steps: bool = False,
                 tracer=None,
                 phase_profiler=None,
                 steps_per_dispatch: int = 1,
                 per_rank_checkpoints: bool = False,
                 pipeline: Optional[bool] = None,
                 pipeline_depth: int = 2,
                 sharding: Optional[str] = None):
        """`averaging_frequency=k > 1` runs k-step local SGD between
        parameter rendezvous — each dp shard trains privately for k
        steps, then params (+ updater state) are averaged. This is the
        DCN-traffic-reduction role of the reference's threshold-encoded
        gradient compression (EncodingHandler.java:64): instead of
        compressing a per-step exchange, the exchange happens k times
        less often; `threshold_compression=t > 0` additionally
        threshold-encodes the k-step parameter delta with per-shard
        residual accumulation before the cross-shard average
        (EncodingHandler.java:57-73) — frequency reduction and byte
        reduction compose. Wire accounting lands in
        training_stats()["wire"]."""
        import jax
        from deeplearning4j_tpu.parallel.mesh import make_mesh

        if checkpoint_format not in ("npz", "orbax"):
            raise ValueError(
                f"checkpoint_format must be npz|orbax: {checkpoint_format}")
        if sharding not in (None, "replicated", "zero1"):
            raise ValueError(
                f"sharding must be None|'replicated'|'zero1': {sharding}")
        # ZeRO-1 (engine/sharding.py, arXiv 2004.13336): optimizer
        # state sharded over the mesh's dp axis, the weight update
        # reduce-scattered / shard-local / all-gathered INSIDE the one
        # compiled step. Byte-identical to the replicated program;
        # 1/n per-replica optimizer memory.
        self.zero1 = sharding == "zero1"
        self.net = net
        # per-rank checkpoint copies (`<dir>/rank-<r>/`): EVERY process
        # writes its own copy instead of process 0 alone — the input
        # the ClusterSupervisor's divergence quorum votes over (a
        # silently forked replica is out-voted, quarantined aside, and
        # healed before any resume). Replicated dp training makes the
        # copies the same state, so the canonical state digest
        # (recorded in each manifest at save) compares equal.
        self.per_rank_checkpoints = bool(per_rank_checkpoints)
        if self.per_rank_checkpoints and checkpoint_format != "npz":
            raise ValueError(
                "per_rank_checkpoints requires checkpoint_format='npz' "
                "(the divergence quorum votes over npz state digests)")
        self._ckpt_base = checkpoint_dir
        if self.per_rank_checkpoints and checkpoint_dir:
            from deeplearning4j_tpu.resilience.checkpoint_integrity import (
                rank_checkpoint_dir,
            )

            checkpoint_dir = rank_checkpoint_dir(
                checkpoint_dir, jax.process_index())
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = checkpoint_every
        self.checkpoint_format = checkpoint_format
        if mesh is None:
            mesh = make_mesh(dp=len(jax.devices()))
        self.mesh = mesh
        from deeplearning4j_tpu.parallel.wrapper import (
            _require_local_sgd,
        )

        self.averaging_frequency = max(1, averaging_frequency)
        self.threshold_compression = float(threshold_compression)
        _require_local_sgd(self.averaging_frequency,
                           self.threshold_compression)
        # keep_last > 0 prunes old step checkpoints after each save;
        # transient filesystem errors on the checkpoint path retry with
        # backoff (injected faults / corruption are NOT retryable)
        self.keep_last = int(keep_last)
        self._ckpt_retry = checkpoint_retry or Retry(
            max_attempts=3, initial_backoff_s=0.05,
            retryable=lambda e: isinstance(e, OSError))
        # --- self-healing hooks (resilience/supervisor.py): all opt-in,
        # all zero-cost when None/False
        if guard is not None and guard.policy == "rollback" \
                and not checkpoint_dir:
            raise ValueError(
                "NonFiniteGuard(policy='rollback') requires a "
                "checkpoint_dir to roll back to")
        self.guard = guard
        self.watchdog = watchdog
        if preemption is True:
            preemption = PreemptionHandler()
        self.preemption = preemption or None
        self.data_retry = data_retry
        self.skip_bad_batches = skip_bad_batches
        self.supervisor = supervisor
        # local-SGD granularity fix (flag-gated — the default compiled
        # program and cost profile are unchanged): the group program
        # additionally returns per-inner-step losses so the guard can
        # localize a poisoned INNER step instead of condemning the
        # whole k-step window
        self.guard_inner_steps = bool(guard_inner_steps)
        # `steps_per_dispatch=k > 1` runs the engine's lax.scan k-step
        # group on the single-program path: one dispatch advances k
        # steps (amortizing per-dispatch RTT, PERF.md), per-inner-step
        # losses preserved so the guard condemns ONE poisoned step.
        # Orthogonal to averaging_frequency (which groups steps at the
        # local-SGD rendezvous instead).
        self.steps_per_dispatch = max(1, int(steps_per_dispatch))
        if self.steps_per_dispatch > 1 and self.averaging_frequency > 1:
            raise ValueError(
                "steps_per_dispatch > 1 and averaging_frequency > 1 "
                "are mutually exclusive groupings (the local-SGD "
                "rendezvous already scans its k steps in one dispatch)")
        # harness-owned input pipeline (engine/pipeline.py): a producer
        # thread runs fetch -> retry/skip -> poison -> h2d staging
        # ahead of the compute so data_wait/h2d overlap device_compute.
        # Default (None): ON for single-process jobs, OFF multi-host
        # (cross-rank staging order stays on the consumer thread until
        # the sharded scale-out arc); pipeline=False opts out.
        self.pipeline = pipeline
        self.pipeline_depth = max(1, int(pipeline_depth))
        self._prefetch = None
        self._staged = False
        self._local_step = None
        # ONE supervisor (engine.StepHarness) owns the guard-verdict
        # dispatch, watchdog lifecycle, preemption checks, the
        # StepAccumulator every per-step metric batches through
        # (flushed every 32 steps and at fit end — container appends,
        # not registry locks), and the opt-in phase profiler; a Tracer
        # records per-step spans (fetch/dispatch/sync/checkpoint) on
        # one exportable timeline.
        from deeplearning4j_tpu.engine import StepHarness

        self._harness = StepHarness(
            net, guard=guard, watchdog=watchdog,
            preemption=self.preemption, supervisor=supervisor,
            tracer=tracer, phase_profiler=phase_profiler)
        self._obs_acc = self._harness.acc
        self._poisoned_steps = self._harness.poisoned_steps
        self._resil_counters = self._harness.counters
        self._mesh_mgr = None
        if self.zero1:
            if self.averaging_frequency > 1:
                raise ValueError(
                    "sharding='zero1' and averaging_frequency > 1 are "
                    "incompatible (local SGD keeps per-shard params; "
                    "ZeRO-1 shards the synchronous update)")
            if checkpoint_format != "npz":
                raise ValueError(
                    "sharding='zero1' requires checkpoint_format='npz'"
                    " (sharded optimizer-state slices ride npz "
                    "sidecars)")
            if (self.checkpoint_dir and jax.process_count() > 1
                    and not self.per_rank_checkpoints):
                raise ValueError(
                    "sharding='zero1' in a multi-process gang needs "
                    "per_rank_checkpoints=True (every rank must write "
                    "its own optimizer-state slice)")
            from deeplearning4j_tpu.engine.mesh import MeshManager

            self._mesh_mgr = MeshManager(mesh=self.mesh)
            self._harness.program.attach_mesh(self._mesh_mgr)

    # tracer / phase_profiler delegate to the harness so post-
    # construction assignment (bench_obs.py's config sweep) reaches
    # the loop that actually reads them
    @property
    def tracer(self):
        return self._harness.tracer

    @tracer.setter
    def tracer(self, tracer):
        self._harness.tracer = tracer
        pp = self._harness.phase_profiler
        if pp is not None and pp.tracer is None:
            pp.tracer = tracer

    @property
    def phase_profiler(self):
        return self._harness.phase_profiler

    @phase_profiler.setter
    def phase_profiler(self, pp):
        if pp is not None:
            if pp.accumulator is None:
                pp.accumulator = self._harness.acc
            if pp.tracer is None:
                pp.tracer = self._harness.tracer
        self._harness.phase_profiler = pp

    # ------------------------------------------------------------ dist init
    @staticmethod
    def initialize_distributed(coordinator_address: str,
                               num_processes: int, process_id: int):
        """`jax.distributed.initialize` wrapper (must run before any
        device use). No-op for num_processes == 1."""
        if num_processes <= 1:
            return
        import jax

        try:
            jax.config.update(
                "jax_cpu_collectives_implementation", "gloo")
        except Exception:   # noqa: BLE001 - non-CPU platforms configure
            pass            # their own collectives; flag absent there
        jax.distributed.initialize(coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)

    @staticmethod
    def process_info() -> Tuple[int, int]:
        import jax

        return jax.process_index(), jax.process_count()

    def world_info(self) -> dict:
        """The LIVE world this master trains in: process count (the
        dp-average denominator's host axis after a shrink-to-fit
        relaunch), device count, and the mesh's dp extent. Everything
        that shards data or averages across replicas derives from
        these live values — never from a configured world size — so an
        elastic gang that relaunches smaller re-derives its global
        batch semantics automatically."""
        import jax

        try:
            dp = int(self.mesh.shape.get("dp", 1))
        except Exception:   # noqa: BLE001 - exotic mesh: report devices
            dp = len(jax.devices())
        return {"processes": int(jax.process_count()),
                "devices": len(jax.devices()),
                "dp": dp,
                "sharding": "zero1" if self.zero1 else "replicated",
                "per_rank_checkpoints": self.per_rank_checkpoints}

    # ------------------------------------------------------------- staging
    def _replicated(self, tree):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        sh = NamedSharding(self.mesh, P())
        return jax.tree_util.tree_map(
            lambda a: jax.make_array_from_process_local_data(
                sh, np.asarray(a)), tree)

    def _stage_net(self):
        if self._staged:
            return
        from deeplearning4j_tpu.parallel.wrapper import (
            _disable_flat_chain,
        )

        if self.net.params is None:
            self.net.init()
        _disable_flat_chain(self.net)
        self.net.params = self._replicated(self.net.params)
        if self._mesh_mgr is not None:
            # ZeRO-1: optimizer state lives SHARDED between steps —
            # divisible leaves split their leading dim over dp (1/n
            # per replica), the rest replicate
            import jax as _jax

            self.net.updater_states = self._mesh_mgr.shard_tree(
                _jax.tree_util.tree_map(self._host_leaf,
                                        self.net.updater_states))
        else:
            self.net.updater_states = self._replicated(
                self.net.updater_states)
        self.net.states = self._replicated(self.net.states)
        self._staged = True

    def _stage(self, a, spec):
        """Host partition -> global device array with `spec` sharding,
        cast to the net's dtype."""
        import jax
        import numpy as _np
        from jax.sharding import NamedSharding

        dtype = _np.dtype(getattr(self.net, "dtype", None) or _np.float32)
        return jax.make_array_from_process_local_data(
            NamedSharding(self.mesh, spec), np.asarray(a, dtype))

    def _global_batch(self, x_local, y_local):
        """Per-host partition -> global [G, ...] device arrays sharded
        over dp (the ExecuteWorkerFlatMap data-partition role)."""
        from jax.sharding import PartitionSpec as P

        return (self._stage(x_local, P("dp")),
                self._stage(y_local, P("dp")))

    # ----------------------------------------------------------------- fit
    def fit(self, batch_fn: Callable[[int], Tuple], num_steps: int,
            start_step: Optional[int] = None,
            collect_training_stats: bool = False):
        """Train for `num_steps` global steps.

        `batch_fn(step) -> (x_local, y_local)`: THIS process's partition
        of the global batch at `step` (deterministic in step, so resume
        replays the data stream from the checkpointed position — the
        step index is the iterator position).

        If `start_step` is None and a checkpoint exists, training
        resumes after the last checkpointed step.

        `collect_training_stats=True` records per-step phase timings
        (data staging / train step / checkpoint) retrievable via
        `training_stats()` — the Spark CommonSparkTrainingStats role
        (ref TrainingMaster.setCollectTrainingStats,
        spark/stats/StatsUtils.java timeline export).

        Self-healing (resilience/supervisor.py, all opt-in via the
        constructor): a NonFiniteGuard checks loss+params after
        (sampled) steps and skips/rolls-back/aborts on NaN or loss
        spikes; a StepWatchdog heartbeats around dispatch/fetch and
        escalates a hung step; a PreemptionHandler turns SIGTERM/SIGINT
        (or the `train.preempt` fault) into checkpoint-then-
        PreemptedError at the next step boundary; `data_retry` +
        `skip_bad_batches` make a flaky batch_fn (the `data.next`
        fault point) survivable. Run the whole fit under
        `Supervisor.run` to also survive crashes/hangs/preemptions via
        checkpoint resume.

        Telemetry (observability/): every loop iteration lands in the
        global MetricsRegistry (`dl4j_train_steps_total` counts
        ATTEMPTED steps, including skipped ones;
        `dl4j_train_step_seconds` their wall time); with a `tracer`
        attached each step records a parent span with
        fetch/dispatch/sync/checkpoint children, and the StepWatchdog's
        monitor thread parents its hang events to the current step
        span."""
        self._stage_net()
        # the live world: data sharding and the dp-average denominator
        # derive from THIS (mesh over the processes actually present),
        # so a shrink-to-fit relaunch predictably re-averages the loss
        # over the surviving replicas; the gauge makes it scrapeable
        _obs.set_gauge("dl4j_cluster_world_size",
                       self.world_info()["processes"])
        guard = self.guard
        if start_step is None:
            start_step = self.load_latest_checkpoint()
        if collect_training_stats:
            self._stats = []
        self._harness.program.require_sgd("TrainingMaster")
        if (guard is not None and guard.policy == "rollback"
                and self.checkpoint_dir and not self.list_checkpoints()):
            # a rollback target must exist before the first poisoned
            # step — seed one at the fit's starting state
            self.save_checkpoint(start_step)
        with self._harness.session():
            self._prefetch = None
            if self._pipeline_enabled():
                self._prefetch = self._harness.build_step_pipeline(
                    lambda s: self._produce(batch_fn, s),
                    start=start_step, stop=num_steps,
                    depth=self.pipeline_depth,
                    skip=self._poisoned_steps.__contains__,
                    meta={"sharding": "dp",
                          "world": self.world_info()})
            if self.averaging_frequency > 1:
                return self._fit_local_sgd(batch_fn, num_steps,
                                           start_step,
                                           collect_training_stats)
            if self.steps_per_dispatch > 1:
                return self._fit_grouped(batch_fn, num_steps,
                                         start_step,
                                         collect_training_stats)
            with self.mesh:
                step = start_step
                while step < num_steps:
                    if step in self._poisoned_steps:
                        step += 1   # rollback replay: skip the poisoned
                        continue    # data window, train nothing on it
                    self._check_preemption(step)
                    with self._harness.step_scope(step):
                        step = self._fit_one_step(
                            batch_fn, step, collect_training_stats)
        return self

    def _fit_one_step(self, batch_fn, step,
                      collect_training_stats) -> int:
        """One attempted global step (fit() wraps it in the harness's
        step_scope for span + metric accounting): returns the step
        index to continue from — step+1 normally and on skips, the
        restored step after a rollback."""
        net = self.net
        guard = self.guard
        harness = self._harness
        tr = self.tracer
        sp = harness.step_span
        _fire("train.step")
        _fire("train.hang")
        fire_hang_hard()
        harness.beat("dispatch", step=step)
        harness.mark("data_wait")
        t0 = time.perf_counter()
        staged = self._fetch_step(batch_fn, step)
        if staged is None:      # bad batch skipped by policy
            return step + 1
        x, y = staged
        t1 = time.perf_counter()
        if tr is not None:
            tr.record("fetch_and_stage", t0, t1, cat="train", parent=sp)
        done = step + 1
        ckpt_due = bool(
            self.checkpoint_dir and self.checkpoint_every
            and done % self.checkpoint_every == 0)
        # a checkpoint must never publish non-finite state: force a
        # check on checkpoint steps even when the sampling cadence
        # would skip them
        check_now = harness.should_check(step=step) \
            or (ckpt_due and harness.should_check(force=True))
        snap = harness.pre_step_snapshot(check_now)
        harness.mark("dispatch")
        harness.program.run(x, y)
        t_disp = time.perf_counter()
        if tr is not None:
            tr.record("dispatch", t1, t_disp, cat="train", parent=sp)
        harness.beat("fetch", step=step)
        # sampled device sync: the blocked interval on the step's
        # loss value is the device_compute phase; everything after
        # is host-side sync work (guard checks, score fetches)
        harness.sync(getattr(net, "_score", None), step=step)
        harness.mark("host_sync")
        if check_now:
            verdict = guard.post_step(net)
            if verdict != "ok":
                restored = {}

                def _rollback_to_checkpoint():
                    self._poisoned_steps.add(step)
                    restored["step"] = self.load_latest_checkpoint()
                    logger.warning(
                        "guard: rolled back to checkpoint step %d; "
                        "step %d will be skipped on replay",
                        restored["step"], step)

                action = harness.dispatch_verdict(
                    verdict, snap=snap,
                    restore_rollback=_rollback_to_checkpoint,
                    context=f"at step {step}")
                if action == "skip":
                    return step + 1
                if action == "rollback":
                    return restored["step"]
        if collect_training_stats:
            # host fetch = true step barrier for honest timing
            # analyze: allow=jit-host-sync — opt-in stats mode only
            float(net.score())
        t2 = time.perf_counter()
        if tr is not None and (check_now or collect_training_stats):
            # the guard check / stats fetch forced a host sync — this
            # span is the device+fetch-result phase made visible
            tr.record("device_sync", t_disp, t2, cat="train",
                      parent=sp)
        harness.mark("telemetry")   # listener callbacks are user telemetry
        for listener in net.listeners:
            listener.iteration_done(net, net.iteration)
        t3 = time.perf_counter()
        if ckpt_due:
            harness.mark("checkpoint")
            self.save_checkpoint(done)
        if collect_training_stats:
            self._stats.append({
                "step": step,
                "data_ms": (t1 - t0) * 1e3,
                "fit_ms": (t2 - t1) * 1e3,
                "listener_ms": (t3 - t2) * 1e3,
                "checkpoint_ms":
                    (time.perf_counter() - t3) * 1e3,
            })
        return step + 1

    # --------------------------------------------------- input pipeline
    def _pipeline_enabled(self) -> bool:
        """Pipeline resolution: explicit flag wins; default ON
        everywhere. Multi-host staging is sharding-aware (the producer
        thread stages THIS rank's partition through
        `make_array_from_process_local_data` on the live mesh — a
        per-process placement, no cross-rank coordination to
        misorder), so the PR 12 multi-host auto-off is gone;
        pipeline=False opts out."""
        if self.pipeline is not None:
            return bool(self.pipeline)
        return True

    def _produce(self, batch_fn, step):
        """Producer-side work for ONE step (runs on the prefetch
        thread): the `data.next` fault point + `data_retry`/
        `skip_bad_batches` policy, chaos poisoning, and the h2d staging
        itself — a poisoned batch condemns the right step, and the copy
        of step k+1 overlaps compute on step k. Returns staged (x, y)
        global arrays sharded over the LIVE mesh's dp axis, or SKIPPED
        when the skip policy consumed the failure."""
        from deeplearning4j_tpu.engine.pipeline import SKIPPED

        b = self._next_batch(batch_fn, step, observe=False)
        if b is None:
            return SKIPPED
        return self._global_batch(self._maybe_poison(b[0]), b[1])

    def _fetch_step(self, batch_fn, step):
        """Staged (x, y) device arrays for `step`, or None when the
        step was skipped by policy — through the harness-owned
        prefetcher when the pipeline is on (fetch + h2d already
        overlapped earlier compute; the residual wait is what
        data_wait shrinks to), else fetched + staged synchronously."""
        harness = self._harness
        if self._prefetch is not None:
            t0 = time.perf_counter()
            out = self._prefetch.get(step)
            harness.mark("h2d")
            if out is None:
                return None
            self._obs_acc.observe("dl4j_train_data_wait_seconds",
                                  time.perf_counter() - t0)
            return out
        batch = self._next_batch(batch_fn, step)
        if batch is None:
            return None
        harness.mark("h2d")
        return self._global_batch(
            self._maybe_poison(batch[0]), batch[1])

    def _fetch_window(self, batch_fn, step, span):
        """(group, abs_steps) for a k-window's non-poisoned steps —
        pipeline on: staged (x, y) device pairs; off: host pairs. The
        per-inner-step ordering (and therefore the fault-point hit →
        step mapping) is identical in both modes."""
        group, abs_steps = [], []
        for s in range(step, step + span):
            if s in self._poisoned_steps:
                continue   # rollback replay: skip poisoned data
            if self._prefetch is not None:
                t0 = time.perf_counter()
                out = self._prefetch.get(s)
                if out is None:
                    continue
                self._obs_acc.observe("dl4j_train_data_wait_seconds",
                                      time.perf_counter() - t0)
                group.append(out)
                abs_steps.append(s)
            else:
                b = self._next_batch(batch_fn, s)
                if b is not None:
                    group.append((self._maybe_poison(b[0]), b[1]))
                    abs_steps.append(s)
        return group, abs_steps

    def _stack_window(self, group):
        """[k] batch pairs -> ([k, G, ...], [k, G, ...]) staged with
        P(None, 'dp'). Pipeline entries stack on DEVICE (stack_staged —
        no host np.stack copy of the k-window); host entries stack then
        stage. Same values, same sharding, same compiled program."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        if self._prefetch is not None:
            from deeplearning4j_tpu.engine.pipeline import stack_staged

            sh = NamedSharding(self.mesh, P(None, "dp"))
            return (stack_staged([g[0] for g in group], sh),
                    stack_staged([g[1] for g in group], sh))
        return (self._stage(np.stack([g[0] for g in group]),
                            P(None, "dp")),
                self._stage(np.stack([g[1] for g in group]),
                            P(None, "dp")))

    # ------------------------------------------------------- self-healing
    def _next_batch(self, batch_fn, step, observe: bool = True):
        """Fetch this step's batch through the `data.next` fault point,
        retried per `data_retry`; returns None (skip the step) when the
        fetch ultimately fails and `skip_bad_batches` is set.
        `observe=False` on the pipeline's producer thread: the
        StepAccumulator is single-owner, so the consumer observes its
        own (residual) wait instead."""
        def get():
            _fire("data.next")
            return batch_fn(step)

        t_fetch = time.perf_counter()
        try:
            if self.data_retry is not None:
                out = self.data_retry.call(get)
            else:
                out = get()
        except (StepHangError, PreemptedError):
            raise          # escalations, not data failures
        except Exception:
            if self.skip_bad_batches:
                self._resil_counters["data_skipped_steps"] += 1
                _obs.count("dl4j_train_data_skipped_steps_total")
                logger.warning("data.next failed at step %d — step "
                               "skipped (skip_bad_batches)", step)
                return None
            raise
        if observe:
            self._obs_acc.observe("dl4j_train_data_wait_seconds",
                                  time.perf_counter() - t_fetch)
        return out

    def _maybe_poison(self, x):
        """`train.grad_nonfinite` chaos hook: a triggered fire is
        consumed by poisoning the batch with NaN, so non-finite
        loss/grads flow through the REAL step math (what the guard must
        catch), not a synthetic exception."""
        try:
            _fire("train.grad_nonfinite")
        except FaultInjectedError:
            self._resil_counters["grad_poisoned_steps"] += 1
            x = np.full(np.shape(x), np.nan, np.float32)
        return x

    def _check_preemption(self, step):
        """Step-boundary preemption check (engine.StepHarness owns the
        logic): a pending SIGTERM/SIGINT or a triggered `train.preempt`
        fault checkpoints the CURRENT state and raises PreemptedError —
        a preempted job loses zero completed steps and a Supervisor (or
        a relaunch) resumes exactly here."""
        self._harness.check_preemption(
            step, save_checkpoint=(self.save_checkpoint
                                   if self.checkpoint_dir else None))

    def _fit_grouped(self, batch_fn, num_steps, start_step,
                     collect_training_stats=False):
        """`steps_per_dispatch=k`: the engine's `lax.scan` k-step group
        on the single-program path — ONE dispatch advances k steps
        (amortizing per-dispatch RTT, PERF.md), data stacked
        [k, G, ...]. The group program returns per-inner-step losses,
        fetched only on checked groups, so the guard condemns the ONE
        poisoned inner step and the window replays without it — same
        granularity contract as the local-SGD `guard_inner_steps`
        path, now the default for engine groups."""
        net = self.net
        guard = self.guard
        harness = self._harness
        program = harness.program
        program.require_sgd("TrainingMaster")
        k = self.steps_per_dispatch
        every = self.checkpoint_every
        pp = self.phase_profiler
        with self.mesh:
            step = start_step
            while step < num_steps:
                self._check_preemption(step)
                _fire("train.step")
                _fire("train.hang")
                fire_hang_hard()
                harness.beat("dispatch", step=step)
                if pp is not None:
                    pp.begin_step(step)
                    pp.mark("data_wait")
                t0 = time.perf_counter()
                span = min(step + k, num_steps) - step
                group, abs_steps = self._fetch_window(
                    batch_fn, step, span)
                if not group:
                    step += span
                    continue
                if pp is not None:
                    pp.mark("h2d")
                xs, ys = self._stack_window(group)
                t1 = time.perf_counter()
                # guard at group granularity: one check per dispatch
                # (already a 1/k sampling of the underlying steps)
                check_now = guard is not None and guard.check_every > 0
                snap = harness.pre_step_snapshot(check_now)
                if pp is not None:
                    pp.mark("dispatch")
                program.run_group(xs, ys)
                harness.beat("fetch", step=step)
                if pp is not None:
                    pp.mark("host_sync")
                if check_now:
                    # the scan group ALWAYS returns per-inner-step
                    # losses: the FIRST non-finite one is the poisoned
                    # step (the scan carries params, so every later
                    # inner loss is downstream contamination — those
                    # steps replay on clean state instead)
                    inner = np.asarray(program.last_step_losses)
                    finite = np.isfinite(inner)
                    bad = ([abs_steps[int(np.argmax(~finite))]]
                           if not finite.all() else [])
                    if bad:
                        guard.counters["checks"] += 1
                        guard.counters["nonfinite"] += 1
                        _obs.count("dl4j_train_guard_checks_total")
                        _obs.count("dl4j_train_guard_nonfinite_total")
                        self._poisoned_steps.update(bad)

                        def _rollback_group():
                            self._grouped_restore = \
                                self.load_latest_checkpoint()

                        action = harness.dispatch_verdict(
                            "nonfinite", snap=snap,
                            restore_rollback=_rollback_group,
                            context=f"at inner step(s) {bad} of group "
                                    f"at step {step}")
                        if action == "skip":
                            logger.warning(
                                "guard: non-finite inner step(s) %s — "
                                "window replayed without them", bad)
                        else:   # rollback
                            step = self._grouped_restore
                        continue   # re-enter the window minus `bad`
                    verdict = guard.post_step(net)
                    if verdict != "ok":
                        def _rollback_window():
                            for s in range(step, step + span):
                                self._poisoned_steps.add(s)
                            self._grouped_restore = \
                                self.load_latest_checkpoint()

                        action = harness.dispatch_verdict(
                            verdict, snap=snap,
                            restore_rollback=_rollback_window,
                            context=f"in group at step {step}")
                        if action == "skip":
                            step += span
                        else:   # rollback
                            step = self._grouped_restore
                        continue
                if collect_training_stats:
                    # analyze: allow=jit-host-sync — opt-in stats barrier
                    float(net.score())
                t2 = time.perf_counter()
                # group telemetry: steps_total counts the inner steps
                # actually trained; step_seconds stays in per-step
                # units (group wall time averaged over its steps)
                self._obs_acc.count_observe(
                    "dl4j_train_steps_total", "dl4j_train_step_seconds",
                    (t2 - t0) / max(1, len(abs_steps)),
                    n=len(abs_steps))
                if self.tracer is not None:
                    self.tracer.record(
                        "train_group", t0, t2, cat="train",
                        args={"step": step, "steps": len(abs_steps)})
                for listener in net.listeners:
                    listener.iteration_done(net, net.iteration)
                prev = step
                step += span
                # checkpoint when the group CROSSES a cadence boundary
                # (group ends rarely align with checkpoint_every)
                if (self.checkpoint_dir and every
                        and prev // every != step // every):
                    if pp is not None:
                        pp.mark("checkpoint")
                    self.save_checkpoint(step)
                if pp is not None:
                    pp.end_step()
                if collect_training_stats:
                    self._stats.append({
                        "step": prev,
                        "data_ms": (t1 - t0) * 1e3,
                        "fit_ms": (t2 - t1) * 1e3,
                        "listener_ms": 0.0,
                        "checkpoint_ms":
                            (time.perf_counter() - t2) * 1e3,
                    })
        return self

    def _fit_local_sgd(self, batch_fn, num_steps, start_step,
                       collect_training_stats=False):
        """k-step local-SGD groups over the global mesh (the DCN
        compression role — see __init__). Reuses LocalStepTrainer's
        shard_map program; data stacked [k, G, ...] per group."""
        import time

        from deeplearning4j_tpu.parallel.wrapper import LocalStepTrainer

        net = self.net
        guard = self.guard
        wd = self.watchdog
        k = self.averaging_frequency
        if self._local_step is None:
            self._local_step = LocalStepTrainer(
                net, self.mesh,
                threshold=self.threshold_compression,
                per_step_losses=self.guard_inner_steps)
        is_graph = hasattr(net.conf, "network_inputs")
        every = self.checkpoint_every
        pp = self.phase_profiler
        with self.mesh:
            step = start_step
            while step < num_steps:
                self._check_preemption(step)
                _fire("train.step")
                _fire("train.hang")
                fire_hang_hard()
                if wd is not None:
                    wd.beat("dispatch", step=step)
                # group-level phase attribution (guard-anomaly exits
                # leave the group unprofiled; begin_step resets state)
                if pp is not None:
                    pp.begin_step(step)
                    pp.mark("data_wait")
                t0 = time.perf_counter()
                span = min(step + k, num_steps) - step
                group, abs_steps = self._fetch_window(
                    batch_fn, step, span)
                if not group:
                    step += span
                    continue
                if pp is not None:
                    pp.mark("h2d")
                xs, ys = self._stack_window(group)
                t1 = time.perf_counter()
                # guard at group granularity: one check per rendezvous
                # (already a 1/k sampling of the underlying steps)
                check_now = guard is not None and guard.check_every > 0
                snap = (guard.snapshot(net)
                        if check_now and guard.policy == "skip_step"
                        else None)
                if pp is not None:
                    pp.mark("dispatch")
                if is_graph:
                    name = net.conf.network_inputs[0]
                    self._local_step.run_arrays({name: xs}, [ys])
                else:
                    self._local_step.run_arrays(xs, ys)
                if wd is not None:
                    wd.beat("fetch", step=step)
                if pp is not None:
                    pp.mark("host_sync")
                if check_now and self.guard_inner_steps:
                    # granularity fix: the compiled group program also
                    # returned per-inner-step (dp-averaged) losses — a
                    # non-finite one condemns THAT step only, not the
                    # whole k-step window
                    inner = np.asarray(
                        self._local_step.last_step_losses)
                    bad = [abs_steps[i] for i in range(len(abs_steps))
                           if not np.isfinite(inner[i])]
                    if bad:
                        guard.counters["checks"] += 1
                        guard.counters["nonfinite"] += 1
                        _obs.count("dl4j_train_guard_checks_total")
                        _obs.count("dl4j_train_guard_nonfinite_total")
                        if guard.policy == "abort":
                            raise NonFiniteLossError(
                                f"non-finite loss at inner step(s) "
                                f"{bad} of group at step {step} "
                                f"(policy=abort)")
                        self._poisoned_steps.update(bad)
                        if guard.policy == "skip_step":
                            guard.restore(net, snap)
                            guard.note_skip()
                            logger.warning(
                                "guard: non-finite inner step(s) %s — "
                                "window replayed without them", bad)
                        else:   # rollback
                            guard.note_rollback()
                            if guard.counters["rollbacks"] \
                                    > guard.max_rollbacks:
                                raise NonFiniteLossError(
                                    "guard exceeded max_rollbacks="
                                    f"{guard.max_rollbacks}")
                            step = self.load_latest_checkpoint()
                        continue   # re-enter the window minus `bad`
                if check_now:
                    verdict = guard.post_step(net)
                    if verdict != "ok":
                        if guard.policy == "skip_step":
                            guard.restore(net, snap)
                            guard.note_skip()
                            step += span
                            continue
                        if guard.policy == "rollback":
                            # the whole group is the poisoned window
                            for s in range(step, step + span):
                                self._poisoned_steps.add(s)
                            guard.note_rollback()
                            if guard.counters["rollbacks"] \
                                    > guard.max_rollbacks:
                                raise NonFiniteLossError(
                                    "guard exceeded max_rollbacks="
                                    f"{guard.max_rollbacks}")
                            step = self.load_latest_checkpoint()
                            continue
                        raise NonFiniteLossError(
                            f"{verdict} training state in group at "
                            f"step {step} (policy=abort)")
                if collect_training_stats:
                    # analyze: allow=jit-host-sync — opt-in stats barrier
                    float(net.score())
                t2 = time.perf_counter()
                # group telemetry: steps_total counts the inner steps
                # actually trained; step_seconds stays in per-step
                # units (group wall time averaged over its steps)
                self._obs_acc.count_observe(
                    "dl4j_train_steps_total", "dl4j_train_step_seconds",
                    (t2 - t0) / max(1, len(abs_steps)),
                    n=len(abs_steps))
                if self.tracer is not None:
                    self.tracer.record(
                        "train_group", t0, t2, cat="train",
                        args={"step": step, "steps": len(abs_steps)})
                prev = step
                step += span
                # checkpoint when the group CROSSES a cadence boundary
                # (group ends rarely align with checkpoint_every)
                if (self.checkpoint_dir and every
                        and prev // every != step // every):
                    if pp is not None:
                        pp.mark("checkpoint")
                    self.save_checkpoint(step)
                if pp is not None:
                    pp.end_step()
                if collect_training_stats:
                    self._stats.append({
                        "step": prev,
                        "data_ms": (t1 - t0) * 1e3,
                        "fit_ms": (t2 - t1) * 1e3,
                        "listener_ms": 0.0,
                        "checkpoint_ms":
                            (time.perf_counter() - t2) * 1e3,
                    })
        return self

    def training_stats(self):
        """Per-step phase timings recorded when fit(...,
        collect_training_stats=True) — the CommonSparkTrainingStats
        equivalent. Returns a list of dicts plus an aggregate row, and a
        `resilience` block (guard / watchdog / preemption / supervisor
        counters) whenever any self-healing hook is attached."""
        stats = list(getattr(self, "_stats", []))
        wire = (self._local_step.wire_stats()
                if self._local_step is not None else None)
        resil = self.resilience_stats()
        prof = self._profiler_stats()
        phases = (self.phase_profiler.report()
                  if self.phase_profiler is not None else None)
        pipe = self._harness.pipeline_stats()
        if not stats:
            return {"steps": [], "summary": {}, "wire": wire,
                    "resilience": resil, "profiler": prof,
                    "phases": phases, "pipeline": pipe}
        summary = {
            k: float(np.mean([s[k] for s in stats]))
            for k in ("data_ms", "fit_ms", "listener_ms", "checkpoint_ms")
        }
        return {"steps": stats, "summary": summary, "wire": wire,
                "resilience": resil, "profiler": prof,
                "phases": phases, "pipeline": pipe}

    def _profiler_stats(self):
        """Surface an attached ProfilerListener's device-trace facts
        (satellite: trace_dir was previously only reachable by digging
        the listener out of net.listeners by hand)."""
        for listener in getattr(self.net, "listeners", []):
            if hasattr(listener, "trace_dir") \
                    and hasattr(listener, "log_dir"):
                return {"trace_dir": listener.trace_dir,
                        "log_dir": listener.log_dir,
                        "active": bool(getattr(listener, "_active",
                                               False)),
                        "done": bool(getattr(listener, "_done", False))}
        return None

    def resilience_stats(self):
        """Guard / watchdog / preemption / restart counters (None when
        no self-healing hook is attached and nothing was counted) —
        delegated to the shared harness, which owns the counters."""
        return self._harness.resilience_stats()

    def export_stats_html(self, path: str):
        """Timeline HTML export (ref StatsUtils.exportStatsAsHtml)."""
        import json as _json

        data = self.training_stats()
        rows = "".join(
            f"<tr><td>{s['step']}</td><td>{s['data_ms']:.2f}</td>"
            f"<td>{s['fit_ms']:.2f}</td>"
            f"<td>{s['checkpoint_ms']:.2f}</td></tr>"
            for s in data["steps"])
        resil = ("" if data.get("resilience") is None else
                 f"<p>resilience: {_json.dumps(data['resilience'])}</p>")
        page = (
            "<!DOCTYPE html><html><head><meta charset='utf-8'>"
            "<title>training timeline</title></head><body>"
            f"<h1>TrainingMaster timeline</h1>"
            f"<p>summary: {_json.dumps(data['summary'])}</p>"
            f"{resil}"
            "<table border='1'><tr><th>step</th><th>data ms</th>"
            "<th>fit ms</th><th>checkpoint ms</th></tr>"
            f"{rows}</table></body></html>")
        with open(path, "w") as f:
            f.write(page)
        return path

    # ------------------------------------------------------------ evaluate
    def evaluate(self, batch_fn: Callable[[int], Tuple], num_steps: int,
                 evaluation=None):
        """Distributed evaluation (the Spark eval flatMap+reduce role,
        IEvaluateFlatMapFunction/IEvaluationReduceFunction): every
        process runs inference on its partition of each batch; the
        device argmax comparison is summed over the dp axis inside the
        compiled program, so each host ends with identical GLOBAL
        confusion counts folded into `evaluation`."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from deeplearning4j_tpu.eval import Evaluation

        self._stage_net()
        net = self.net
        if evaluation is None:
            evaluation = Evaluation()
        is_graph = hasattr(net.conf, "network_inputs")
        rep = NamedSharding(self.mesh, P())

        if getattr(self, "_eval_fn", None) is None:
            @partial(jax.jit, static_argnums=(4,))
            def confusion_counts(params, states, x, y, has_mask, lm):
                if is_graph:
                    name = net.conf.network_inputs[0]
                    acts, _, _ = net._forward(params, states, {name: x},
                                              train=False, rng=None)
                    out = acts[net.conf.network_outputs[0]]
                else:
                    out, _, _ = net._forward(params, states, x,
                                             train=False, rng=None)
                c = y.shape[-1]
                # time-series outputs [N,T,C] flatten to rows like
                # Evaluation.eval does
                pred = jnp.argmax(out, axis=-1).reshape(-1)
                actual = jnp.argmax(y, axis=-1).reshape(-1)
                onehot = (jax.nn.one_hot(actual, c)[:, :, None]
                          * jax.nn.one_hot(pred, c)[:, None, :])
                if has_mask:
                    # label mask [N,T] (or [N]): drop padded timesteps
                    # exactly like Evaluation.eval(..., mask=lm) — any
                    # nonzero mask value means "keep" (boolean semantics)
                    keep = (lm.reshape(-1) != 0).astype(onehot.dtype)
                    onehot = onehot * keep[:, None, None]
                # global sum: GSPMD reduces over the dp-sharded batch
                return jax.lax.with_sharding_constraint(
                    jnp.sum(onehot, axis=0), rep)

            self._eval_fn = confusion_counts
        confusion_counts = self._eval_fn

        with self.mesh:
            for step in range(num_steps):
                # batch_fn follows the container convention
                # (x, y[, features_mask[, labels_mask]]); like the
                # containers' evaluate(), only the LABEL mask shapes the
                # confusion counts (Evaluation.eval(..., mask=lm))
                batch = batch_fn(step)
                x, y = self._global_batch(batch[0], batch[1])
                lm = batch[3] if len(batch) > 3 else None
                if lm is not None:
                    lm = self._stage(lm, P("dp"))
                counts = confusion_counts(net.params, net.states, x, y,
                                          lm is not None, lm)
                m = np.asarray(self._host_leaf(counts)).astype(np.int64)
                evaluation._ensure(m.shape[0])
                evaluation.confusion.matrix += m
        return evaluation

    # ------------------------------------------------------- checkpointing
    def _ckpt_path(self, step: int) -> str:
        return os.path.join(self.checkpoint_dir, f"step-{step:08d}.npz")

    @staticmethod
    def _host_leaf(a):
        """Fetch a (replicated) global array to host."""
        if hasattr(a, "addressable_shards"):
            return np.asarray(a.addressable_shards[0].data)
        return np.asarray(a)

    def save_checkpoint(self, step: int):
        """Timed wrapper around the format-specific save: checkpoint
        write latency + count land in the registry, and with a tracer
        attached the save records a span parented to the current step
        span."""
        t0 = time.perf_counter()
        result = self._save_checkpoint_impl(step)
        t1 = time.perf_counter()
        _obs.count("dl4j_checkpoint_writes_total")
        _obs.observe("dl4j_checkpoint_write_seconds", t1 - t0)
        if self.tracer is not None:
            self.tracer.record("checkpoint_save", t0, t1,
                               cat="checkpoint",
                               parent=self._harness.step_span,
                               args={"step": step})
        return result

    def _save_checkpoint_impl(self, step: int):
        """Write {params, updater state, states, step, rng}.

        format="npz": process 0 gathers everything to host and writes
        one crash-safe .npz (shared-FS model, ref
        ParameterAveragingTrainingMaster's driver-side ownership) —
        right for replicated dp training at this scale. The write is
        tmp + fsync + os.replace with a sha256 manifest entry recorded
        from the pre-publish bytes, so a kill mid-write publishes
        nothing and a torn write is detected on load; transient OSErrors
        retry per `checkpoint_retry`; `keep_last` prunes old steps.
        format="orbax": every process participates in an
        orbax.checkpoint save (SURVEY §7's "orbax-style sharded
        checkpoints for scale" — sharded arrays are written without
        gathering to one host)."""
        import jax

        if self.checkpoint_format == "orbax":
            return self._save_orbax(step)
        # per-rank mode: EVERY process writes its own copy (into its
        # rank-<r> dir) — the divergence quorum's voters
        if jax.process_index() != 0 and not self.per_rank_checkpoints:
            return
        os.makedirs(self.checkpoint_dir, exist_ok=True)
        net = self.net
        payload = {}
        for group, tree in (("params", net.params),
                            ("states", net.states)):
            for i, leaf in enumerate(jax.tree_util.tree_leaves(tree)):
                payload[f"{group}:{i}"] = self._host_leaf(leaf)
        # ZeRO-1: sharded optimizer leaves go to a per-rank sidecar
        # (this rank's slice only); the replicated remainder rides the
        # main payload so the divergence quorum's state digest stays
        # identical across ranks
        shard_slices = None
        if self._mesh_mgr is not None:
            shard_slices = self._sharded_upd_payload(payload)
        else:
            for i, leaf in enumerate(
                    jax.tree_util.tree_leaves(net.updater_states)):
                payload[f"upd:{i}"] = self._host_leaf(leaf)
        payload["rng"] = np.asarray(net._rng)
        # self-describing: fallback loads recover position without
        # trusting latest.json (which may point at the damaged step)
        payload["step"] = np.asarray(step)
        payload["iteration"] = np.asarray(int(net.iteration))
        payload["epoch"] = np.asarray(int(net.epoch))
        final = self._ckpt_path(step)
        fn = os.path.basename(final)
        # canonical state digest (container-timestamp-immune): what the
        # cross-rank divergence quorum compares — identical replicated
        # state hashes equal on every rank even though the zip bytes
        # differ
        state_h = hashlib.sha256()
        for k in sorted(payload):
            a = np.ascontiguousarray(payload[k])
            state_h.update(k.encode())
            state_h.update(str(a.dtype).encode())
            state_h.update(str(a.shape).encode())
            state_h.update(a.tobytes())
        state_sha = state_h.hexdigest()

        def _write():
            with _ci.atomic_writer(final, suffix=".tmp.npz") as tmp:
                with open(tmp, "wb") as f:
                    np.savez(f, **payload)
                digest = _ci.sha256_file(tmp)
                size = os.path.getsize(tmp)
                # chaos hook: 'raise' = kill mid-write (tmp discarded,
                # nothing published); 'truncate' = torn write slipping
                # past the atomic publish — caught by the checksum
                _fire("checkpoint.write", path=tmp)
            _ci.record_checksum(self.checkpoint_dir, fn, digest, size,
                                extra={"step": step,
                                       "state_sha256": state_sha})

        if shard_slices is not None:
            # sidecar FIRST: a published main step implies its slice
            # exists (a kill between the two leaves an orphan sidecar,
            # which the sharded quorum simply never elects)
            self._write_shard_sidecar(step, shard_slices, state_sha)
        self._ckpt_retry.call(_write)
        meta = {"step": step, "iteration": int(net.iteration),
                "epoch": int(net.epoch)}
        _ci.atomic_write_json(
            os.path.join(self.checkpoint_dir, "latest.json"), meta)
        _ci.apply_retention(self.checkpoint_dir, self.keep_last)

    def _sharded_upd_payload(self, payload) -> dict:
        """Split the updater-state leaves for the ZeRO-1 checkpoint
        layout: replicated leaves into the (quorum-voted) main
        `payload` as `upd:<i>`, sharded leaves gathered from the mesh
        (timed as `dl4j_mesh_allgather_seconds`) and sliced to THIS
        process's contiguous rows for the sidecar. The main payload
        records `upd_sharded_idx` + `shard_world` so the digest covers
        the layout itself."""
        import jax

        from deeplearning4j_tpu.engine.sharding import slice_rows

        net = self.net
        mgr = self._mesh_mgr
        layout = mgr.shard_layout(net.updater_states)
        full = mgr.gather_tree(net.updater_states)
        leaves = jax.tree_util.tree_leaves(full)
        world = max(1, int(jax.process_count()))
        rank = int(jax.process_index())
        slices = {}
        sharded_idx = []
        for i, (leaf, sharded) in enumerate(zip(leaves, layout)):
            if sharded and leaf.shape[0] % world == 0:
                sharded_idx.append(i)
                slices[f"slice:{i}"] = slice_rows(leaf, rank, world)
            else:
                payload[f"upd:{i}"] = leaf
        payload["upd_sharded_idx"] = np.asarray(sharded_idx, np.int64)
        payload["shard_world"] = np.asarray(world)
        return slices

    def _write_shard_sidecar(self, step, slices, state_sha):
        """This rank's optimizer-state slice sidecar: atomic write +
        manifest entry carrying `main_state_sha256`, the digest of the
        main state the slice belongs to — the linkage the sharded
        quorum verifies before trusting a slice."""
        import jax

        side_fn = _ci.shard_sidecar_filename(step)
        side = os.path.join(self.checkpoint_dir, side_fn)
        world = max(1, int(jax.process_count()))
        rank = int(jax.process_index())

        def _write_side():
            with _ci.atomic_writer(side, suffix=".tmp.npz") as tmp:
                with open(tmp, "wb") as f:
                    np.savez(f, shard_rank=np.asarray(rank),
                             shard_world=np.asarray(world), **slices)
                digest = _ci.sha256_file(tmp)
                size = os.path.getsize(tmp)
            _ci.record_checksum(
                self.checkpoint_dir, side_fn, digest, size,
                extra={"step": step, "shard_rank": rank,
                       "shard_world": world,
                       "main_state_sha256": state_sha})

        self._ckpt_retry.call(_write_side)

    def _restore_sharded_upd(self, data, step: int):
        """Host updater-state tree reassembled from the sharded
        checkpoint layout: replicated leaves from the main payload,
        sharded leaves from the per-rank sidecar slices — saved at ANY
        world size; the zero1 staging re-slices for the CURRENT world
        (resharding on resume, counted as `dl4j_mesh_reshard_total`
        when the worlds differ)."""
        import jax

        from deeplearning4j_tpu.engine.sharding import assemble_rows
        from deeplearning4j_tpu.resilience.errors import (
            CheckpointIntegrityError,
        )

        net = self.net
        leaves, treedef = jax.tree_util.tree_flatten(net.updater_states)
        world = int(data["shard_world"])
        sharded_idx = [int(i) for i in
                       np.asarray(data["upd_sharded_idx"]).reshape(-1)]
        new = [None] * len(leaves)
        for i in range(len(leaves)):
            if i not in sharded_idx:
                new[i] = data[f"upd:{i}"]
        if sharded_idx:
            fn = os.path.basename(self._ckpt_path(step))
            expect = _ci.state_digest(self.checkpoint_dir, fn)
            if self.per_rank_checkpoints or world > 1:
                base = self._ckpt_base
                dirs = [_ci.rank_checkpoint_dir(base, r)
                        for r in range(world)]
            else:
                dirs = [self.checkpoint_dir]
            slices = _ci.collect_sharded_slices(
                dirs, step, expect_digest=expect)
            if slices is None:
                raise CheckpointIntegrityError(
                    f"sharded checkpoint step {step}: optimizer-state "
                    f"slice set incomplete or untrusted across "
                    f"{len(dirs)} rank dir(s)")
            opened = {r: np.load(p) for r, p in slices.items()}
            try:
                for i in sharded_idx:
                    new[i] = assemble_rows(
                        {r: d[f"slice:{i}"] for r, d in opened.items()},
                        world)
            finally:
                for d in opened.values():
                    d.close()
        cur_world = self.world_info()["processes"]
        if world != cur_world:
            # loading slices written by a different world: the staging
            # below re-slices them for the live mesh
            if self._mesh_mgr is not None:
                self._mesh_mgr.reshards += 1
            _obs.count("dl4j_mesh_reshard_total")
            logger.warning(
                "sharded checkpoint step %d: resharding optimizer "
                "state from save-world %d to live world %d", step,
                world, cur_world)
        return jax.tree_util.tree_unflatten(treedef, new)

    def _orbax_path(self, step: int) -> str:
        return os.path.abspath(os.path.join(
            self.checkpoint_dir, f"step-{step}.orbax"))

    def _save_orbax(self, step: int):
        import jax
        import orbax.checkpoint as ocp

        net = self.net
        os.makedirs(self.checkpoint_dir, exist_ok=True)
        # self-describing payload (step/iteration/epoch ride inside):
        # the fallback scan can resume position without latest.json,
        # matching the npz format's contract
        payload = {"params": net.params, "upd": net.updater_states,
                   "states": net.states, "rng": np.asarray(net._rng),
                   "step": np.asarray(step),
                   "iteration": np.asarray(int(net.iteration)),
                   "epoch": np.asarray(int(net.epoch))}
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(self._orbax_path(step), payload, force=True)
        if jax.process_index() == 0:
            # integrity parity with the .npz path: per-file sha256
            # sidecar inside the orbax dir, verified before any restore
            # so the fallback scan skips torn directories
            _ci.write_tree_manifest(self._orbax_path(step))
            meta = {"step": step, "iteration": int(net.iteration),
                    "epoch": int(net.epoch), "format": "orbax"}
            _ci.atomic_write_json(
                os.path.join(self.checkpoint_dir, "latest.json"), meta)
            _ci.apply_retention(self.checkpoint_dir, self.keep_last)

    def _load_orbax(self, meta) -> int:
        import jax
        import orbax.checkpoint as ocp

        t_restore = time.perf_counter()
        net = self.net
        if net.params is None:
            net.init()
        # torn/tampered orbax dir: raise BEFORE restore so the caller's
        # fallback scan moves on to the next-newest candidate
        _ci.require_valid_tree(self._orbax_path(meta["step"]))
        with ocp.StandardCheckpointer() as ckptr:
            data = ckptr.restore(self._orbax_path(meta["step"]))
        net.params = self._replicated(data["params"])
        net.updater_states = self._replicated(data["upd"])
        net.states = self._replicated(data["states"])
        net._rng = jax.numpy.asarray(np.asarray(data["rng"]))
        # meta (latest.json) may be missing during a fallback scan;
        # newer payloads are self-describing
        if "iteration" in meta:
            net.iteration = meta["iteration"]
            net.epoch = meta["epoch"]
        elif "iteration" in data:
            net.iteration = int(np.asarray(data["iteration"]))
            net.epoch = int(np.asarray(data["epoch"]))
        self._staged = True
        _obs.count("dl4j_checkpoint_restores_total")
        _obs.observe("dl4j_checkpoint_restore_seconds",
                     time.perf_counter() - t_restore)
        return meta["step"]

    def _orbax_steps(self):
        return [s for s, fn in _ci.list_all_checkpoints(
            self.checkpoint_dir) if fn.endswith(".orbax")]

    def _restore_newest_valid_orbax(self) -> int:
        """Fallback scan parity for orbax-format checkpoints: when the
        latest pointer is damaged/missing (or points at a damaged dir),
        restore the newest orbax directory that actually loads."""
        for step in reversed(self._orbax_steps()):
            try:
                return self._load_orbax({"step": step})
            except Exception:   # noqa: BLE001 - damaged dir: try older
                continue
        return 0

    @staticmethod
    def _structural_ok(path: str) -> None:
        """Cheap structural probe: a truncated/torn .npz fails to open
        or to yield its zip directory. Raises on damage."""
        with np.load(path) as z:
            z["rng"]

    def _read_latest_meta(self):
        latest = os.path.join(self.checkpoint_dir, "latest.json")
        try:
            with open(latest) as f:
                return json.load(f)
        except (OSError, ValueError):
            # missing or torn latest pointer: fall back to a dir scan
            return None

    def _select_valid_step(self, meta) -> Optional[int]:
        """The step to restore: the latest pointer's target if it passes
        checksum + structural validation, else the newest checkpoint in
        the directory that does (SURVEY §5.3 made real: a truncated
        'latest' must never win)."""
        if meta is not None and "step" in meta:
            step = meta["step"]
            fn = os.path.basename(self._ckpt_path(step))
            if _ci.validate_file(self.checkpoint_dir, fn):
                try:
                    self._structural_ok(self._ckpt_path(step))
                    return step
                except Exception:   # noqa: BLE001 - damaged file
                    pass
        return _ci.newest_valid_checkpoint(
            self.checkpoint_dir, structural_check=self._structural_ok)

    def load_latest_checkpoint(self) -> int:
        """Restore the newest *valid* checkpoint if present; returns the
        step to resume FROM (0 if none survives validation). All
        processes load the same file. Corrupt/truncated candidates are
        skipped in favor of the newest one passing sha256 + structural
        checks."""
        if not self.checkpoint_dir or not os.path.isdir(self.checkpoint_dir):
            return 0
        meta = self._read_latest_meta()
        if meta is not None and meta.get("format") == "orbax":
            try:
                return self._load_orbax(meta)
            except Exception:   # noqa: BLE001 - damaged target: scan
                return self._restore_newest_valid_orbax()
        step = self._select_valid_step(meta)
        if step is None:
            # no valid npz: orbax dirs saved without (or with a torn)
            # latest pointer still count — retention/fallback parity
            return self._restore_newest_valid_orbax()
        return self._restore_npz(step, meta)

    def load_checkpoint_at(self, step: int) -> int:
        """Resume handshake: restore EXACTLY `step` (validated),
        raising on a missing/torn file instead of silently falling back
        — the ClusterSupervisor relaunches every rank with one shared
        resume step, and a rank whose filesystem view disagrees must
        fail loudly (and be gang-restarted) rather than resume
        elsewhere. step <= 0 means 'no checkpoint': start fresh."""
        from deeplearning4j_tpu.resilience.errors import (
            CheckpointIntegrityError,
        )

        if step <= 0:
            self._stage_net()
            return 0
        if self.checkpoint_format == "orbax":
            return self._load_orbax({"step": step})
        path = self._ckpt_path(step)
        fn = os.path.basename(path)
        if not _ci.validate_file(self.checkpoint_dir or "", fn):
            raise CheckpointIntegrityError(
                f"resume handshake: checkpoint step {step} missing or "
                f"failed validation in {self.checkpoint_dir}")
        self._structural_ok(path)
        return self._restore_npz(step, self._read_latest_meta())

    def _restore_npz(self, step: int, meta) -> int:
        t_restore = time.perf_counter()
        data = self._ckpt_retry.call(np.load, self._ckpt_path(step))
        import jax

        net = self.net
        if net.params is None:
            net.init()

        def restore(group, tree):
            leaves, treedef = jax.tree_util.tree_flatten(tree)
            new = [data[f"{group}:{i}"] for i in range(len(leaves))]
            return jax.tree_util.tree_unflatten(treedef, new)

        net.params = self._replicated(restore("params", net.params))
        if "shard_world" in data.files:
            upd = self._restore_sharded_upd(data, step)
        else:
            upd = restore("upd", net.updater_states)
        if self._mesh_mgr is not None:
            # zero1 staging re-slices the assembled state for the LIVE
            # mesh — the resharding-on-resume placement
            net.updater_states = self._mesh_mgr.shard_tree(upd)
        else:
            net.updater_states = self._replicated(upd)
        net.states = self._replicated(restore("states", net.states))
        net._rng = jax.numpy.asarray(data["rng"])
        # newer checkpoints are self-describing; latest.json only covers
        # the pre-manifest format (and may describe a different step)
        if "iteration" in data.files:
            net.iteration = int(data["iteration"])
            net.epoch = int(data["epoch"])
        elif meta is not None and meta.get("step") == step:
            net.iteration = meta["iteration"]
            net.epoch = meta["epoch"]
        self._staged = True
        _obs.count("dl4j_checkpoint_restores_total")
        _obs.observe("dl4j_checkpoint_restore_seconds",
                     time.perf_counter() - t_restore)
        return step

    def list_checkpoints(self):
        if not self.checkpoint_dir or not os.path.isdir(self.checkpoint_dir):
            return []
        out = []
        for fn in sorted(os.listdir(self.checkpoint_dir)):
            m = re.match(r"step-(\d+)\.(npz|orbax)$", fn)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)
