"""Numeric gradient checking: the framework's universal correctness oracle.

Parity: gradientcheck/GradientCheckUtil.java:77 (MLN), :238 (CG) — central
difference with eps, forced double precision, max relative error vs the
analytic gradient. Here the analytic gradient is `jax.grad`, so this
validates every layer's forward math end-to-end (autodiff makes per-layer
hand-written backprop bugs impossible, but forward bugs, stop_gradients,
and custom losses still need the oracle).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def check_gradients(net, x, y, fmask=None, lmask=None,
                    epsilon: float = 1e-6, max_rel_error: float = 1e-5,
                    min_abs_error: float = 1e-8,
                    subset: Optional[int] = None,
                    seed: int = 0, verbose: bool = False) -> bool:
    """Central-difference vs jax.grad over every parameter of `net`.

    Requires float64 (enable via `jax.enable_x64(True)` and build
    the net with dtype=jnp.float64). Raises AssertionError on failure.
    `subset`: check only this many randomly-chosen params per layer
    (for larger nets); None = all.
    """
    if net.params is None:
        net.init()
    if net.dtype != jnp.float64:
        raise ValueError(
            "gradient checks need a float64 network "
            "(MultiLayerNetwork(conf, dtype=jnp.float64) under enable_x64)")
    is_graph = hasattr(net.conf, "network_inputs")
    if is_graph:
        # ComputationGraph path (GradientCheckUtil.java:238): inputs are a
        # {name: array} dict, labels/masks are per-output lists.
        xs = x if isinstance(x, (list, tuple)) else [x]
        ys = y if isinstance(y, (list, tuple)) else [y]
        names = net.conf.network_inputs
        if len(xs) != len(names):
            raise ValueError(
                f"graph has {len(names)} inputs {names}, got {len(xs)} arrays")
        x = {name: jnp.asarray(a, jnp.float64)
             for name, a in zip(names, xs)}
        y = [jnp.asarray(a, jnp.float64) for a in ys]
        fm = None if fmask is None else {
            name: jnp.asarray(m, jnp.float64)
            for name, m in zip(net.conf.network_inputs,
                               fmask if isinstance(fmask, (list, tuple))
                               else [fmask])}
        lm = None if lmask is None else [
            jnp.asarray(m, jnp.float64)
            for m in (lmask if isinstance(lmask, (list, tuple))
                      else [lmask])]
    else:
        x = jnp.asarray(x, jnp.float64)
        y = jnp.asarray(y, jnp.float64)
        fm = None if fmask is None else jnp.asarray(fmask, jnp.float64)
        lm = None if lmask is None else jnp.asarray(lmask, jnp.float64)
    rng = jax.random.PRNGKey(seed)

    def loss(params):
        l, _ = net._loss_fn(params, net.states, x, y, rng, fm, lm,
                            train=True)
        return l

    analytic = jax.grad(loss)(net.params)

    flat_params, treedef = jax.tree_util.tree_flatten(net.params)
    flat_grads = jax.tree_util.tree_leaves(analytic)
    loss_j = jax.jit(loss)
    rs = np.random.default_rng(seed)

    total_checked = 0
    max_err = 0.0
    for li, (p, g) in enumerate(zip(flat_params, flat_grads)):
        p_np = np.array(p, np.float64)  # writable copy
        n = p_np.size
        idxs = (np.arange(n) if subset is None or n <= subset
                else rs.choice(n, size=subset, replace=False))
        for i in idxs:
            orig = p_np.flat[i]
            p_np.flat[i] = orig + epsilon
            leaves = list(flat_params)
            leaves[li] = jnp.asarray(p_np)
            lp = float(loss_j(jax.tree_util.tree_unflatten(treedef, leaves)))
            p_np.flat[i] = orig - epsilon
            leaves[li] = jnp.asarray(p_np)
            lmn = float(loss_j(jax.tree_util.tree_unflatten(treedef, leaves)))
            p_np.flat[i] = orig
            numeric = (lp - lmn) / (2 * epsilon)
            a = float(np.asarray(g).flat[i])
            denom = abs(a) + abs(numeric)
            rel = 0.0 if denom == 0 else abs(a - numeric) / denom
            if rel > max_rel_error and abs(a - numeric) > min_abs_error:
                raise AssertionError(
                    f"Gradient check FAILED: leaf {li} flat index {i}: "
                    f"analytic={a:.3e} numeric={numeric:.3e} rel={rel:.3e}")
            max_err = max(max_err, rel)
            total_checked += 1
    if verbose:
        print(f"gradient check OK: {total_checked} params, "
              f"max rel err {max_err:.3e}")
    return True
