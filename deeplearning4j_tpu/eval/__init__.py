from deeplearning4j_tpu.eval.evaluation import Evaluation, ConfusionMatrix  # noqa: F401
from deeplearning4j_tpu.eval.regression import RegressionEvaluation  # noqa: F401
from deeplearning4j_tpu.eval.roc import ROC, ROCBinary, ROCMultiClass  # noqa: F401
from deeplearning4j_tpu.eval.binary import EvaluationBinary  # noqa: F401
from deeplearning4j_tpu.eval.calibration import EvaluationCalibration  # noqa: F401
from deeplearning4j_tpu.eval.tools import (  # noqa: F401
    export_evaluation_calibration_to_html,
    export_roc_charts_to_html,
)
