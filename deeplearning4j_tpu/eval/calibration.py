"""EvaluationCalibration: reliability diagrams, residual plots,
probability histograms.

Parity: eval/EvaluationCalibration.java — accumulates per-bin counts of
predicted probability vs empirical accuracy (reliability), |label - p|
residuals, and predicted-probability histograms; plus expected
calibration error as the summary scalar."""

from __future__ import annotations

from typing import Optional

import numpy as np


class EvaluationCalibration:
    """Accumulate with eval(labels, predictions) per batch
    (labels one-hot [N, C], predictions probabilities [N, C])."""

    def __init__(self, reliability_bins: int = 10,
                 histogram_bins: int = 50):
        self.reliability_bins = reliability_bins
        self.histogram_bins = histogram_bins
        self._counts = None        # [C, bins] predictions per bin
        self._correct = None       # [C, bins] correct predictions per bin
        self._prob_sum = None      # [C, bins] sum of predicted prob
        self._residual_hist = None # [bins] |label - p| histogram (all)
        self._prob_hist = None     # [C, bins] predicted prob histogram
        self.num_classes = None

    def eval(self, labels, predictions, mask: Optional[np.ndarray] = None):
        labels = np.asarray(labels, np.float64)
        p = np.asarray(predictions, np.float64)
        if labels.ndim != 2:
            raise ValueError("labels must be one-hot [N, C]")
        n, c = labels.shape
        if self._counts is None:
            self.num_classes = c
            b = self.reliability_bins
            self._counts = np.zeros((c, b), np.int64)
            self._correct = np.zeros((c, b), np.int64)
            self._prob_sum = np.zeros((c, b), np.float64)
            self._residual_hist = np.zeros(self.histogram_bins, np.int64)
            self._prob_hist = np.zeros((c, self.histogram_bins), np.int64)
        if mask is not None:
            keep = np.asarray(mask).astype(bool).reshape(-1)
            labels, p = labels[keep], p[keep]
            n = labels.shape[0]
        if n == 0:
            return self
        b = self.reliability_bins
        bins = np.clip((p * b).astype(int), 0, b - 1)       # [N, C]
        correct = labels > 0.5                              # [N, C]
        for ci in range(c):
            np.add.at(self._counts[ci], bins[:, ci], 1)
            np.add.at(self._correct[ci], bins[:, ci],
                      correct[:, ci].astype(np.int64))
            np.add.at(self._prob_sum[ci], bins[:, ci], p[:, ci])
            hb = np.clip((p[:, ci] * self.histogram_bins).astype(int),
                         0, self.histogram_bins - 1)
            np.add.at(self._prob_hist[ci], hb, 1)
        res = np.abs(labels - p).reshape(-1)
        rb = np.clip((res * self.histogram_bins).astype(int), 0,
                     self.histogram_bins - 1)
        np.add.at(self._residual_hist, rb, 1)
        return self

    # ------------------------------------------------------------- queries
    def reliability_info(self, class_idx: int):
        """(mean predicted prob per bin, empirical frequency per bin,
        counts per bin) — the reliability diagram
        (ref getReliabilityDiagram)."""
        cnt = self._counts[class_idx]
        safe = np.maximum(cnt, 1)
        mean_p = self._prob_sum[class_idx] / safe
        freq = self._correct[class_idx] / safe
        return mean_p, freq, cnt.copy()

    def expected_calibration_error(self, class_idx: Optional[int] = None
                                   ) -> float:
        """ECE = sum_b (n_b / N) |acc_b - conf_b| (macro over classes if
        class_idx is None)."""
        idxs = (range(self.num_classes) if class_idx is None
                else [class_idx])
        eces = []
        for ci in idxs:
            mean_p, freq, cnt = self.reliability_info(ci)
            total = max(cnt.sum(), 1)
            eces.append(float(np.sum(cnt / total * np.abs(freq - mean_p))))
        return float(np.mean(eces))

    def residual_plot(self):
        """(bin_edges, counts) of |label - p| (ref getResidualPlot)."""
        edges = np.linspace(0, 1, self.histogram_bins + 1)
        return edges, self._residual_hist.copy()

    def probability_histogram(self, class_idx: int):
        """(bin_edges, counts) of predicted P(class) (ref
        getProbabilityHistogram)."""
        edges = np.linspace(0, 1, self.histogram_bins + 1)
        return edges, self._prob_hist[class_idx].copy()

    def stats(self) -> str:
        lines = ["EvaluationCalibration "
                 f"(bins={self.reliability_bins}):"]
        for ci in range(self.num_classes):
            lines.append(f"  class {ci}: ECE="
                         f"{self.expected_calibration_error(ci):.4f}")
        lines.append(f"  macro ECE={self.expected_calibration_error():.4f}")
        return "\n".join(lines)
