"""ROC / AUC evaluation.

Parity: eval/ROC.java, ROCBinary.java, ROCMultiClass.java + eval/curves/.
The reference uses `thresholdSteps` binning; we accumulate exact score
histograms per batch with fixed bins (default 200 steps like the reference's
default), giving O(bins) memory independent of dataset size.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


class _BinnedRoc:
    """TPR/FPR via score binning in [0, 1]."""

    def __init__(self, threshold_steps: int = 200):
        self.bins = threshold_steps
        self.pos_hist = np.zeros(self.bins, dtype=np.int64)
        self.neg_hist = np.zeros(self.bins, dtype=np.int64)

    def add(self, scores: np.ndarray, is_positive: np.ndarray):
        idx = np.clip((scores * self.bins).astype(np.int64), 0, self.bins - 1)
        np.add.at(self.pos_hist, idx[is_positive], 1)
        np.add.at(self.neg_hist, idx[~is_positive], 1)

    def curve(self) -> Tuple[np.ndarray, np.ndarray]:
        """Returns (fpr, tpr) arrays from highest threshold to lowest."""
        # cumulate from the top bin down: predictions >= threshold
        pos_cum = np.cumsum(self.pos_hist[::-1])
        neg_cum = np.cumsum(self.neg_hist[::-1])
        P = max(int(self.pos_hist.sum()), 1)
        N = max(int(self.neg_hist.sum()), 1)
        tpr = np.concatenate([[0.0], pos_cum / P])
        fpr = np.concatenate([[0.0], neg_cum / N])
        return fpr, tpr

    def auc(self) -> float:
        fpr, tpr = self.curve()
        return float(np.trapezoid(tpr, fpr))

    def precision_recall(self) -> Tuple[np.ndarray, np.ndarray]:
        """(precision, recall) from highest threshold to lowest
        (ref eval/curves/PrecisionRecallCurve.java)."""
        pos_cum = np.cumsum(self.pos_hist[::-1])
        neg_cum = np.cumsum(self.neg_hist[::-1])
        P = max(int(self.pos_hist.sum()), 1)
        predicted = pos_cum + neg_cum
        # no predicted positives -> precision defined as 1.0 (ref
        # PrecisionRecallCurve semantics)
        precision = np.where(predicted > 0,
                             pos_cum / np.maximum(predicted, 1), 1.0)
        precision = np.concatenate([[1.0], precision])
        recall = np.concatenate([[0.0], pos_cum / P])
        return precision, recall


class ROC:
    """Binary-problem ROC: labels [N, 1] (0/1) or [N, 2] one-hot; scores are
    P(class=1)."""

    def __init__(self, threshold_steps: int = 200):
        self._roc = _BinnedRoc(threshold_steps)

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:
            labels = labels.reshape(-1, labels.shape[-1])
            predictions = predictions.reshape(-1, predictions.shape[-1])
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            labels, predictions = labels[m], predictions[m]
        if labels.shape[-1] == 2:
            pos = labels[:, 1] >= 0.5
            score = predictions[:, 1]
        else:
            pos = labels[:, 0] >= 0.5
            score = predictions[:, 0]
        self._roc.add(score, pos)

    def calculate_auc(self) -> float:
        return self._roc.auc()

    auc = calculate_auc

    def get_roc_curve(self):
        return self._roc.curve()

    roc_curve = get_roc_curve

    def precision_recall_curve(self):
        return self._roc.precision_recall()


class ROCBinary:
    """Per-output-column ROC for multi-label binary outputs."""

    def __init__(self, threshold_steps: int = 200):
        self.steps = threshold_steps
        self._rocs = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:
            labels = labels.reshape(-1, labels.shape[-1])
            predictions = predictions.reshape(-1, predictions.shape[-1])
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            labels, predictions = labels[m], predictions[m]
        if self._rocs is None:
            self._rocs = [_BinnedRoc(self.steps) for _ in range(labels.shape[-1])]
        for c, roc in enumerate(self._rocs):
            roc.add(predictions[:, c], labels[:, c] >= 0.5)

    def calculate_auc(self, col: int) -> float:
        return self._rocs[col].auc()

    def average_auc(self) -> float:
        return float(np.mean([r.auc() for r in self._rocs]))


class ROCMultiClass:
    """One-vs-all ROC per class for softmax outputs."""

    def __init__(self, threshold_steps: int = 200):
        self.steps = threshold_steps
        self._rocs = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:
            labels = labels.reshape(-1, labels.shape[-1])
            predictions = predictions.reshape(-1, predictions.shape[-1])
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            labels, predictions = labels[m], predictions[m]
        if self._rocs is None:
            self._rocs = [_BinnedRoc(self.steps) for _ in range(labels.shape[-1])]
        actual = labels.argmax(axis=-1)
        for c, roc in enumerate(self._rocs):
            roc.add(predictions[:, c], actual == c)

    def calculate_auc(self, cls: int) -> float:
        return self._rocs[cls].auc()

    def average_auc(self) -> float:
        return float(np.mean([r.auc() for r in self._rocs]))
