"""Classification evaluation: accuracy/precision/recall/F1 + confusion matrix.

Parity: eval/Evaluation.java (`eval`:288, `stats()`:502, `f1`:978) and
eval/ConfusionMatrix.java. Accumulates over batches like the reference
(call `eval(labels, predictions)` per batch, read metrics at the end).
Counts accumulate in a host-side numpy confusion matrix — evaluation is not
a hot path; the argmax runs on device.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np


class ConfusionMatrix:
    def __init__(self, num_classes: int):
        self.num_classes = num_classes
        self.matrix = np.zeros((num_classes, num_classes), dtype=np.int64)

    def add(self, actual: int, predicted: int, count: int = 1):
        self.matrix[actual, predicted] += count

    def add_batch(self, actual: np.ndarray, predicted: np.ndarray):
        np.add.at(self.matrix, (actual, predicted), 1)

    def get_count(self, actual: int, predicted: int) -> int:
        return int(self.matrix[actual, predicted])

    def actual_total(self, cls: int) -> int:
        return int(self.matrix[cls].sum())

    def predicted_total(self, cls: int) -> int:
        return int(self.matrix[:, cls].sum())

    def total(self) -> int:
        return int(self.matrix.sum())

    def __str__(self):
        return str(self.matrix)


class Evaluation:
    def __init__(self, num_classes: Optional[int] = None,
                 labels: Optional[List[str]] = None):
        self.label_names = labels
        if labels is not None and num_classes is None:
            num_classes = len(labels)
        self.num_classes = num_classes
        self.confusion: Optional[ConfusionMatrix] = None
        self.top_n_correct = 0
        self.top_n_total = 0

    def _ensure(self, n: int):
        if self.confusion is None:
            self.num_classes = self.num_classes or n
            self.confusion = ConfusionMatrix(self.num_classes)

    def eval(self, labels, predictions, mask=None, top_n: int = 1):
        """Accumulate a batch. labels/predictions: [N, C] (one-hot / prob)
        or [N, T, C] time series with optional [N, T] mask."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:
            if mask is not None:
                m = np.asarray(mask).reshape(-1).astype(bool)
            else:
                m = np.ones(labels.shape[0] * labels.shape[1], dtype=bool)
            labels = labels.reshape(-1, labels.shape[-1])[m]
            predictions = predictions.reshape(-1, predictions.shape[-1])[m]
        elif mask is not None:
            # [N] example mask on 2D input: drop masked-out rows (e.g. DP
            # batch padding) so they don't enter the confusion matrix
            m = np.asarray(mask).reshape(-1).astype(bool)
            labels, predictions = labels[m], predictions[m]
        self._ensure(labels.shape[-1])
        actual = labels.argmax(axis=-1)
        pred = predictions.argmax(axis=-1)
        self.confusion.add_batch(actual, pred)
        if top_n > 1:
            topk = np.argsort(-predictions, axis=-1)[:, :top_n]
            self.top_n_correct += int((topk == actual[:, None]).any(axis=1).sum())
            self.top_n_total += len(actual)

    # ---- metrics ----
    def accuracy(self) -> float:
        m = self.confusion.matrix
        total = m.sum()
        return float(np.trace(m) / total) if total else 0.0

    def top_n_accuracy(self) -> float:
        return self.top_n_correct / self.top_n_total if self.top_n_total else 0.0

    def true_positives(self, cls: int) -> int:
        return self.confusion.get_count(cls, cls)

    def false_positives(self, cls: int) -> int:
        return self.confusion.predicted_total(cls) - self.true_positives(cls)

    def false_negatives(self, cls: int) -> int:
        return self.confusion.actual_total(cls) - self.true_positives(cls)

    def precision(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            denom = self.confusion.predicted_total(cls)
            return self.true_positives(cls) / denom if denom else 0.0
        vals = [self.precision(c) for c in range(self.num_classes)
                if self.confusion.actual_total(c) > 0
                or self.confusion.predicted_total(c) > 0]
        return float(np.mean(vals)) if vals else 0.0

    def recall(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            denom = self.confusion.actual_total(cls)
            return self.true_positives(cls) / denom if denom else 0.0
        vals = [self.recall(c) for c in range(self.num_classes)
                if self.confusion.actual_total(c) > 0]
        return float(np.mean(vals)) if vals else 0.0

    def f1(self, cls: Optional[int] = None) -> float:
        p = self.precision(cls)
        r = self.recall(cls)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def matthews_correlation(self, cls: int) -> float:
        tp = self.true_positives(cls)
        fp = self.false_positives(cls)
        fn = self.false_negatives(cls)
        tn = self.confusion.total() - tp - fp - fn
        denom = np.sqrt(float((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn)))
        return ((tp * tn - fp * fn) / denom) if denom else 0.0

    def stats(self) -> str:
        """Pretty report (ref: Evaluation.stats():502)."""
        lines = ["========================Scores========================"]
        lines.append(f" # of classes:    {self.num_classes}")
        lines.append(f" Accuracy:        {self.accuracy():.4f}")
        lines.append(f" Precision:       {self.precision():.4f}")
        lines.append(f" Recall:          {self.recall():.4f}")
        lines.append(f" F1 Score:        {self.f1():.4f}")
        if self.top_n_total:
            lines.append(f" Top-N Accuracy:  {self.top_n_accuracy():.4f}")
        lines.append("======================================================")
        lines.append("Confusion matrix (rows=actual, cols=predicted):")
        lines.append(str(self.confusion))
        return "\n".join(lines)

    def merge(self, other: "Evaluation"):
        """Combine accumulated counts (the distributed-eval reduce step,
        ref: spark IEvaluationReduceFunction)."""
        if other.confusion is None:
            return self
        self._ensure(other.num_classes)
        self.confusion.matrix += other.confusion.matrix
        self.top_n_correct += other.top_n_correct
        self.top_n_total += other.top_n_total
        return self
