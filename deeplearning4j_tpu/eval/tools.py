"""EvaluationTools: HTML exports for ROC and calibration.

Parity: deeplearning4j-core evaluation/EvaluationTools.java:107
(exportRocChartsToHtmlFile, exportevaluationCalibrationToHtmlFile) —
self-contained dependency-free HTML with inline SVG, same approach as
stats/dashboard.py."""

from __future__ import annotations

import json
from typing import Optional


_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>{title}</title>
<style>
 body {{ font-family: system-ui, sans-serif; margin: 24px; color: #222; }}
 .row {{ display: flex; flex-wrap: wrap; gap: 24px; }}
 .chart {{ border: 1px solid #ddd; border-radius: 6px; padding: 8px; }}
 .lbl {{ font-size: 12px; fill: #555; text-anchor: middle; }}
</style></head><body>
<h1>{title}</h1>{meta}
<div id="charts" class="row"></div>
<script>
const DATA = {data};
function line(pts, w, h, color, diag) {{
  const sx = v => 30 + (w - 40) * v, sy = v => (h - 25) - (h - 40) * v;
  let out = '';
  if (diag) out += `<path d="M${{sx(0)}} ${{sy(0)}} L${{sx(1)}} ${{sy(1)}}"
     stroke="#bbb" stroke-dasharray="4" fill="none"/>`;
  if (pts.length)
    out += '<path d="' + pts.map((p, i) =>
      (i ? 'L' : 'M') + sx(p[0]).toFixed(1) + ' ' + sy(p[1]).toFixed(1))
      .join(' ') + `" fill="none" stroke="${{color}}" stroke-width="1.5"/>`;
  return out;
}}
function chart(title, pts, color, diag) {{
  const w = 360, h = 300;
  return `<div class="chart"><svg width="${{w}}" height="${{h}}">` +
    line(pts, w, h, color, diag) +
    `<text class="lbl" x="${{w / 2}}" y="${{h - 6}}">${{title}}</text>` +
    `</svg></div>`;
}}
let html = '';
for (const c of DATA.charts) html += chart(c.title, c.points, c.color,
                                           c.diagonal);
document.getElementById('charts').innerHTML = html;
</script></body></html>
"""


def _render(title, meta, charts, path):
    page = _PAGE.format(title=title, meta=meta,
                        data=json.dumps({"charts": charts}))
    if path:
        with open(path, "w") as f:
            f.write(page)
    return page


def export_roc_charts_to_html(roc, path: Optional[str] = None) -> str:
    """ROC + precision/recall curves (ref exportRocChartsToHtmlFile)."""
    fpr, tpr = roc.get_roc_curve()
    prec, rec = roc.precision_recall_curve()
    charts = [
        {"title": f"ROC (AUC={roc.auc():.4f})", "color": "#c0392b",
         "diagonal": True,
         "points": [[float(a), float(b)] for a, b in zip(fpr, tpr)]},
        {"title": "Precision vs Recall", "color": "#2c6fad",
         "diagonal": False,
         "points": [[float(a), float(b)] for a, b in zip(rec, prec)]},
    ]
    meta = f"<p>AUC: {roc.auc():.4f}</p>"
    return _render("ROC", meta, charts, path)


def export_evaluation_calibration_to_html(
        calibration, path: Optional[str] = None) -> str:
    """Reliability diagrams per class + residual histogram line
    (ref EvaluationTools calibration export)."""
    charts = []
    for ci in range(calibration.num_classes):
        mean_p, freq, cnt = calibration.reliability_info(ci)
        pts = [[float(p), float(f)] for p, f, n in
               zip(mean_p, freq, cnt) if n > 0]
        charts.append({
            "title": f"reliability class {ci} "
                     f"(ECE={calibration.expected_calibration_error(ci):.3f})",
            "color": "#27ae60", "diagonal": True, "points": pts})
    edges, res = calibration.residual_plot()
    total = max(int(res.sum()), 1)
    charts.append({
        "title": "residual |label-p| histogram", "color": "#8e44ad",
        "diagonal": False,
        "points": [[float(edges[i]), float(res[i]) / total]
                   for i in range(len(res))]})
    meta = (f"<p>macro ECE: "
            f"{calibration.expected_calibration_error():.4f}</p>")
    return _render("Calibration", meta, charts, path)
