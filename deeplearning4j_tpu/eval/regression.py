"""Regression evaluation: MSE, MAE, RMSE, RSE, PC (Pearson), R^2 per column.

Parity: eval/RegressionEvaluation.java — accumulates sufficient statistics
per output column across batches.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


class RegressionEvaluation:
    def __init__(self, n_columns: Optional[int] = None,
                 column_names: Optional[List[str]] = None):
        self.column_names = column_names
        if column_names is not None and n_columns is None:
            n_columns = len(column_names)
        self.n = n_columns
        self._initialized = False

    def _ensure(self, n):
        if not self._initialized:
            self.n = self.n or n
            z = lambda: np.zeros(self.n)
            self.count = z()
            self.sum_abs_err = z()
            self.sum_sq_err = z()
            self.sum_label = z()
            self.sum_label_sq = z()
            self.sum_pred = z()
            self.sum_pred_sq = z()
            self.sum_label_pred = z()
            self._initialized = True

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, dtype=np.float64)
        predictions = np.asarray(predictions, dtype=np.float64)
        if labels.ndim == 3:
            if mask is not None:
                m = np.asarray(mask).reshape(-1).astype(bool)
            else:
                m = np.ones(labels.shape[0] * labels.shape[1], dtype=bool)
            labels = labels.reshape(-1, labels.shape[-1])[m]
            predictions = predictions.reshape(-1, predictions.shape[-1])[m]
        self._ensure(labels.shape[-1])
        err = predictions - labels
        self.count += len(labels)
        self.sum_abs_err += np.abs(err).sum(axis=0)
        self.sum_sq_err += (err * err).sum(axis=0)
        self.sum_label += labels.sum(axis=0)
        self.sum_label_sq += (labels * labels).sum(axis=0)
        self.sum_pred += predictions.sum(axis=0)
        self.sum_pred_sq += (predictions * predictions).sum(axis=0)
        self.sum_label_pred += (labels * predictions).sum(axis=0)

    def mean_squared_error(self, col: int) -> float:
        return float(self.sum_sq_err[col] / self.count[col])

    def mean_absolute_error(self, col: int) -> float:
        return float(self.sum_abs_err[col] / self.count[col])

    def root_mean_squared_error(self, col: int) -> float:
        return float(np.sqrt(self.mean_squared_error(col)))

    def relative_squared_error(self, col: int) -> float:
        n = self.count[col]
        mean_label = self.sum_label[col] / n
        denom = self.sum_label_sq[col] - n * mean_label**2
        return float(self.sum_sq_err[col] / denom) if denom else float("inf")

    def pearson_correlation(self, col: int) -> float:
        n = self.count[col]
        cov = self.sum_label_pred[col] - self.sum_label[col] * self.sum_pred[col] / n
        var_l = self.sum_label_sq[col] - self.sum_label[col] ** 2 / n
        var_p = self.sum_pred_sq[col] - self.sum_pred[col] ** 2 / n
        denom = np.sqrt(var_l * var_p)
        return float(cov / denom) if denom else 0.0

    def r_squared(self, col: int) -> float:
        n = self.count[col]
        mean_label = self.sum_label[col] / n
        ss_tot = self.sum_label_sq[col] - n * mean_label**2
        return float(1.0 - self.sum_sq_err[col] / ss_tot) if ss_tot else 0.0

    def average_mean_squared_error(self) -> float:
        return float(np.mean([self.mean_squared_error(c) for c in range(self.n)]))

    def average_mean_absolute_error(self) -> float:
        return float(np.mean([self.mean_absolute_error(c) for c in range(self.n)]))

    def stats(self) -> str:
        lines = ["Column    MSE          MAE          RMSE         RSE          R^2"]
        for c in range(self.n):
            name = (self.column_names[c] if self.column_names
                    else f"col_{c}")
            lines.append(
                f"{name:<9} {self.mean_squared_error(c):<12.5g} "
                f"{self.mean_absolute_error(c):<12.5g} "
                f"{self.root_mean_squared_error(c):<12.5g} "
                f"{self.relative_squared_error(c):<12.5g} "
                f"{self.r_squared(c):<12.5g}")
        return "\n".join(lines)

    def merge(self, other: "RegressionEvaluation"):
        if not getattr(other, "_initialized", False):
            return self
        self._ensure(other.n)
        for attr in ("count", "sum_abs_err", "sum_sq_err", "sum_label",
                     "sum_label_sq", "sum_pred", "sum_pred_sq",
                     "sum_label_pred"):
            setattr(self, attr, getattr(self, attr) + getattr(other, attr))
        return self
