"""Multi-label binary evaluation.

Parity: eval/EvaluationBinary.java — per-output-column binary counts at a
0.5 decision threshold, accuracy/precision/recall/F1 per column.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class EvaluationBinary:
    def __init__(self, n_columns: Optional[int] = None, threshold: float = 0.5):
        self.n = n_columns
        self.threshold = threshold
        self._initialized = False

    def _ensure(self, n):
        if not self._initialized:
            self.n = self.n or n
            self.tp = np.zeros(self.n, dtype=np.int64)
            self.fp = np.zeros(self.n, dtype=np.int64)
            self.tn = np.zeros(self.n, dtype=np.int64)
            self.fn = np.zeros(self.n, dtype=np.int64)
            self._initialized = True

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:
            labels = labels.reshape(-1, labels.shape[-1])
            predictions = predictions.reshape(-1, predictions.shape[-1])
            if mask is not None:
                m = np.asarray(mask).reshape(-1).astype(bool)
                labels, predictions = labels[m], predictions[m]
        self._ensure(labels.shape[-1])
        pred = predictions >= self.threshold
        actual = labels >= 0.5
        self.tp += (pred & actual).sum(axis=0)
        self.fp += (pred & ~actual).sum(axis=0)
        self.tn += (~pred & ~actual).sum(axis=0)
        self.fn += (~pred & actual).sum(axis=0)

    def accuracy(self, col: int) -> float:
        total = self.tp[col] + self.fp[col] + self.tn[col] + self.fn[col]
        return float((self.tp[col] + self.tn[col]) / total) if total else 0.0

    def precision(self, col: int) -> float:
        d = self.tp[col] + self.fp[col]
        return float(self.tp[col] / d) if d else 0.0

    def recall(self, col: int) -> float:
        d = self.tp[col] + self.fn[col]
        return float(self.tp[col] / d) if d else 0.0

    def f1(self, col: int) -> float:
        p, r = self.precision(col), self.recall(col)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def average_accuracy(self) -> float:
        return float(np.mean([self.accuracy(c) for c in range(self.n)]))

    def stats(self) -> str:
        lines = ["Column    Acc      Prec     Recall   F1"]
        for c in range(self.n):
            lines.append(
                f"col_{c:<5} {self.accuracy(c):<8.4f} {self.precision(c):<8.4f} "
                f"{self.recall(c):<8.4f} {self.f1(c):<8.4f}")
        return "\n".join(lines)

    def merge(self, other: "EvaluationBinary"):
        if not getattr(other, "_initialized", False):
            return self
        self._ensure(other.n)
        self.tp += other.tp
        self.fp += other.fp
        self.tn += other.tn
        self.fn += other.fn
        return self
