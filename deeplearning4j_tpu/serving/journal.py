"""Write-ahead generation journal: durable serving state on disk.

PR 16 made an in-flight generation survive anything short of losing
every replica — this module closes that qualifier. The decode engine's
replay discipline (re-prefill of the ORIGINAL prompt + forced replay
of the recorded tokens is bitwise-identical — serving/continuous.py)
means the minimal replayable state of ANY generation is just
`(prompt, params, tokens-so-far)`. The journal persists exactly that,
write-ahead:

  admitted{id, tenant, prompt, params, deadline}
          appended BEFORE the request becomes visible to the step
          loop — the WAL ordering that makes recovery complete
  progress{id, start, tokens}
          absolute-positioned token deltas from the step loop.
          Idempotent by construction: replaying a progress record
          twice lands the same tokens at the same positions
  done{id, finish_reason}
          terminal states a restart must NOT resurrect (eos / length /
          deadline / cancelled / poisoned / shed / unrecoverable).
          Crash-shaped finishes (ShutdownError on engine stop,
          watchdog restart exhaustion) are deliberately NOT journaled:
          those streams stay live on disk, which is exactly what makes
          them recoverable after a cold restart.

Record framing (torn-tail safety): every record is
`<u32 len><sha256(payload)><payload json>`. Appends go to the head
segment and are group-fsync'd on a configurable interval / byte
threshold; a crash mid-append leaves a torn tail that recovery
TRUNCATES back to the last whole record — the checkpoint_integrity
newest-valid discipline applied to a log instead of a snapshot.

Segments (`seg-%08d.wal`) rotate at `segment_bytes`; rotation runs
compaction: every LIVE request is consolidated (admitted + one
progress record at its current state) into a fresh segment published
atomically via `checkpoint_integrity.atomic_writer`, a new empty head
opens AFTER it, and every older segment is deleted. Idempotent replay
makes a kill at ANY point of compaction safe — old segments and the
consolidated one replay to the same live set, and recovery scans
whatever segments survive, oldest to newest.

`frame_record` / `read_records` / `write_records` are the shared
framing: FleetController persists its hold-down ledger and autoscaler
target through the same helpers, so a restarted controller refuses to
re-canary a held build.

Chaos points (resilience/faults.py):
  journal.write_torn      fired with the head segment path right after
                          an append lands — a `truncate` spec mauls
                          the tail, the torn-write drill
  journal.fsync_fail      fired just before the group os.fsync —
                          `raise` is consumed by keeping the unsynced
                          bytes pending (the next flush retries);
                          durability degrades, serving continues
  journal.recover_corrupt fired once per replayed record during the
                          recovery scan — `raise` declares THAT record
                          corrupt: treated as a torn tail, the segment
                          truncated to the records before it
"""

from __future__ import annotations

import json
import os
import shutil
import struct
import threading
import time
import weakref
from hashlib import sha256
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.observability import metrics as _obs
from deeplearning4j_tpu.resilience.checkpoint_integrity import (
    atomic_writer,
)
from deeplearning4j_tpu.resilience.errors import FaultInjectedError
from deeplearning4j_tpu.resilience.faults import fire as _fire

_LEN = struct.Struct("<I")
_DIGEST = 32                       # sha256 digest bytes per record
SEGMENT_PREFIX = "seg-"
SEGMENT_SUFFIX = ".wal"

# every journal constructed in this process (weak — dead journals drop
# out); tests/conftest.py closes whatever a failed durability test left
# open so no WAL file handle leaks into later tier-1 tests
_LIVE_JOURNALS: "weakref.WeakSet[GenerationJournal]" = weakref.WeakSet()
# mkdtemp dirs handed out by `ephemeral_journal_dir` — reaped with the
# journals so an interrupted bench/test run leaks no /tmp litter
_EPHEMERAL_DIRS: List[str] = []


def reap_stray_journals() -> None:
    """Close every journal still open and remove tracked ephemeral
    dirs. Teardown backstop for chaos tests — idempotent, touches
    nothing if every journal was closed properly."""
    for j in list(_LIVE_JOURNALS):
        j.close()
    while _EPHEMERAL_DIRS:
        shutil.rmtree(_EPHEMERAL_DIRS.pop(), ignore_errors=True)


def ephemeral_journal_dir(prefix: str = "dl4j-journal-") -> str:
    """A mkdtemp journal dir tracked for teardown (bench/drill use —
    tests prefer tmp_path): `reap_stray_journals` removes it."""
    import tempfile

    d = tempfile.mkdtemp(prefix=prefix)
    _EPHEMERAL_DIRS.append(d)
    return d


# ------------------------------------------------------- record framing
def frame_record(rec: dict) -> bytes:
    """One framed record: `<u32 len><sha256(payload)><payload>`. The
    payload is canonical JSON (sorted keys, no whitespace), so framing
    the same dict twice yields identical bytes — recovery relies on
    this to recompute valid-prefix lengths."""
    payload = json.dumps(rec, sort_keys=True,
                         separators=(",", ":")).encode()
    return _LEN.pack(len(payload)) + sha256(payload).digest() + payload


def read_records(path: str) -> Tuple[List[dict], int, int]:
    """Parse the longest valid record prefix of `path`: returns
    (records, valid_bytes, file_bytes). valid_bytes < file_bytes means
    a torn tail (a crash mid-append) — everything past the last whole
    record is ignored, and the caller may truncate it away."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        return [], 0, 0
    records: List[dict] = []
    off, n = 0, len(blob)
    while off + _LEN.size + _DIGEST <= n:
        (plen,) = _LEN.unpack_from(blob, off)
        start = off + _LEN.size + _DIGEST
        end = start + plen
        if end > n:
            break
        if sha256(blob[start:end]).digest() \
                != blob[off + _LEN.size:start]:
            break
        try:
            rec = json.loads(blob[start:end].decode())
        except (ValueError, UnicodeDecodeError):
            break
        if not isinstance(rec, dict):
            break
        records.append(rec)
        off = end
    return records, off, n


def write_records(path: str, records: List[dict]) -> None:
    """Atomically publish `records` as one framed file (write tmp,
    fsync, rename — checkpoint_integrity.atomic_writer): readers see
    the old file or the new one, never a half-written hybrid. Shared
    by journal compaction and FleetController state persistence."""
    blob = b"".join(frame_record(r) for r in records)
    with atomic_writer(path) as tmp:
        with open(tmp, "wb") as f:
            f.write(blob)


class GenerationJournal:
    """Per-replica write-ahead generation journal.

    Thread-safe: bookkeeping AND file appends serialize under one io
    lock (the lock's whole job is the blocking resource, the
    concurrency lint's file-lock exemption). Construction recovers:
    every segment is scanned oldest to newest, each record replayed
    idempotently, torn tails truncated in place; `live()` then holds
    every request a crash interrupted, ready for the engine's
    resume_tokens replay path.

    `fsync_interval_s=0` fsyncs every append (strict durability);
    otherwise appends buffer until the interval elapses or
    `fsync_bytes` of unsynced records accumulate — group commit. The
    window bounds what a POWER loss could lose to the last interval;
    a plain process kill loses nothing (appends are flushed to the OS
    on every write), and recovery replay regenerates trailing tokens
    bitwise anyway."""

    def __init__(self, directory, fsync_interval_s: float = 0.05,
                 fsync_bytes: int = 64 * 1024,
                 segment_bytes: int = 1 << 20,
                 clock=time.monotonic):
        self.directory = os.fspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.fsync_interval_s = float(fsync_interval_s)
        self.fsync_bytes = int(fsync_bytes)
        self.segment_bytes = int(segment_bytes)
        self._clock = clock
        self._io_lock = threading.Lock()
        # rid -> {prompt, max_new_tokens, eos_id, tenant, deadline_s,
        #         tokens, done, finish_reason}
        self._requests: Dict[str, dict] = {}
        self._live_count = 0       # maintained by _replay, O(1) stats
        self._records = 0
        self._fsyncs = 0
        self._fsync_failures = 0
        self._torn_tails = 0
        self._compactions = 0
        self._bytes = 0            # framed bytes across segments
        self._unsynced = 0
        self._last_sync = self._clock()
        self._head_f = None
        self._head_index = 0
        self._head_pathname = self._seg_path(0)
        self._head_bytes = 0
        self._closed = False
        # deferred metric deltas: counted under the io lock, emitted
        # outside it by _emit (the repo-wide emission discipline)
        self._pend_records = 0
        self._pend_fsyncs = 0
        self._pend_compactions = 0
        torn = self._recover()
        self._open_head()
        _LIVE_JOURNALS.add(self)
        if torn:
            self._torn_tails += torn
            _obs.count("dl4j_journal_torn_tails_total", n=torn)
        self._emit()

    # ---------------------------------------------------------- segments
    def _segments(self) -> List[str]:
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        segs = sorted(n for n in names
                      if n.startswith(SEGMENT_PREFIX)
                      and n.endswith(SEGMENT_SUFFIX))
        return [os.path.join(self.directory, n) for n in segs]

    def _seg_path(self, index: int) -> str:
        return os.path.join(
            self.directory,
            f"{SEGMENT_PREFIX}{index:08d}{SEGMENT_SUFFIX}")

    @staticmethod
    def _seg_index(path: str) -> int:
        name = os.path.basename(path)
        return int(name[len(SEGMENT_PREFIX):-len(SEGMENT_SUFFIX)])

    def _head_path(self) -> str:
        return self._head_pathname

    def _open_head(self) -> None:
        """Open a FRESH head segment past every existing one — recovery
        never appends to a segment an earlier process wrote, so a torn
        tail can never be buried under new valid records."""
        segs = self._segments()
        if segs:
            self._head_index = self._seg_index(segs[-1]) + 1
        self._head_pathname = self._seg_path(self._head_index)
        self._head_f = open(self._head_pathname, "ab")
        self._head_bytes = 0

    # ---------------------------------------------------------- recovery
    def _recover(self) -> int:
        """Scan all segments oldest to newest, replay each record
        idempotently, truncate torn tails in place. Returns the number
        of torn tails truncated."""
        torn = 0
        total = 0
        for path in self._segments():
            records, valid, size = read_records(path)
            seg_torn = valid < size
            replayed = 0
            for rec in records:
                try:
                    # `journal.recover_corrupt` chaos: a raise verdict
                    # declares THIS record corrupt — it and everything
                    # after it are a torn tail, truncated like one
                    _fire("journal.recover_corrupt")
                except FaultInjectedError:
                    seg_torn = True
                    # canonical framing: re-framing the replayed
                    # prefix recomputes its exact byte length
                    valid = sum(len(frame_record(r))
                                for r in records[:replayed])
                    break
                self._replay(rec)
                replayed += 1
            if seg_torn:
                torn += 1
                try:
                    with open(path, "r+b") as f:
                        f.truncate(valid)
                    size = valid
                except OSError:
                    pass
            total += size
        self._bytes = total
        return torn

    def _replay(self, rec: dict) -> None:
        """Apply one record to the in-memory request map. Idempotent:
        duplicate admits are ignored, progress placement is absolute,
        done is terminal — so recovery may replay overlapping segments
        (mid-compaction kills) and land in the same state."""
        kind = rec.get("kind")
        rid = rec.get("id")
        if not rid:
            return
        if kind == "admitted":
            rid = str(rid)
            if rid not in self._requests:
                self._requests[rid] = {
                    "prompt": [int(t)
                               for t in rec.get("prompt") or []],
                    "max_new_tokens": int(
                        rec.get("max_new_tokens") or 1),
                    "eos_id": rec.get("eos_id"),
                    "tenant": rec.get("tenant"),
                    "deadline_s": rec.get("deadline_s"),
                    "trace": rec.get("trace"),
                    "tokens": [],
                    "done": False,
                    "finish_reason": None,
                }
                self._live_count += 1
        elif kind == "progress":
            req = self._requests.get(str(rid))
            if req is None or req["done"]:
                return
            start = int(rec.get("start") or 0)
            toks = [int(t) for t in rec.get("tokens") or []]
            if start <= len(req["tokens"]):
                req["tokens"][start:start + len(toks)] = toks
        elif kind == "done":
            req = self._requests.get(str(rid))
            if req is not None:
                if not req["done"]:
                    self._live_count -= 1
                req["done"] = True
                req["finish_reason"] = rec.get("finish_reason")

    # --------------------------------------------------------- appending
    def append_admitted(self, rid, prompt, max_new_tokens,
                        eos_id: Optional[int] = None,
                        tenant: Optional[str] = None,
                        deadline_s: Optional[float] = None,
                        trace: Optional[str] = None) -> None:
        """Journal a request's admission. Idempotent on `rid`: a client
        retry (or a racing duplicate submit) appends nothing. `trace`
        is the request's cross-process trace id — journaled so a
        cold-restart recovery leg rejoins the original timeline."""
        rid = str(rid)
        rec = {"kind": "admitted", "id": rid,
               "prompt": [int(t) for t in prompt],
               "max_new_tokens": int(max_new_tokens)}
        if eos_id is not None:
            rec["eos_id"] = int(eos_id)
        if tenant is not None:
            rec["tenant"] = str(tenant)
        if deadline_s is not None:
            rec["deadline_s"] = float(deadline_s)
        if trace is not None:
            rec["trace"] = str(trace)
        with self._io_lock:
            if rid not in self._requests:
                self._replay(rec)
                self._write(rec)

    def record_progress(self, rid, tokens) -> None:
        """Append the NEW tokens of `rid` — the delta past what the
        journal already holds — as an absolute-positioned progress
        record. Passing the full token list every time is the
        intended calling convention; the journal computes the delta."""
        rid = str(rid)
        toks = [int(t) for t in tokens]
        with self._io_lock:
            req = self._requests.get(rid)
            if req is not None and not req["done"] \
                    and len(toks) > len(req["tokens"]):
                start = len(req["tokens"])
                rec = {"kind": "progress", "id": rid, "start": start,
                       "tokens": toks[start:]}
                self._replay(rec)
                self._write(rec)

    def append_done(self, rid, finish_reason: Optional[str]) -> None:
        """Journal a request's terminal state — a restart will not
        resurrect it. No-op for unknown or already-done ids."""
        rid = str(rid)
        with self._io_lock:
            req = self._requests.get(rid)
            if req is not None and not req["done"]:
                rec = {"kind": "done", "id": rid,
                       "finish_reason": finish_reason}
                self._replay(rec)
                self._write(rec)

    def flush(self, force: bool = True) -> None:
        """Group-commit checkpoint: fsync now (`force=True`) or let
        the interval/byte policy decide (`force=False` — the step
        loop's per-iteration call)."""
        with self._io_lock:
            self._maybe_sync(force)
        self._emit()

    def close(self) -> None:
        """Flush and close the head segment. Closing is NOT completion:
        the live set stays on disk for the next process to recover."""
        with self._io_lock:
            if self._closed:
                return
            self._maybe_sync(True)
            if self._head_f is not None:
                try:
                    self._head_f.close()
                except OSError:
                    pass
                self._head_f = None
            self._closed = True
        self._emit()

    # ------------------------------------------------- io (under lock)
    def _write(self, rec: dict) -> None:
        if self._closed or self._head_f is None:
            return
        blob = frame_record(rec)
        self._head_f.write(blob)
        self._head_f.flush()
        self._records += 1
        self._pend_records += 1
        self._head_bytes += len(blob)
        self._bytes += len(blob)
        self._unsynced += len(blob)
        # `journal.write_torn` chaos: a truncate spec mauls the head
        # segment right after this append landed — the torn-tail drill
        # recovery must truncate back from
        _fire("journal.write_torn", path=self._head_path())
        self._maybe_sync(False)
        if self._head_bytes >= self.segment_bytes:
            self._compact_locked()

    def _maybe_sync(self, force: bool) -> None:
        if self._unsynced <= 0 or self._head_f is None:
            return
        now = self._clock()
        if not force and self.fsync_interval_s > 0 \
                and self._unsynced < self.fsync_bytes \
                and now - self._last_sync < self.fsync_interval_s:
            return
        try:
            # `journal.fsync_fail` chaos: the group fsync failing must
            # not lose the journal — the bytes stay pending and the
            # next flush retries them
            _fire("journal.fsync_fail")
            os.fsync(self._head_f.fileno())
        except (OSError, FaultInjectedError):
            self._fsync_failures += 1
            return
        self._fsyncs += 1
        self._pend_fsyncs += 1
        self._unsynced = 0
        self._last_sync = now

    # -------------------------------------------------------- compaction
    def compact(self) -> int:
        """Consolidate the journal: rewrite every LIVE request into one
        fresh segment (atomic publish), open a new empty head AFTER
        it, delete every older segment — done requests' records vanish
        with them. Returns the number of segments deleted. Safe to
        kill at any point: the consolidated segment only becomes
        visible complete (fsync + rename), and idempotent replay means
        any mix of old and new segments recovers the same live set."""
        with self._io_lock:
            deleted = self._compact_locked()
        self._emit()
        return deleted

    def _compact_locked(self) -> int:
        if self._closed or self._head_f is None:
            return 0
        self._maybe_sync(True)
        olds = self._segments()
        try:
            self._head_f.close()
        except OSError:
            pass
        consolidated = self._head_index + 1
        records: List[dict] = []
        for rid in sorted(self._requests):
            req = self._requests[rid]
            if req["done"]:
                continue
            rec = {"kind": "admitted", "id": rid,
                   "prompt": list(req["prompt"]),
                   "max_new_tokens": req["max_new_tokens"]}
            if req["eos_id"] is not None:
                rec["eos_id"] = req["eos_id"]
            if req["tenant"] is not None:
                rec["tenant"] = req["tenant"]
            if req["deadline_s"] is not None:
                rec["deadline_s"] = req["deadline_s"]
            if req.get("trace") is not None:
                rec["trace"] = req["trace"]
            records.append(rec)
            if req["tokens"]:
                records.append({"kind": "progress", "id": rid,
                                "start": 0,
                                "tokens": list(req["tokens"])})
        write_records(self._seg_path(consolidated), records)
        # a done request survives only in memory from here: the engine
        # keeps its own bounded dedup map; the journal's job is the
        # LIVE set, and forgetting the finished keeps it O(in-flight)
        self._requests = {rid: req
                          for rid, req in self._requests.items()
                          if not req["done"]}
        self._head_index = consolidated + 1
        self._head_pathname = self._seg_path(self._head_index)
        self._head_f = open(self._head_pathname, "ab")
        self._head_bytes = 0
        self._unsynced = 0
        deleted = 0
        for path in olds:
            try:
                os.remove(path)
                deleted += 1
            except OSError:
                pass
        try:
            self._bytes = os.path.getsize(self._seg_path(consolidated))
        except OSError:
            self._bytes = 0
        self._compactions += 1
        self._pend_compactions += 1
        return deleted

    # ------------------------------------------------------------- facts
    def live(self) -> Dict[str, dict]:
        """Every admitted-but-not-done request: the recovery work
        list. Token lists are copies — safe to hand to submit()."""
        with self._io_lock:
            return {rid: {"prompt": list(req["prompt"]),
                          "max_new_tokens": req["max_new_tokens"],
                          "eos_id": req["eos_id"],
                          "tenant": req["tenant"],
                          "deadline_s": req["deadline_s"],
                          "trace": req.get("trace"),
                          "tokens": list(req["tokens"])}
                    for rid, req in self._requests.items()
                    if not req["done"]}

    def stats(self) -> Dict:
        with self._io_lock:
            live = self._live_count
            return {
                "directory": self.directory,
                "segments": len(self._segments()),
                "bytes": self._bytes,
                "live": live,
                "done": len(self._requests) - live,
                "records": self._records,
                "fsyncs": self._fsyncs,
                "fsync_failures": self._fsync_failures,
                "torn_tails": self._torn_tails,
                "compactions": self._compactions,
                "fsync_interval_s": self.fsync_interval_s,
            }

    def _emit(self) -> None:
        """Drain deferred metric deltas OUTSIDE the io lock. Called at
        group-commit boundaries (flush/compact/close/init), NOT per
        append — the hot decode loop appends thousands of records a
        second and one emission per step is plenty for dashboards."""
        with self._io_lock:
            rec = self._pend_records
            fs = self._pend_fsyncs
            comp = self._pend_compactions
            self._pend_records = 0
            self._pend_fsyncs = 0
            self._pend_compactions = 0
            nbytes = self._bytes
            live = self._live_count
        if rec:
            _obs.count("dl4j_journal_records_total", n=rec)
        if fs:
            _obs.count("dl4j_journal_fsyncs_total", n=fs)
        if comp:
            _obs.count("dl4j_journal_compactions_total", n=comp)
        _obs.set_gauge("dl4j_journal_bytes", nbytes)
        _obs.set_gauge("dl4j_journal_live", live)
