"""ModelRegistry: N named models × versions with zero-downtime
hot-swap and one-call rollback.

The control-plane core above the ParallelInference data plane. Every
model VERSION owns its own ParallelInference batcher (bucket pool,
pipeline window, warmup state) so versions never share compiled-trace
or staging-buffer state; the registry's job is lifecycle:

  load      `load_version()` restores a model zip through the
            model_serializer/checkpoint_integrity machinery — sha256
            sidecar validation plus structural restore — and REJECTS
            corrupted uploads (CheckpointIntegrityError, counted in
            dl4j_serving_load_rejected_total) before they can touch
            traffic;
  warm      a new version's ParallelInference is constructed (and its
            pow2 buckets pre-traced) BEFORE the active pointer flips,
            so the first post-swap request never pays a compile;
  swap      the flip itself is one pointer write under the entry lock —
            requests lease (version, pi) atomically, so every response
            is computed end-to-end by exactly one version. The old
            version keeps draining its in-flight pipeline window on its
            still-running batcher (state `standby`) and stays warm as
            the rollback target;
  rollback  one call flips active back to the previous version — still
            warm, zero downtime in the other direction;
  retire    versions older than `keep_warm` standbys drain (leases and
            pipeline window to zero) in a background thread and only
            then shut their batcher down.

Lease discipline: `entry.lease()` pins one (version, pi) pair for the
duration of a request. A swap between lease and response is harmless —
the leased version finishes the request and the drain logic waits for
the lease count to hit zero before any shutdown. That is the whole
zero-dropped / zero-mixed-version guarantee, and the chaos test in
tests/test_serving_registry.py hammers it mid-soak.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import zipfile
from contextlib import contextmanager
from typing import Dict, List, Optional

from deeplearning4j_tpu.observability import metrics as _obs
from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.resilience.errors import (
    CheckpointIntegrityError,
    ModelNotFoundError,
)
from deeplearning4j_tpu.util import model_serializer

logger = logging.getLogger("deeplearning4j_tpu")

# version lifecycle states
ACTIVE, STANDBY, RETIRING, RETIRED = ("active", "standby",
                                      "retiring", "retired")


class _Version:
    """One servable version: the net, its own batcher, lease count."""

    __slots__ = ("version", "net", "pi", "owns_pi", "state",
                 "source_path", "loaded_at", "leases", "served")

    def __init__(self, version: str, net, pi: ParallelInference,
                 owns_pi: bool, source_path: Optional[str]):
        self.version = version
        self.net = net
        self.pi = pi
        self.owns_pi = owns_pi
        self.state = STANDBY
        self.source_path = source_path
        self.loaded_at = time.time()
        self.leases = 0
        self.served = 0

    def facts(self) -> dict:
        return {"state": self.state, "leases": self.leases,
                "served": self.served, "source_path": self.source_path,
                "loaded_at": self.loaded_at,
                "healthy": self.pi.healthy}


class ModelEntry:
    """One named model: its version set and the active pointer."""

    def __init__(self, name: str, registry: "ModelRegistry"):
        self.name = name
        self._registry = registry
        self._lock = threading.RLock()
        self.versions: Dict[str, _Version] = {}
        self.active: Optional[str] = None
        self.previous: Optional[str] = None
        self.warmup_inputs = None   # remembered for later uploads
        self._seq = 0

    # ------------------------------------------------------------ leases
    @contextmanager
    def lease(self):
        """Pin the ACTIVE (version, pi) pair for one request. The pin
        is what makes a concurrent swap invisible: this request
        finishes on the version it started on, and that version cannot
        shut down while the lease is held."""
        with self._lock:
            if self.active is None:
                raise ModelNotFoundError(
                    f"model {self.name!r} has no active version")
            v = self.versions[self.active]
            v.leases += 1
        try:
            yield v.version, v.pi
            with self._lock:
                v.served += 1
        finally:
            with self._lock:
                v.leases -= 1

    # ------------------------------------------------------- lifecycle
    def next_version_name(self) -> str:
        with self._lock:
            self._seq += 1
            name = f"v{self._seq}"
            while name in self.versions:
                self._seq += 1
                name = f"v{self._seq}"
            return name

    def add(self, ver: _Version, activate: bool) -> None:
        with self._lock:
            if ver.version in self.versions:
                old = self.versions[ver.version]
                if old.state in (ACTIVE,):
                    raise ValueError(
                        f"version {ver.version!r} of {self.name!r} is "
                        "active; swap away before replacing it")
                self._registry._retire_async(self.name, old)
            self.versions[ver.version] = ver
        if activate:
            self.activate(ver.version)

    def activate(self, version: str) -> None:
        """The atomic flip. The new version is already constructed and
        warmed by the time this runs; the old active becomes the warm
        `standby` rollback target and keeps draining its in-flight
        window on its own still-running batcher."""
        swapped = False
        with self._lock:
            if version not in self.versions:
                raise ModelNotFoundError(
                    f"model {self.name!r} has no version {version!r}")
            ver = self.versions[version]
            if ver.state in (RETIRING, RETIRED):
                raise ValueError(
                    f"version {version!r} of {self.name!r} is "
                    f"{ver.state}; reload it before activating")
            if self.active == version:
                return
            old = self.active
            if old is not None:
                self.versions[old].state = STANDBY
                swapped = True
            self.active = version
            self.previous = old
            ver.state = ACTIVE
            self._trim_standbys()
        # emission stays outside the registry lock (dl4j-analyze
        # thr-blocking-under-lock): the obs registry takes its own lock
        if swapped:
            _obs.count("dl4j_serving_swaps_total",
                       labels={"model": self.name})

    def rollback(self) -> str:
        with self._lock:
            if self.previous is None \
                    or self.previous not in self.versions:
                raise ModelNotFoundError(
                    f"model {self.name!r} has no previous version to "
                    "roll back to")
            target = self.previous
            ver = self.versions[target]
            if ver.state != STANDBY:
                raise ValueError(
                    f"previous version {target!r} of {self.name!r} is "
                    f"{ver.state}, not standby — cannot roll back")
            old = self.active
            self.active = target
            self.previous = old
            ver.state = ACTIVE
            if old is not None:
                self.versions[old].state = STANDBY
        _obs.count("dl4j_serving_rollbacks_total",
                   labels={"model": self.name})
        return target

    def _trim_standbys(self) -> None:
        """Retire standbys beyond keep_warm (called under the lock).
        The previous (rollback target) is always kept."""
        keep = {self.active, self.previous}
        standbys = [v for v in self.versions.values()
                    if v.state == STANDBY and v.version not in keep]
        standbys.sort(key=lambda v: v.loaded_at)
        excess = len(standbys) - max(0, self._registry.keep_warm - 1)
        for v in standbys[:max(0, excess)]:
            self._registry._retire_async(self.name, v)

    def delete_version(self, version: str) -> None:
        with self._lock:
            if version not in self.versions:
                raise ModelNotFoundError(
                    f"model {self.name!r} has no version {version!r}")
            ver = self.versions[version]
            if ver.state == ACTIVE:
                raise ValueError(
                    f"version {version!r} of {self.name!r} is active; "
                    "swap or roll back before deleting it")
            if self.previous == version:
                self.previous = None
            del self.versions[version]
        self._registry._retire_async(self.name, ver)

    def status(self) -> dict:
        with self._lock:
            facts = {
                "name": self.name,
                "active": self.active,
                "previous": self.previous,
                "versions": {v.version: v.facts()
                             for v in self.versions.values()},
            }
            active = (self.versions.get(self.active)
                      if self.active else None)
        if active is not None:
            facts["pipeline"] = active.pi.stats()
            facts["trace"] = active.pi.trace_stats()
            facts["queue_depth"] = active.pi.queue_depth()
            facts["healthy"] = active.pi.healthy
        return facts


class ModelRegistry:
    """The model catalog a multi-model ModelServer serves from.

    `pi_kwargs` (batch_limit, queue_limit, pipeline_depth, warmup,
    max_wait_ms, adaptive_wait, completion_streams, tracer, ...) are
    applied to every version's ParallelInference this registry
    constructs; pre-built ParallelInference front-ends register as-is
    and are never shut down by the registry (caller owns them)."""

    def __init__(self, keep_warm: int = 1,
                 drain_timeout_s: float = 30.0, **pi_kwargs):
        self.keep_warm = max(0, int(keep_warm))
        self.drain_timeout_s = float(drain_timeout_s)
        self.pi_kwargs = dict(pi_kwargs)
        self._lock = threading.RLock()
        self._entries: Dict[str, ModelEntry] = {}
        self._default: Optional[str] = None
        self._drainers: List[threading.Thread] = []
        self._closed = False

    # -------------------------------------------------------- catalog
    def entry(self, name: str) -> ModelEntry:
        with self._lock:
            try:
                return self._entries[name]
            except KeyError:
                raise ModelNotFoundError(
                    f"no model named {name!r} "
                    f"(have: {sorted(self._entries)})") from None

    def default_entry(self) -> ModelEntry:
        with self._lock:
            if self._default is None:
                raise ModelNotFoundError("registry is empty")
            return self._entries[self._default]

    @property
    def default_model(self) -> Optional[str]:
        return self._default

    def model_names(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)

    def _entry_or_create(self, name: str) -> ModelEntry:
        created_n = None
        with self._lock:
            if self._closed:
                raise ModelNotFoundError("registry is shut down")
            e = self._entries.get(name)
            if e is None:
                e = self._entries[name] = ModelEntry(name, self)
                if self._default is None:
                    self._default = name
                created_n = len(self._entries)
        if created_n is not None:
            _obs.set_gauge("dl4j_serving_active_models", created_n)
        return e

    # ------------------------------------------------------- register
    def register(self, name: str, net_or_pi, version: Optional[str] = None,
                 activate: bool = True, warmup_inputs=None,
                 source_path: Optional[str] = None) -> str:
        """Register an in-memory net (a ParallelInference is built and
        warmed around it) or a pre-built ParallelInference (registered
        as-is, caller keeps ownership). Returns the version id."""
        e = self._entry_or_create(name)
        if warmup_inputs is not None:
            e.warmup_inputs = warmup_inputs
        version = version or e.next_version_name()
        if isinstance(net_or_pi, ParallelInference):
            pi, net, owns = net_or_pi, net_or_pi.net, False
        else:
            net = net_or_pi
            pi = self._make_pi(net, e.warmup_inputs)
            owns = True
        e.add(_Version(version, net, pi, owns, source_path), activate)
        return version

    def load_version(self, name: str, version: Optional[str],
                     path: str, model_type: str = "auto",
                     activate: bool = True, warmup_inputs=None) -> str:
        """Restore a model zip through the integrity-checked
        serializer path and register it. A corrupted or torn upload is
        rejected (CheckpointIntegrityError) before the version exists —
        it can never take traffic. The model ENTRY is created first —
        a rejected upload leaves the name visible with no servable
        versions, so operators see the attempt in /status."""
        self._entry_or_create(name)
        try:
            net = self._restore(path, model_type)
        except CheckpointIntegrityError:
            _obs.count("dl4j_serving_load_rejected_total",
                       labels={"model": name})
            raise
        except Exception as exc:   # noqa: BLE001 - structural rejects
            _obs.count("dl4j_serving_load_rejected_total",
                       labels={"model": name})
            raise CheckpointIntegrityError(
                f"model upload {path!r} failed structural restore: "
                f"{exc}") from exc
        return self.register(name, net, version=version,
                             activate=activate,
                             warmup_inputs=warmup_inputs,
                             source_path=path)

    @staticmethod
    def _restore(path: str, model_type: str):
        if model_type == "auto":
            if not model_serializer.verify_model(path):
                raise CheckpointIntegrityError(
                    f"{path} failed sha256 validation "
                    "(truncated or torn upload?)")
            try:
                with zipfile.ZipFile(path, "r") as z:
                    names = set(z.namelist())
                    meta = (json.loads(
                        z.read(model_serializer.META_ENTRY).decode())
                        if model_serializer.META_ENTRY in names else {})
            except (zipfile.BadZipFile, OSError, ValueError) as exc:
                raise CheckpointIntegrityError(
                    f"{path} is not a readable model zip: {exc}") \
                    from exc
            model_type = meta.get("model_type", "MultiLayerNetwork")
        if model_type in ("ComputationGraph", "graph"):
            return model_serializer.restore_computation_graph(path)
        return model_serializer.restore_multi_layer_network(path)

    def _make_pi(self, net, warmup_inputs) -> ParallelInference:
        kwargs = dict(self.pi_kwargs)
        if warmup_inputs is not None:
            kwargs.setdefault("warmup_inputs", warmup_inputs)
        # construction IS the warm phase: buckets pre-trace here,
        # before the version can be activated
        return ParallelInference(net, **kwargs)

    # ------------------------------------------------------ lifecycle
    def swap(self, name: str, version: str) -> None:
        self.entry(name).activate(version)

    def rollback(self, name: str) -> str:
        return self.entry(name).rollback()

    def delete_version(self, name: str, version: str) -> None:
        self.entry(name).delete_version(version)

    def remove(self, name: str) -> None:
        """Remove a model entirely; every version drains then shuts
        down in the background."""
        with self._lock:
            e = self.entry(name)
            del self._entries[name]
            if self._default == name:
                self._default = next(iter(sorted(self._entries)), None)
            remaining = len(self._entries)
        _obs.set_gauge("dl4j_serving_active_models", remaining)
        with e._lock:
            vers = list(e.versions.values())
            e.versions.clear()
            e.active = e.previous = None
        for v in vers:
            self._retire_async(name, v)

    def _retire_async(self, name: str, ver: _Version) -> None:
        """Drain-then-shutdown in a daemon thread: wait for leases and
        the in-flight pipeline window to clear (bounded by
        drain_timeout_s), then stop the batcher. Never blocks a swap."""
        ver.state = RETIRING

        def _drain():
            deadline = time.monotonic() + self.drain_timeout_s
            while time.monotonic() < deadline:
                stats = ver.pi.stats()
                if (ver.leases == 0 and stats["queue_depth"] == 0
                        and stats["in_flight"] == 0):
                    break
                time.sleep(0.01)
            else:
                logger.warning(
                    "model %s version %s drain timed out after %.1fs "
                    "(leases=%d); shutting down anyway", name,
                    ver.version, self.drain_timeout_s, ver.leases)
            if ver.owns_pi:
                ver.pi.shutdown()
            ver.state = RETIRED

        t = threading.Thread(
            target=_drain, daemon=True,
            name=f"ModelRegistry-drain-{name}-{ver.version}")
        t.start()
        with self._lock:
            self._drainers = [d for d in self._drainers
                              if d.is_alive()] + [t]

    # --------------------------------------------------------- status
    def models_status(self) -> dict:
        with self._lock:
            entries = list(self._entries.values())
            default = self._default
        return {"default": default,
                "models": {e.name: e.status() for e in entries}}

    def healthy(self) -> bool:
        """True while every model's ACTIVE version is healthy. Standby
        and retiring versions don't gate liveness — and neither does an
        entry with no active version yet (a first upload still loading,
        or one whose only upload was rejected): flipping /healthz 503
        mid-PUT would get the pod killed by its liveness probe."""
        with self._lock:
            entries = list(self._entries.values())
        saw_active = False
        for e in entries:
            with e._lock:
                v = e.versions.get(e.active) if e.active else None
            if v is None:
                continue
            saw_active = True
            if not v.pi.healthy:
                return False
        return saw_active

    def shutdown(self, timeout_s: float = 5.0) -> None:
        with self._lock:
            self._closed = True
            entries = list(self._entries.values())
            self._entries.clear()
            self._default = None
            drainers = list(self._drainers)
        for e in entries:
            with e._lock:
                vers = list(e.versions.values())
            for v in vers:
                if v.owns_pi:
                    v.pi.shutdown()
                v.state = RETIRED
        for t in drainers:
            t.join(timeout=timeout_s)
