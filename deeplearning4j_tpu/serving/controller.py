"""FleetController: rollout and capacity for a ReplicaRouter fleet.

The serving fleet's supervisor — the same shape the training side
already has (Supervisor/ClusterSupervisor): a version flip stops being
a sequence of manual PUTs and becomes an observable, reversible,
automatically-guarded state machine; the replica pool stops being a
static URL list and becomes a control loop driven by the admission
layer's own shed/queue metrics.

Three responsibilities:

  rollout     `rollout(model, version)` canaries ONE replica first:
              warm-before-flip through the registry hot-swap the
              replica already implements (PUT with activate=False,
              then swap), then WATCHES the canary's error-rate / p99 /
              `dl4j_perf_*` telemetry — scraped per replica and merged
              through the PR 7 cross-rank snapshot aggregation — in
              consecutive windows against a declared `SLOPolicy`.
              Healthy windows ramp the remaining replicas one by one;
              a breach auto-rolls the canary (and any already-flipped
              replica) back to the still-warm previous version and
              records the version in the HOLD-DOWN LEDGER, so a
              failing build cannot be re-canaried in a tight loop
              (`RolloutHeldError`, exponential hold-down). Zero
              mixed-version responses throughout: each flip is the
              ModelRegistry lease-pinned pointer write, so every
              request is computed end-to-end by exactly one version.
  autoscale   `start()` runs a control loop that (a) health-polls
              every replica — a dead one (real /healthz failure or the
              `serving.replica_kill` drill verdict) leaves the router
              WITHOUT counting against its breaker accounting and is
              backfilled from `replica_factory` up to `min_replicas` —
              and (b) grows/shrinks the pool from the
              AdmissionController's shed-rate and queue-depth metrics:
              bounded [min_replicas, max_replicas], one scale event
              per `cooldown_s`, scale-down only after the router
              DRAINS the victim's in-flight requests (then the
              replica's own drain-then-retire machinery tears it
              down).
  observe     every replica snapshot merges through
              `perf.aggregate_snapshots` into one fleet-level
              exposition (`fleet_prometheus_text`), and the controller
              emits `dl4j_fleet_*` / `dl4j_rollout_*` metrics so the
              dashboard's "fleet —" line and a /metrics scrape show
              pool size, rollout state, and rollback counts live.

Replica handles are duck-typed (name, snapshot, healthy,
active_version, load_version, swap, rollback, retire): `HttpReplica`
drives a remote ModelServer over the /v1/models surface + /metrics
scrape; `LocalReplica` drives an in-process ModelRegistry directly
(tier-1 drills, single-process fleets). In-process fleets share one
global MetricsRegistry, so per-replica scrape attribution is a
deployment property — one process per replica — not something the
controller can conjure; the drills account for this.
"""

from __future__ import annotations

import logging
import os
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from deeplearning4j_tpu.observability import metrics as _obs
from deeplearning4j_tpu.observability.metrics import (
    parse_prometheus_snapshot,
)
from deeplearning4j_tpu.observability.perf import aggregate_snapshots
from deeplearning4j_tpu.resilience.errors import (
    FaultInjectedError,
    RolloutHeldError,
)
from deeplearning4j_tpu.resilience.faults import fire as _fire

logger = logging.getLogger("deeplearning4j_tpu")

# rollout state machine; the dl4j_rollout_state gauge carries the index
ROLLOUT_STATES = ("idle", "canary", "ramping", "rolling_back", "held",
                  "completed")

_CODE = re.compile(r'code="(\d+)"')
_DURATION = re.compile(r"^([0-9.]+)(ms|s)?$")


def _parse_duration_s(raw: str) -> float:
    m = _DURATION.match(raw.strip())
    if not m:
        raise ValueError(f"bad duration {raw!r} (want e.g. 250ms, 2s)")
    v = float(m.group(1))
    return v / 1e3 if m.group(2) == "ms" else v


class SLOPolicy:
    """The declared rollout SLO: what a healthy canary looks like.

    Bounds (any may be None = unchecked):
      max_error_rate   5xx fraction of requests per window
      max_p99_s        absolute p99 latency bound
      max_p99_ratio    p99 vs. the pre-flip baseline window
      max_ttft_p99_s   absolute decode time-to-first-token p99 bound

    Watch shape:
      window_s       one observation window (snapshot delta)
      windows        consecutive healthy windows to clear the canary
      ramp_windows   healthy windows between ramp flips
      min_requests   below this a window carries no signal and counts
                     as healthy ("no traffic = no harm") — drills and
                     real rollouts always have traffic flowing

    Grammar (the README "Fleet control" section documents it):

        SLOPolicy.parse("error_rate<0.02,p99<250ms,p99_ratio<1.5,"
                        "ttft_p99<100ms,"
                        "min_requests=20,window=500ms,windows=3")
    """

    def __init__(self, max_error_rate: Optional[float] = 0.02,
                 max_p99_s: Optional[float] = None,
                 max_p99_ratio: Optional[float] = None,
                 max_ttft_p99_s: Optional[float] = None,
                 min_requests: int = 10, window_s: float = 1.0,
                 windows: int = 3, ramp_windows: int = 1):
        self.max_error_rate = max_error_rate
        self.max_p99_s = max_p99_s
        self.max_p99_ratio = max_p99_ratio
        self.max_ttft_p99_s = max_ttft_p99_s
        self.min_requests = int(min_requests)
        self.window_s = float(window_s)
        self.windows = int(windows)
        self.ramp_windows = int(ramp_windows)

    @classmethod
    def parse(cls, spec: str) -> "SLOPolicy":
        kw: dict = {"max_error_rate": None}
        for item in spec.split(","):
            item = item.strip()
            if not item:
                continue
            key, sep, val = (item.partition("<") if "<" in item
                             else item.partition("="))
            if not sep:
                raise ValueError(f"bad SLO clause {item!r} "
                                 "(want key<bound or key=value)")
            key, val = key.strip(), val.strip()
            if key == "error_rate":
                kw["max_error_rate"] = float(val)
            elif key == "p99":
                kw["max_p99_s"] = _parse_duration_s(val)
            elif key == "p99_ratio":
                kw["max_p99_ratio"] = float(val)
            elif key == "ttft_p99":
                kw["max_ttft_p99_s"] = _parse_duration_s(val)
            elif key == "min_requests":
                kw["min_requests"] = int(val)
            elif key == "window":
                kw["window_s"] = _parse_duration_s(val)
            elif key == "windows":
                kw["windows"] = int(val)
            elif key == "ramp_windows":
                kw["ramp_windows"] = int(val)
            else:
                raise ValueError(f"unknown SLO key {key!r}")
        return cls(**kw)

    def to_spec(self) -> str:
        parts = []
        if self.max_error_rate is not None:
            parts.append(f"error_rate<{self.max_error_rate:g}")
        if self.max_p99_s is not None:
            parts.append(f"p99<{self.max_p99_s * 1e3:g}ms")
        if self.max_p99_ratio is not None:
            parts.append(f"p99_ratio<{self.max_p99_ratio:g}")
        if self.max_ttft_p99_s is not None:
            parts.append(f"ttft_p99<{self.max_ttft_p99_s * 1e3:g}ms")
        parts += [f"min_requests={self.min_requests}",
                  f"window={self.window_s:g}s",
                  f"windows={self.windows}",
                  f"ramp_windows={self.ramp_windows}"]
        return ",".join(parts)

    def breach(self, sample: dict,
               baseline_p99_s: Optional[float]) -> Optional[str]:
        """The verdict for one watch window: a reason string when the
        sample violates the policy, None when it is healthy (or
        carries too little traffic to judge)."""
        if sample["requests"] < self.min_requests:
            return None
        if self.max_error_rate is not None \
                and sample["error_rate"] > self.max_error_rate:
            return (f"error_rate {sample['error_rate']:.4f} > "
                    f"{self.max_error_rate:g}")
        p99 = sample.get("p99_s")
        if p99 is not None:
            if self.max_p99_s is not None and p99 > self.max_p99_s:
                return f"p99 {p99 * 1e3:.1f}ms > " \
                       f"{self.max_p99_s * 1e3:g}ms"
            if self.max_p99_ratio is not None \
                    and baseline_p99_s is not None \
                    and baseline_p99_s > 0 \
                    and p99 > self.max_p99_ratio * baseline_p99_s:
                return (f"p99 {p99 * 1e3:.1f}ms > "
                        f"{self.max_p99_ratio:g}x baseline "
                        f"{baseline_p99_s * 1e3:.1f}ms")
        ttft = sample.get("ttft_p99_s")
        if self.max_ttft_p99_s is not None and ttft is not None \
                and ttft > self.max_ttft_p99_s:
            return (f"ttft_p99 {ttft * 1e3:.1f}ms > "
                    f"{self.max_ttft_p99_s * 1e3:g}ms")
        return None


# -------------------------------------------------- snapshot arithmetic
def _counter_total(snap: dict, name: str) -> float:
    return float(sum(snap.get("counters", {}).get(name, {}).values()))


def _error_total(snap: dict) -> float:
    """Genuine serving failures only. A shed (429) or a client error
    (4xx) is not replica badness, and a 503 is BACKPRESSURE — a
    capacity signal the autoscaler owns; judging a canary on it under
    a deliberate overload soak would roll back every version. The
    rollback guard counts 500-class handler failures."""
    total = 0.0
    for lab, v in snap.get("counters", {}).get(
            "dl4j_serving_errors_total", {}).items():
        m = _CODE.search(lab)
        code = int(m.group(1)) if m else 500
        if code >= 500 and code != 503:
            total += float(v)
    return total


def _hist_series(snap: dict, name: str) -> Tuple[int, Dict[str, int]]:
    """(count, per-bucket counts) summed over every label set of a
    histogram family."""
    count, buckets = 0, {}
    for full, h in snap.get("histograms", {}).items():
        if full != name and not full.startswith(name + "{"):
            continue
        count += int(h.get("count", 0))
        for le, c in h.get("buckets", {}).items():
            buckets[le] = buckets.get(le, 0) + int(c)
    return count, buckets


def _bucket_upper(le: str) -> float:
    return float("inf") if le == "+Inf" else float(le)


def _hist_p99_delta(prev: dict, cur: dict,
                    hist: str) -> Optional[float]:
    """p99 of one histogram family between two snapshots, read from
    the BUCKET deltas — an upper bound at bucket resolution, which is
    exactly what an SLO bound wants (never under-reports a breach).
    None when the window saw no observations."""
    c0, b0 = _hist_series(prev, hist)
    c1, b1 = _hist_series(cur, hist)
    dcount = c1 - c0
    if dcount <= 0:
        return None
    deltas = sorted(
        ((le, b1.get(le, 0) - b0.get(le, 0))
         for le in b1), key=lambda kv: _bucket_upper(kv[0]))
    cum, target = 0, 0.99 * dcount
    for le, c in deltas:
        cum += c
        if cum >= target:
            return _bucket_upper(le)
    return None


def slo_sample(prev: dict, cur: dict,
               hist: str = "dl4j_serving_request_seconds") -> dict:
    """Error-rate + latency p99s between two metric snapshots (the one
    watch window). `p99_s` is end-to-end request latency;
    `ttft_p99_s` is decode time-to-first-token (the user-visible
    responsiveness bound rollout policies gate on via `ttft_p99<...`).
    Both come from histogram bucket deltas via `_hist_p99_delta`."""
    req = (_counter_total(cur, "dl4j_serving_requests_total")
           - _counter_total(prev, "dl4j_serving_requests_total"))
    err = _error_total(cur) - _error_total(prev)
    p99 = _hist_p99_delta(prev, cur, hist)
    ttft_p99 = _hist_p99_delta(prev, cur, "dl4j_decode_ttft_seconds")
    mfu_series = cur.get("gauges", {}).get("dl4j_perf_mfu") or {}
    mfu = list(mfu_series.values())[-1] if mfu_series else None
    return {"requests": req, "errors": err,
            "error_rate": (err / req) if req > 0 else 0.0,
            "p99_s": p99, "ttft_p99_s": ttft_p99, "mfu": mfu}


# ------------------------------------------------------ replica handles
class HttpReplica:
    """A remote ModelServer replica driven over its own HTTP surface:
    lifecycle through the /v1/models routes, observation through a
    /metrics scrape parsed back into a registry snapshot."""

    def __init__(self, url: str, client=None, timeout: float = 10.0,
                 on_retire: Optional[Callable] = None):
        from deeplearning4j_tpu.parallel.serving import ModelClient
        from deeplearning4j_tpu.resilience.retry import Retry

        self.name = url.rstrip("/")
        self.client = client if client is not None else ModelClient(
            url, timeout=timeout, retry=Retry(max_attempts=2),
            breaker=None)
        self._on_retire = on_retire

    def snapshot(self) -> dict:
        return parse_prometheus_snapshot(self.client.metrics_text())

    def healthy(self) -> bool:
        try:
            return self.client.healthz()
        except Exception:   # noqa: BLE001 - unreachable means unhealthy
            return False

    def active_version(self, model: str) -> Optional[str]:
        return self.client.status(model=model).get("active")

    def load_version(self, model: str, version: str, path: str,
                     **kw) -> None:
        kw.setdefault("activate", False)   # warm BEFORE the flip
        self.client.put_version(model, version, path, **kw)

    def swap(self, model: str, version: str) -> None:
        self.client.swap(model, version)

    def rollback(self, model: str) -> None:
        self.client.rollback(model)

    def retire(self) -> None:
        if self._on_retire is not None:
            self._on_retire()


class LocalReplica:
    """An in-process replica: a ModelRegistry (optionally with the
    ModelServer wrapping it, so `retire` can stop the HTTP surface
    too). Snapshots read the process-global MetricsRegistry — an
    in-process fleet shares it, see the module docstring."""

    def __init__(self, name: str, registry, server=None):
        self.name = name
        self.registry = registry
        self.server = server

    def snapshot(self) -> dict:
        return _obs.get_registry().snapshot()

    def healthy(self) -> bool:
        try:
            return bool(self.registry.healthy())
        except Exception:   # noqa: BLE001 - unreachable means unhealthy
            return False

    def active_version(self, model: str) -> Optional[str]:
        return self.registry.entry(model).active

    def load_version(self, model: str, version: str, path: str,
                     **kw) -> None:
        kw.setdefault("activate", False)
        self.registry.load_version(model, version, path, **kw)

    def swap(self, model: str, version: str) -> None:
        self.registry.swap(model, version)

    def rollback(self, model: str) -> None:
        self.registry.rollback(model)

    def retire(self) -> None:
        if self.server is not None:
            self.server.stop()       # drains the registry behind it
        else:
            self.registry.shutdown()


# ------------------------------------------------------ the controller
class FleetController:
    """Rollout + capacity supervisor over a replica fleet (see the
    module docstring for the full story).

    `replicas` are handles (HttpReplica/LocalReplica/stubs); `router`
    is the ReplicaRouter whose membership this controller owns;
    `replica_factory()` mints a new handle (spawning whatever backs
    it) for backfill and scale-up — without one the pool can only
    shrink. `clock`/`sleep` are injectable for deterministic drills."""

    def __init__(self, replicas: List, router=None,
                 slo: Optional[SLOPolicy] = None,
                 replica_factory: Optional[Callable] = None,
                 min_replicas: int = 1, max_replicas: int = 8,
                 autoscale_interval_s: float = 2.0,
                 cooldown_s: float = 30.0,
                 scale_up_shed_rate: float = 0.05,
                 scale_up_queue_depth: int = 32,
                 scale_down_rps_per_replica: float = 1.0,
                 drain_timeout_s: float = 10.0,
                 holddown_s: float = 300.0,
                 state_dir: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        self.replicas = list(replicas)
        self.router = router
        self.slo = slo if slo is not None else SLOPolicy()
        self.replica_factory = replica_factory
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.autoscale_interval_s = float(autoscale_interval_s)
        self.cooldown_s = float(cooldown_s)
        self.scale_up_shed_rate = float(scale_up_shed_rate)
        self.scale_up_queue_depth = int(scale_up_queue_depth)
        self.scale_down_rps_per_replica = float(
            scale_down_rps_per_replica)
        self.drain_timeout_s = float(drain_timeout_s)
        self.holddown_s = float(holddown_s)
        self._clock = clock
        self._sleep = sleep

        self._lock = threading.Lock()           # membership + ledgers
        self._rollout_lock = threading.Lock()   # one rollout at a time
        self._holddown: Dict[Tuple[str, str], dict] = {}
        self._state = "idle"
        self._history: List[dict] = []
        self._scale_events = {"up": 0, "down": 0}
        self._deaths = 0
        self._last_scale_t: Optional[float] = None
        self._prev_fleet: Optional[dict] = None
        self._prev_tick_t: Optional[float] = None
        self._last_fleet_sample: Optional[dict] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # durable control plane: hold-down ledger + autoscaler target
        # persisted with the journal's record framing (`state_dir`), so
        # a restarted controller refuses to re-canary a held build
        self._state_path: Optional[str] = None
        self._restored_target: Optional[int] = None
        if state_dir is not None:
            os.makedirs(state_dir, exist_ok=True)
            self._state_path = os.path.join(state_dir,
                                            "controller.state")
            self._restore_state()
        self._emit_pool_gauge()
        self._set_state("idle")

    # ---------------------------------------------------- state/metrics
    def _set_state(self, state: str) -> None:
        self._state = state
        _obs.set_gauge("dl4j_rollout_state",
                       ROLLOUT_STATES.index(state))

    def _emit_pool_gauge(self) -> None:
        with self._lock:
            n = len(self.replicas)
        _obs.set_gauge("dl4j_fleet_replicas", n)

    @property
    def rollout_state(self) -> str:
        return self._state

    # -------------------------------------------------------- hold-down
    def _check_holddown(self, model: str, version: str) -> None:
        now = self._clock()
        with self._lock:
            entry = self._holddown.get((model, version))
            held = entry is not None and entry["until"] > now
            if held:
                entry = dict(entry)
        if held:
            raise RolloutHeldError(
                f"version {version!r} of {model!r} is held down for "
                f"{entry['until'] - now:.1f}s more after "
                f"{entry['failures']} failed rollout(s) "
                f"({entry['reason']})", model=model, version=version,
                until_s=entry["until"], failures=entry["failures"])

    def _enter_holddown(self, model: str, version: str,
                        reason: str) -> None:
        now = self._clock()
        with self._lock:
            entry = self._holddown.setdefault(
                (model, version), {"failures": 0, "until": 0.0,
                                   "reason": ""})
            entry["failures"] += 1
            # exponential: a repeatedly-failing build backs off harder
            entry["until"] = now + self.holddown_s \
                * (2 ** (entry["failures"] - 1))
            entry["reason"] = reason
        _obs.count("dl4j_rollout_holddowns_total",
                   labels={"model": model})
        self._persist_state()

    def clear_holddown(self, model: str, version: str) -> None:
        """Operator override: release a held-down version."""
        with self._lock:
            self._holddown.pop((model, version), None)
        self._persist_state()

    # -------------------------------------------------- state durability
    def _persist_state(self) -> None:
        """Publish the hold-down ledger + autoscaler target to the
        state file — the journal's record framing through the atomic
        writer, so a kill mid-write leaves the previous state intact.
        Monotonic deadlines convert to wall clock for the trip through
        disk (a restart gets a fresh monotonic epoch). Runs OUTSIDE
        the membership lock; file I/O never holds it."""
        if self._state_path is None:
            return
        from deeplearning4j_tpu.serving.journal import write_records

        now_m, now_w = self._clock(), time.time()
        with self._lock:
            records = [{"kind": "holddown", "model": m, "version": v,
                        "failures": e["failures"],
                        "until_wall": now_w + (e["until"] - now_m),
                        "reason": e["reason"]}
                       for (m, v), e in self._holddown.items()]
            records.append({"kind": "autoscaler",
                            "target": len(self.replicas),
                            "scale_events": dict(self._scale_events)})
        try:
            write_records(self._state_path, records)
        except OSError:
            logger.warning("controller state persist to %s failed",
                           self._state_path, exc_info=True)

    def _restore_state(self) -> None:
        """Load whatever a previous controller persisted: expired
        hold-downs are dropped, live ones re-enter the ledger with
        their remaining wall-clock time; the autoscaler target is
        surfaced in stats() for the operator (membership itself is
        re-discovered from the router/factory, not conjured)."""
        if self._state_path is None \
                or not os.path.exists(self._state_path):
            return
        from deeplearning4j_tpu.serving.journal import read_records

        now_m, now_w = self._clock(), time.time()
        records, _, _ = read_records(self._state_path)
        for rec in records:
            if rec.get("kind") == "holddown":
                remaining = float(rec.get("until_wall", 0.0)) - now_w
                if remaining <= 0:
                    continue
                key = (str(rec.get("model")), str(rec.get("version")))
                with self._lock:
                    self._holddown[key] = {
                        "failures": int(rec.get("failures", 1)),
                        "until": now_m + remaining,
                        "reason": str(rec.get("reason",
                                              "restored from disk")),
                    }
            elif rec.get("kind") == "autoscaler":
                target = rec.get("target")
                self._restored_target = (int(target)
                                         if target is not None
                                         else None)

    # ---------------------------------------------------------- rollout
    def rollout(self, model: str, version: str,
                path: Optional[str] = None, canary_index: int = 0,
                **load_kwargs) -> dict:
        """Run the full rollout state machine; returns a report dict
        (`outcome` is "completed" or "rolled_back"). With `path` the
        version is loaded warm (activate=False) on each replica just
        before its flip; without it every replica must already hold
        `version` as a warm standby. Raises RolloutHeldError when the
        version is in hold-down."""
        if not self._rollout_lock.acquire(blocking=False):
            raise RuntimeError(
                f"a rollout is already in progress ({self._state})")
        try:
            return self._rollout_locked(model, version, path,
                                        canary_index, load_kwargs)
        finally:
            self._rollout_lock.release()

    def _rollout_locked(self, model, version, path, canary_index,
                        load_kwargs) -> dict:
        self._check_holddown(model, version)
        with self._lock:
            if not self.replicas:
                raise RuntimeError("fleet is empty — nothing to roll")
            order = list(self.replicas)
        canary = order.pop(canary_index % len(order))
        t_start = self._clock()
        report = {"model": model, "version": version,
                  "canary": canary.name, "flipped": [],
                  "outcome": None, "breach": None,
                  "detection_s": None, "baseline_p99_s": None,
                  "slo": self.slo.to_spec()}
        try:
            # pre-flip baseline window (only needed for ratio bounds)
            baseline_p99 = None
            if self.slo.max_p99_ratio is not None:
                s0 = canary.snapshot()
                self._sleep(self.slo.window_s)
                base = slo_sample(s0, canary.snapshot())
                if base["requests"] >= self.slo.min_requests:
                    baseline_p99 = base["p99_s"]
                report["baseline_p99_s"] = baseline_p99

            # ---- canary: warm, flip, watch
            self._set_state("canary")
            previous = canary.active_version(model)
            report["previous"] = previous
            if path is not None:
                canary.load_version(model, version, path,
                                    **load_kwargs)
            canary.swap(model, version)
            t_flip = self._clock()
            report["flipped"].append(canary.name)
            breach = self._watch(canary, self.slo.windows,
                                 baseline_p99)
            if breach is not None:
                return self._roll_back(report, [canary], model,
                                       breach, t_flip)

            # ---- ramp: replica by replica, health-checked between
            self._set_state("ramping")
            for replica in order:
                if path is not None:
                    replica.load_version(model, version, path,
                                         **load_kwargs)
                replica.swap(model, version)
                report["flipped"].append(replica.name)
                breach = self._watch(replica, self.slo.ramp_windows,
                                     baseline_p99)
                if breach is not None:
                    flipped = [canary] + order[:order.index(replica)
                                               + 1]
                    return self._roll_back(report, flipped, model,
                                           breach, t_flip)

            report["outcome"] = "completed"
            report["duration_s"] = self._clock() - t_start
            self._set_state("completed")
            _obs.count("dl4j_rollout_total",
                       labels={"model": model, "outcome": "completed"})
            self._remember(report)
            return report
        except RolloutHeldError:
            raise
        except Exception:
            # lifecycle errors (missing standby, unreachable replica)
            # surface to the caller, but the machine never wedges in a
            # transient state and the abort is observable
            self._set_state("idle")
            _obs.count("dl4j_rollout_total",
                       labels={"model": model, "outcome": "aborted"})
            raise

    def _watch(self, replica, windows: int,
               baseline_p99: Optional[float]) -> Optional[dict]:
        """Watch one replica for `windows` consecutive healthy
        windows; returns the breach ({reason, sample}) or None."""
        clean = 0
        prev = replica.snapshot()
        while clean < windows:
            self._sleep(self.slo.window_s)
            cur = replica.snapshot()
            sample = slo_sample(prev, cur)
            prev = cur
            reason = self.slo.breach(sample, baseline_p99)
            if reason is not None:
                return {"reason": reason, "sample": sample,
                        "replica": replica.name}
            clean += 1
        return None

    def _roll_back(self, report, flipped, model, breach,
                   t_flip) -> dict:
        detection_s = self._clock() - t_flip
        self._set_state("rolling_back")
        for replica in reversed(flipped):
            try:
                replica.rollback(model)
            except Exception:   # noqa: BLE001 - roll the rest back anyway
                logger.exception("rollback of %s on %s failed",
                                 model, replica.name)
        self._enter_holddown(model, report["version"],
                             breach["reason"])
        report["outcome"] = "rolled_back"
        report["breach"] = breach
        report["detection_s"] = detection_s
        self._set_state("held")
        _obs.count("dl4j_rollout_rollbacks_total",
                   labels={"model": model})
        _obs.count("dl4j_rollout_total",
                   labels={"model": model, "outcome": "rolled_back"})
        _obs.observe("dl4j_rollout_detection_seconds", detection_s)
        self._remember(report)
        return report

    def _remember(self, report: dict) -> None:
        with self._lock:
            self._history.append(report)
            del self._history[:-32]

    # -------------------------------------------------- fleet snapshots
    def fleet_snapshot(self) -> dict:
        """Every live replica's metric snapshot merged through the
        PR 7 cross-rank aggregation — counters summed, histogram
        buckets merged, gauges re-keyed per replica."""
        snaps = []
        with self._lock:
            handles = list(self.replicas)
        for h in handles:
            try:
                snaps.append(h.snapshot())
            except Exception:   # noqa: BLE001 - a dead replica can't block the scrape
                logger.warning("fleet snapshot: %s unreachable", h.name)
        return aggregate_snapshots(snaps)

    def fleet_prometheus_text(self) -> str:
        from deeplearning4j_tpu.observability.metrics import (
            render_prometheus,
        )

        return render_prometheus(self.fleet_snapshot())

    def fleet_slo_sample(self) -> Optional[dict]:
        """The most recent tick-over-tick SLO sample of the AGGREGATED
        fleet (None until two ticks have run)."""
        with self._lock:
            return (dict(self._last_fleet_sample)
                    if self._last_fleet_sample else None)

    # ------------------------------------------------------- autoscaler
    def start(self) -> "FleetController":
        """Run the health+autoscale control loop in a background
        thread (one `tick()` per autoscale_interval_s)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name="FleetController-loop")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.autoscale_interval_s):
            try:
                self.tick()
            except Exception:   # noqa: BLE001 - the loop must survive a bad tick
                logger.exception("FleetController tick failed")

    def tick(self) -> dict:
        """One control-loop step: health poll (replica death →
        remove + backfill), then the scale decision from the fleet's
        shed-rate / queue-depth / throughput deltas. Public so drills
        can step the loop deterministically."""
        now = self._clock()
        self._health_poll()
        self._backfill_to_min()

        agg = self.fleet_snapshot()
        decision = {"action": None, "reason": None}
        if self._prev_fleet is not None and self._prev_tick_t is not None:
            dt = max(1e-9, now - self._prev_tick_t)
            sample = slo_sample(self._prev_fleet, agg)
            admitted = (_counter_total(agg,
                                       "dl4j_serving_admitted_total")
                        - _counter_total(self._prev_fleet,
                                         "dl4j_serving_admitted_total"))
            shed = (_counter_total(agg, "dl4j_serving_shed_total")
                    - _counter_total(self._prev_fleet,
                                     "dl4j_serving_shed_total"))
            attempts = admitted + shed
            shed_rate = shed / attempts if attempts > 0 else 0.0
            depth = max([0.0] + [
                v for v in (agg.get("gauges", {})
                            .get("dl4j_serving_queue_depth") or {})
                .values()])
            rps = sample["requests"] / dt
            sample.update({"shed_rate": shed_rate,
                           "queue_depth": depth, "rps": rps,
                           "dt_s": dt})
            with self._lock:
                self._last_fleet_sample = sample
                n = len(self.replicas)
            cooled = (self._last_scale_t is None
                      or now - self._last_scale_t >= self.cooldown_s)
            if cooled and n < self.max_replicas and (
                    shed_rate > self.scale_up_shed_rate
                    or depth > self.scale_up_queue_depth):
                decision = {"action": "up",
                            "reason": f"shed_rate={shed_rate:.3f} "
                                      f"depth={depth:g}"}
                self._scale_up(now)
            elif cooled and n > self.min_replicas \
                    and shed_rate == 0.0 \
                    and depth <= 0.0 \
                    and rps / max(1, n) \
                    < self.scale_down_rps_per_replica:
                decision = {"action": "down",
                            "reason": f"rps/replica="
                                      f"{rps / max(1, n):.2f}"}
                self._scale_down(now)
        self._prev_fleet = agg
        self._prev_tick_t = now
        return decision

    def _health_poll(self) -> None:
        with self._lock:
            handles = list(self.replicas)
        for h in handles:
            dead = False
            try:
                # chaos drill: an armed raise is consumed as a forced
                # "this replica is dead" verdict — the SIGKILL drill
                # without killing a real process
                _fire("serving.replica_kill")
            except FaultInjectedError:
                dead = True
            if not dead:
                dead = not h.healthy()
            if dead:
                self._remove_dead(h)

    def _remove_dead(self, handle) -> None:
        """Drop a dead replica and retire its backing.

        Generation durability rides on this ordering: membership drops
        FIRST (drain=False — the replica is dead, nothing to wait for),
        so the router treats any in-flight failure on it as an
        orchestrated removal, not replica badness; then `retire()` —
        for a still-reachable ModelServer that stops the decode engines
        BEFORE the HTTP listener, so in-flight generations answer 503
        with their resumable partial streams and the router's
        `generate` failover re-dispatches them to a healthy replica as
        continuations. A hard-killed replica leaves no partial; those
        requests restart from their prompts, which greedy decode makes
        byte-identical anyway."""
        logger.warning("replica %s is dead; removing from the fleet",
                       handle.name)
        with self._lock:
            self.replicas = [r for r in self.replicas
                             if r is not handle]
            self._deaths += 1
        if self.router is not None:
            try:
                self.router.remove_replica(handle.name, drain=False)
            except ValueError:
                pass   # already gone from the router
        try:
            handle.retire()
        except Exception:   # noqa: BLE001 - it is already dead
            pass
        _obs.count("dl4j_fleet_replica_deaths_total")
        self._emit_pool_gauge()

    def _backfill_to_min(self) -> None:
        """Replace dead capacity up to min_replicas immediately —
        backfill is repair, not scaling, so no cooldown applies."""
        if self.replica_factory is None:
            return
        while True:
            with self._lock:
                need = len(self.replicas) < self.min_replicas
            if not need:
                return
            self._spawn_replica()

    def _spawn_replica(self) -> None:
        handle = self.replica_factory()
        if self.router is not None:
            self.router.add_replica(handle.name)
        with self._lock:
            self.replicas.append(handle)
        self._emit_pool_gauge()

    def _scale_up(self, now: float) -> None:
        if self.replica_factory is None:
            return
        self._spawn_replica()
        self._last_scale_t = now
        with self._lock:
            self._scale_events["up"] += 1
        _obs.count("dl4j_fleet_scale_events_total",
                   labels={"direction": "up"})
        self._persist_state()

    def _scale_down(self, now: float) -> None:
        with self._lock:
            if len(self.replicas) <= self.min_replicas:
                return
            victim = self.replicas[-1]
        # the router DRAINS the victim's in-flight requests before
        # membership drops; only then does the replica's own
        # drain-then-retire machinery tear it down
        if self.router is not None:
            try:
                self.router.remove_replica(
                    victim.name, drain=True,
                    drain_timeout_s=self.drain_timeout_s)
            except ValueError:
                pass
        with self._lock:
            self.replicas = [r for r in self.replicas
                             if r is not victim]
        try:
            victim.retire()
        except Exception:   # noqa: BLE001 - best-effort teardown
            logger.exception("retire of %s failed", victim.name)
        self._last_scale_t = now
        with self._lock:
            self._scale_events["down"] += 1
        _obs.count("dl4j_fleet_scale_events_total",
                   labels={"direction": "down"})
        self._emit_pool_gauge()
        self._persist_state()

    # ------------------------------------------------------------ facts
    def stats(self) -> dict:
        now = self._clock()
        with self._lock:
            return {
                "replicas": [r.name for r in self.replicas],
                "rollout": {"state": self._state,
                            "history": list(self._history)},
                "holddown": {
                    f"{m}:{v}": {
                        "failures": e["failures"],
                        "remaining_s": max(0.0, e["until"] - now),
                        "reason": e["reason"],
                    } for (m, v), e in self._holddown.items()},
                "autoscaler": {
                    "scale_events": dict(self._scale_events),
                    "deaths": self._deaths,
                    "last_sample": (dict(self._last_fleet_sample)
                                    if self._last_fleet_sample
                                    else None),
                    "min": self.min_replicas,
                    "max": self.max_replicas,
                    "restored_target": self._restored_target,
                },
                "state_path": self._state_path,
            }
