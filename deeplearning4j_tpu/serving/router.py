"""ReplicaRouter: client-side spreading over N ModelServer replicas.

The scale-out half of the serving story: one logical client over many
server URLs. Per-replica health is the CircuitBreaker already wired
into every ModelClient (503s/connection failures trip it; any response
proves liveness); the router adds:

  picking    least-outstanding-requests among replicas whose breaker
             admits traffic (open circuits are skipped without paying
             a connection attempt), with round-robin tie-breaking so
             equal replicas share load;
  failover   an unavailable-class failure (connection error, retry
             exhaustion, 503, open circuit) moves the request to the
             next-best replica automatically — the caller sees one
             logical call. Responses that prove the server is alive
             but unhappy (400/404/429/500) surface immediately:
             another replica would answer the same.
  membership `add_replica`/`remove_replica` at runtime — the
             FleetController's autoscaler grows and shrinks the pool
             through these. Removal DRAINS by default: the replica
             stops being picked immediately, and the call blocks
             (bounded) until its in-flight requests finish. A replica
             removed mid-flight (autoscale shrink, replica kill) still
             fails over, but the failure is NOT counted against the
             removed replica's accounting — an orchestrated removal is
             not replica badness.

`NoHealthyReplicaError` (with the last failure as `cause` and the
fleet `membership` snapshot at failure time) is raised only when every
replica has been tried or is open-circuited.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Callable, List, Optional

from deeplearning4j_tpu.observability import metrics as _obs
from deeplearning4j_tpu.resilience.errors import (
    CircuitOpenError,
    FaultInjectedError,
    NoHealthyReplicaError,
    RetriesExhaustedError,
    ServingError,
)
from deeplearning4j_tpu.resilience.faults import fire as _fire

# NOTE: ModelClient is imported lazily inside _default_factory —
# parallel/serving.py imports this package for the control-plane
# classes, so a module-level import here would be circular.

# failures that mean "this REPLICA is unavailable" — fail over.
_FAILOVER = (ConnectionError, OSError, TimeoutError,
             RetriesExhaustedError, CircuitOpenError)


def _default_factory(timeout: float):
    from deeplearning4j_tpu.parallel.serving import ModelClient

    return lambda url: ModelClient(url, timeout=timeout)


class _Replica:
    __slots__ = ("url", "client", "outstanding", "requests",
                 "failures", "draining")

    def __init__(self, url: str, client):
        self.url = url
        self.client = client
        self.outstanding = 0
        self.requests = 0
        self.failures = 0
        self.draining = False


class ReplicaRouter:
    """Spread requests across ModelServer replicas with
    least-outstanding picking, automatic failover, and runtime
    membership (`add_replica`/`remove_replica` with in-flight
    draining).

    `client_factory(url)` defaults to a ModelClient with its stock
    CircuitBreaker and retry policy; inject a factory to tune either
    (or to stub replicas in tests)."""

    def __init__(self, urls: List[str], timeout: float = 30.0,
                 client_factory: Optional[Callable] = None,
                 tracer=None):
        if not urls:
            raise ValueError("ReplicaRouter needs at least one URL")
        self._factory = client_factory or _default_factory(timeout)
        self._replicas = [_Replica(u.rstrip("/"), self._factory(u))
                          for u in urls]
        self._lock = threading.Lock()
        self._rr = 0
        self.failovers = 0
        # optional observability.tracing.Tracer: when set, generate()
        # opens a client-side root span and a per-leg span per replica
        # attempt so the merged timeline shows the migration hops
        self.tracer = tracer

    # ----------------------------------------------------- membership
    def urls(self) -> List[str]:
        """Current fleet membership (draining replicas included — they
        are still finishing in-flight work)."""
        with self._lock:
            return [r.url for r in self._replicas]

    def add_replica(self, url: str, client=None) -> None:
        """Join a replica to the pool; it becomes pickable
        immediately. `client` defaults to one from the router's
        factory."""
        url = url.rstrip("/")
        with self._lock:
            if any(r.url == url for r in self._replicas):
                raise ValueError(f"replica {url!r} is already a member")
        # client construction stays outside the lock (it may do I/O)
        replica = _Replica(url, client if client is not None
                           else self._factory(url))
        with self._lock:
            if any(r.url == url for r in self._replicas):
                raise ValueError(f"replica {url!r} is already a member")
            self._replicas.append(replica)

    def remove_replica(self, url: str, drain: bool = True,
                       drain_timeout_s: float = 10.0) -> bool:
        """Leave the pool. The replica stops being picked immediately;
        with `drain=True` the call waits (bounded) for its in-flight
        requests to finish before membership drops. Returns True when
        the replica left with zero requests still in flight."""
        url = url.rstrip("/")
        with self._lock:
            target = next((r for r in self._replicas if r.url == url),
                          None)
            if target is None:
                raise ValueError(f"no replica {url!r} in the pool")
            target.draining = True
        deadline = time.monotonic() + (drain_timeout_s if drain else 0.0)
        while True:
            with self._lock:
                clear = target.outstanding == 0
            if clear or time.monotonic() >= deadline:
                break
            time.sleep(0.005)
        with self._lock:
            self._replicas = [r for r in self._replicas
                              if r is not target]
        return clear

    def _is_member(self, replica: _Replica) -> bool:
        with self._lock:
            return any(r is replica for r in self._replicas) \
                and not replica.draining

    # -------------------------------------------------------- picking
    def _pick(self, exclude: set) -> Optional[_Replica]:
        """Least outstanding among breaker-admitting, non-draining
        replicas not yet tried for this request; round-robin offset
        breaks ties so idle-equal replicas alternate."""
        with self._lock:
            n = len(self._replicas)
            best, best_key = None, None
            for i in range(n):
                r = self._replicas[(self._rr + i) % n]
                if r.url in exclude or r.draining:
                    continue
                if r.client.breaker is not None \
                        and not r.client.breaker.allow():
                    continue
                key = r.outstanding
                if best is None or key < best_key:
                    best, best_key = r, key
            if best is not None:
                self._rr = (self._rr + 1) % n
                best.outstanding += 1
                best.requests += 1
            return best

    def _release(self, r: _Replica, failed: bool) -> None:
        with self._lock:
            r.outstanding -= 1
            if failed:
                r.failures += 1

    # -------------------------------------------------------- calling
    def _call(self, fn: Callable[[_Replica], dict]) -> dict:
        tried: set = set()
        causes: list = []
        last: Optional[Exception] = None
        while True:
            r = self._pick(tried)
            if r is None:
                break
            tried.add(r.url)
            try:
                out = fn(r)
            except _FAILOVER as exc:
                # a replica removed mid-flight (shrink or kill) fails
                # over WITHOUT the failure counting against it — the
                # removal was orchestrated, not replica badness
                removed = not self._is_member(r)
                self._release(r, failed=not removed)
                if not removed:
                    last = exc
                    causes.append((r.url, exc))
                    with self._lock:
                        self.failovers += 1
                    _obs.count("dl4j_serving_replica_failovers_total")
                continue
            except ServingError as exc:
                removed = not self._is_member(r)
                self._release(r, failed=exc.retryable and not removed)
                if exc.retryable:   # 503/429: the replica is drowning
                    if not removed:
                        last = exc
                        causes.append((r.url, exc))
                        with self._lock:
                            self.failovers += 1
                        _obs.count(
                            "dl4j_serving_replica_failovers_total")
                    continue
                raise               # 400/404/500: same answer anywhere
            self._release(r, failed=False)
            return out
        raise NoHealthyReplicaError(
            f"no healthy replica answered (tried {sorted(tried)}; "
            f"last: {last!r})", cause=last, membership=self.urls(),
            causes=causes)

    def predict(self, inputs, model: Optional[str] = None,
                tenant: Optional[str] = None,
                decode_top: int = 0) -> dict:
        return self._call(lambda r: r.client.predict(
            inputs, decode_top=decode_top, model=model, tenant=tenant))

    @staticmethod
    def _resumable_partial(exc: Exception) -> Optional[dict]:
        """The resumable-partial body a retiring replica shipped with
        its failure, or None when the failure carries none."""
        if isinstance(exc, RetriesExhaustedError):
            exc = exc.cause
        if isinstance(exc, ServingError):
            body = exc.body or {}
            if body.get("resumable") and body.get("tokens") is not None:
                return body
        return None

    def generate(self, prompt, max_new_tokens: int = 16,
                 eos_id: Optional[int] = None,
                 model: Optional[str] = None,
                 tenant: Optional[str] = None,
                 timeout_s: Optional[float] = None,
                 deadline_s: Optional[float] = None,
                 resume_tokens: Optional[list] = None,
                 request_id: Optional[str] = None,
                 trace: Optional[str] = None) -> dict:
        """One logical generation over the fleet, with cross-replica
        MIGRATION: when the serving replica dies or retires
        mid-generation, its resumable 503 body (tokens decoded so far)
        is re-dispatched to the next healthy replica as a continuation
        — the target re-prefills the ORIGINAL prompt and force-replays
        the recorded tokens through the shared decode loop, so the
        final stream is byte-identical to an un-faulted run. A
        hard-killed replica leaves no partial; the request restarts
        from the prompt, which greedy decode makes byte-identical
        anyway. The armed `serving.migrate_fail` fault drops the
        continuation (the handoff itself failed) and the request
        restarts from the prompt — still losing nothing.

        The response dict gains `migrations`: how many times this
        request's partial stream moved between replicas.

        `request_id` (client-generated here when not supplied) is ONE
        idempotency key for the whole logical request: every failover
        attempt carries it, so a replica that already journaled the
        stream — including one recovered from its journal after a
        fleet-wide outage — joins it instead of double-executing.

        `trace` rides the same road: ONE trace id for the whole
        logical request, re-sent with every failover attempt, so the
        legs a migrating generation leaves on different replicas merge
        into a single timeline (observability.tracing
        `merge_chrome_traces`). Minted here when the router has a
        tracer and the caller supplied none."""
        rid = str(request_id) if request_id else uuid.uuid4().hex
        tid = str(trace) if trace else None
        if self.tracer is not None:
            if tid is None:
                from deeplearning4j_tpu.observability.tracing import (
                    new_trace_id,
                )
                tid = new_trace_id()
            with self.tracer.span("client.generate", cat="client",
                                  args={"trace": tid,
                                        "request_id": rid}):
                return self._generate_attempts(
                    prompt, max_new_tokens, eos_id, model, tenant,
                    timeout_s, deadline_s, resume_tokens, rid, tid)
        return self._generate_attempts(
            prompt, max_new_tokens, eos_id, model, tenant, timeout_s,
            deadline_s, resume_tokens, rid, tid)

    def _leg_span(self, tid, url, t0, ok: bool) -> None:
        """One pre-measured `client.leg` span per replica attempt —
        failed legs show on the timeline too (that's the point)."""
        if self.tracer is None or tid is None:
            return
        self.tracer.record("client.leg", t0, time.perf_counter(),
                           cat="client",
                           args={"trace": tid, "replica": url,
                                 "ok": ok})

    def _generate_attempts(self, prompt, max_new_tokens, eos_id, model,
                           tenant, timeout_s, deadline_s, resume_tokens,
                           rid, tid) -> dict:
        tried: set = set()
        causes: list = []
        last: Optional[Exception] = None
        resume = ([int(t) for t in resume_tokens]
                  if resume_tokens else [])
        migrations = 0
        while True:
            r = self._pick(tried)
            if r is None:
                break
            tried.add(r.url)
            continuation = list(resume)
            if continuation:
                try:
                    _fire("serving.migrate_fail")
                except FaultInjectedError:
                    # the migration handoff itself failed: drop the
                    # tokens-so-far and restart from the prompt on this
                    # replica — greedy decode is deterministic, so the
                    # output is unchanged either way
                    continuation = []
            if continuation:
                migrations += 1
                _obs.count("dl4j_decode_migrations_total")
            t_leg = time.perf_counter()
            try:
                # max_resumes=0: migration is the ROUTER's job here —
                # the client surfaces the resumable failure instead of
                # hammering the same dying replica with continuations
                out = r.client.generate(
                    prompt, max_new_tokens, eos_id=eos_id, model=model,
                    tenant=tenant, timeout_s=timeout_s,
                    deadline_s=deadline_s,
                    resume_tokens=continuation or None, max_resumes=0,
                    request_id=rid, trace=tid)
            except _FAILOVER as exc:
                self._leg_span(tid, r.url, t_leg, ok=False)
                removed = not self._is_member(r)
                self._release(r, failed=not removed)
                partial = self._resumable_partial(exc)
                if partial is not None:
                    got = partial.get("tokens") or []
                    if len(got) > len(resume):
                        resume = [int(t) for t in got]
                if not removed:
                    last = exc
                    causes.append((r.url, exc))
                    with self._lock:
                        self.failovers += 1
                    _obs.count("dl4j_serving_replica_failovers_total")
                continue
            except ServingError as exc:
                self._leg_span(tid, r.url, t_leg, ok=False)
                removed = not self._is_member(r)
                partial = self._resumable_partial(exc)
                self._release(r, failed=exc.retryable and not removed)
                if partial is not None:
                    got = partial.get("tokens") or []
                    if len(got) > len(resume):
                        resume = [int(t) for t in got]
                if not (exc.retryable or partial is not None):
                    raise       # 400/404/500: same answer anywhere
                if not removed:
                    last = exc
                    causes.append((r.url, exc))
                    with self._lock:
                        self.failovers += 1
                    _obs.count("dl4j_serving_replica_failovers_total")
                continue
            self._leg_span(tid, r.url, t_leg, ok=True)
            self._release(r, failed=False)
            out["migrations"] = migrations
            if tid is not None:
                out.setdefault("trace", tid)
            return out
        raise NoHealthyReplicaError(
            f"no healthy replica finished the generation "
            f"(tried {sorted(tried)}; last: {last!r})", cause=last,
            membership=self.urls(), causes=causes)

    def status(self, model: Optional[str] = None) -> dict:
        return self._call(lambda r: r.client.status(model=model))

    # ---------------------------------------------------------- facts
    def stats(self) -> dict:
        with self._lock:
            return {
                "failovers": self.failovers,
                "replicas": [{
                    "url": r.url,
                    "outstanding": r.outstanding,
                    "requests": r.requests,
                    "failures": r.failures,
                    "draining": r.draining,
                    "breaker": (r.client.breaker.state
                                if r.client.breaker is not None
                                else None),
                } for r in self._replicas],
            }
