"""Serving control plane above the ParallelInference data plane.

PRs 1-2 built a single-model data plane (pipelined batching, bounded
queues, deadlines, integrity-checked persistence); this package is the
control plane that makes it multi-model and multi-tenant:

  registry.py   ModelRegistry — N named models × versions, verified
                loads, zero-downtime hot-swap, one-call rollback,
                background drain/retire;
  admission.py  AdmissionController — per-tenant token-bucket quotas +
                priority classes with shed-lowest-first load shedding
                in front of the bounded queue;
  router.py     ReplicaRouter — client-side least-outstanding spreading
                over N ModelServer replicas with CircuitBreaker health,
                automatic failover, and runtime membership
                (add_replica/remove_replica with in-flight draining);
  controller.py FleetController — the fleet supervisor: canary/ramp
                rollouts auto-rolled-back on SLO breach (hold-down
                ledger against tight relaunch loops), metric-driven
                autoscaling of the replica pool, replica-death
                detection + backfill, fleet-level metric aggregation.

The HTTP surface (the /v1/models routes) lives on ModelServer in
parallel/serving.py, which consumes all of these.
"""

from deeplearning4j_tpu.serving.admission import (  # noqa: F401
    DEFAULT_SHED_THRESHOLDS,
    PRIORITY_CLASSES,
    AdmissionController,
    TenantConfig,
    TokenBucket,
)
from deeplearning4j_tpu.serving.registry import (  # noqa: F401
    ModelEntry,
    ModelRegistry,
)
from deeplearning4j_tpu.serving.router import ReplicaRouter  # noqa: F401
from deeplearning4j_tpu.serving.continuous import (  # noqa: F401
    DecodeEngine,
    GenerationHandle,
    sequential_decode,
)
from deeplearning4j_tpu.serving.controller import (  # noqa: F401
    ROLLOUT_STATES,
    FleetController,
    HttpReplica,
    LocalReplica,
    SLOPolicy,
    slo_sample,
)

__all__ = [
    "DEFAULT_SHED_THRESHOLDS", "PRIORITY_CLASSES", "ROLLOUT_STATES",
    "AdmissionController", "TenantConfig", "TokenBucket",
    "ModelEntry", "ModelRegistry", "ReplicaRouter",
    "FleetController", "HttpReplica", "LocalReplica", "SLOPolicy",
    "slo_sample",
    "DecodeEngine", "GenerationHandle", "sequential_decode",
]
