"""Serving control plane above the ParallelInference data plane.

PRs 1-2 built a single-model data plane (pipelined batching, bounded
queues, deadlines, integrity-checked persistence); this package is the
control plane that makes it multi-model and multi-tenant:

  registry.py   ModelRegistry — N named models × versions, verified
                loads, zero-downtime hot-swap, one-call rollback,
                background drain/retire;
  admission.py  AdmissionController — per-tenant token-bucket quotas +
                priority classes with shed-lowest-first load shedding
                in front of the bounded queue;
  router.py     ReplicaRouter — client-side least-outstanding spreading
                over N ModelServer replicas with CircuitBreaker health
                and automatic failover.

The HTTP surface (the /v1/models routes) lives on ModelServer in
parallel/serving.py, which consumes all three.
"""

from deeplearning4j_tpu.serving.admission import (  # noqa: F401
    DEFAULT_SHED_THRESHOLDS,
    PRIORITY_CLASSES,
    AdmissionController,
    TenantConfig,
    TokenBucket,
)
from deeplearning4j_tpu.serving.registry import (  # noqa: F401
    ModelEntry,
    ModelRegistry,
)
from deeplearning4j_tpu.serving.router import ReplicaRouter  # noqa: F401

__all__ = [
    "DEFAULT_SHED_THRESHOLDS", "PRIORITY_CLASSES",
    "AdmissionController", "TenantConfig", "TokenBucket",
    "ModelEntry", "ModelRegistry", "ReplicaRouter",
]
