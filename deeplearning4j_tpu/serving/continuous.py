"""Continuous batching: the slot-based autoregressive decode engine.

ROADMAP item 3a — THE serving regime for autoregressive traffic at
"millions of users" scale. The fixed-shape request pipeline
(ParallelInference) coalesces one-shot requests into pow2 buckets;
generation is different: a request is ALIVE for many steps, and naive
per-request serving pays a full program dispatch per token for ONE
stream. The DecodeEngine instead runs ONE compiled decode step over a
fixed `max_slots` batch (engine/decode_program.DecodeProgram) and
treats request lifecycle as pure data:

  join    an admitted request claims a free slot at ANY step: its
          prompt prefills in page_size CHUNKS, one chunk dispatch
          interleaved per engine step, so a long prompt never stalls
          resident generations; once its K/V pages are in, a uniform
          first-token decode step (write suppressed — the cells are
          already written) emits its first token and the slot rides
          the shared decode loop. Nothing recompiles;
  leave   EOS or max-tokens frees the slot between two steps; the
          program never learns a request ended (per-slot active masks
          are host state — the compiled shape is invariant);
  evict   the `serving.slot_evict` fault point (chaos drills) can rip
          an active request out mid-generation: its recovery is
          re-prefill of the ORIGINAL prompt on a free slot + forced
          replay of the already-emitted tokens through the shared
          decode loop. Replay recomputes the exact K/V the evicted
          slot held (same programs, same inputs), so the continuation
          is byte-identical to a never-evicted run — the property
          `sequential_decode` oracles pin.

Paged KV virtual memory (this file owns the HOST half; the compiled
half is engine/decode_program.py): each slot holds a ring page table
over a shared refcounted physical pool (PagePool) —

  share   a PrefixTrie caches prompt pages by page-aligned token
          blocks; N requests with a common prefix MAP the same
          read-only pages (one pool ref per referent), and the Kth
          identical prompt skips prefill entirely. Sharing is bitwise
          safe because a shared page holds exactly the bytes its
          unshared twin would have computed, and the uniform
          first-token step runs identically either way;
  CoW     the first generation write into a page something else still
          references (a trie entry, a prefix twin) copies it first
          (`decode_page_copy`) — divergence costs one page copy, not
          correctness;
  wrap    logical positions run PAST the attention window: the ring
          table recycles the slot's own oldest page (sliding-window
          attention), so long generations never die at max_ctx;
  reclaim under pool pressure the engine LRU-evicts trie-only cached
          pages, then evicts resident requests (replay makes that
          safe); page quarantine mirrors slot quarantine — a poisoned
          slot's PRIVATE pages are written off, its trie
          registrations purged, while genuinely shared pages merely
          lose a reference (the poison only ever wrote private
          cells).

Byte-identity contract: greedy decoding + per-slot independence of the
compiled step mean every emitted token is a deterministic function of
the request's own tokens — independent of which slot it lands in, who
its neighbors are, and when it joins. tests/test_decode.py pins
engine output == sequential per-request oracle under staggered churn
AND mid-soak eviction chaos.

Generation durability (the crash-proof layer on the same replay
mechanism — a generation request is a durable object, not
slot-lifetime ephemera):

  continuation  `submit(resume_tokens=[...])` re-enters a stream that
          already emitted tokens ELSEWHERE (an evicted replica, a
          dropped connection): re-prefill + forced replay of the
          recorded tokens, then greedy continuation — byte-identical
          to an uninterrupted run. This is the eviction-recovery path
          crossing process boundaries (the wire field ModelServer /
          ReplicaRouter migration rides).
  quarantine  the decode step returns a per-slot finite-logits
          verdict (engine/decode_program.py, the NonFiniteGuard
          discipline applied to serving); a non-finite slot is
          quarantined — NEVER reused — and its request replayed on a
          healthy slot. Poison that travels WITH a request (its own
          tokens drive the numerics) aborts with
          GenerationPoisonedError after `poison_strike_limit` strikes
          instead of quarantining the fleet slot by slot. The
          `decode.nonfinite` fault point forces the verdict
          deterministically.
  watchdog  `watchdog_timeout_s=` arms a StepWatchdog
          (resilience/supervisor.py) over the loop thread's
          heartbeats; a hung iteration (the `decode.hang` drill)
          escalates to engine teardown + bounded restart
          (`max_engine_restarts`): fresh KV cache, every live request
          re-queued as a replay continuation — never an indefinite
          hang, never a lost stream.
  deadline  `submit(deadline_s=)` / `GenerationHandle.cancel()` free
          the slot at the next step boundary and finish the handle
          with its PARTIAL tokens and an explicit finish_reason
          ("deadline" / "cancelled") — surfaced as 504/partial over
          HTTP.

Admission rides the same vocabulary as the fixed-shape plane: an
optional AdmissionController (tenant quotas / priority shed) in front,
and a hard capacity bound (`max_slots` resident + `queue_limit`
waiting) that rejects with QuotaExceededError -> HTTP 429 +
Retry-After on slot exhaustion.

Per-token accumulation is streaming-capable: tokens land in the
handle under a condition variable as they are emitted
(`tokens_so_far()` / `wait_for_tokens(n)`), so a streaming transport
can drain mid-generation; `result()` blocks for the final sequence.
"""

from __future__ import annotations

import threading
import time
import uuid
import weakref
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.observability import metrics as _obs
from deeplearning4j_tpu.resilience.errors import (
    FaultInjectedError,
    GenerationPoisonedError,
    QuotaExceededError,
    RestartsExhaustedError,
    ShutdownError,
)
from deeplearning4j_tpu.resilience.faults import fire as _fire
from deeplearning4j_tpu.serving.flight import FlightRecorder

# every engine constructed in this process (weak — dead engines drop
# out); tests/conftest.py reaps whatever a failed chaos test left
# running so no loop/watchdog thread leaks into later tier-1 tests
_LIVE_ENGINES: "weakref.WeakSet[DecodeEngine]" = weakref.WeakSet()


def _ring_quantile(ring, q: float) -> Optional[float]:
    """Exact quantile over a bounded ring of recent observations (the
    window IS the estimator — same discipline as _Hist.quantile)."""
    vals = sorted(ring)
    if not vals:
        return None
    idx = min(len(vals) - 1, max(0, int(q * len(vals))))
    return vals[idx]


def reap_stray_engines() -> None:
    """Stop every engine still running (loop thread, watchdog, zombie
    restart threads). Teardown backstop for chaos tests — idempotent,
    touches nothing if every engine was stopped properly."""
    for eng in list(_LIVE_ENGINES):
        if eng.running or eng._watchdog is not None:
            eng.stop()


class GenerationHandle:
    """One generation stream: prompt in, tokens accumulating out.

    Thread-safe: the engine loop appends, any number of consumers
    read. `finish_reason` is "eos" (the eos token was emitted — it IS
    included in the output), "length" (max_new_tokens reached),
    "deadline" (the submit deadline expired — the tokens are a
    PARTIAL result), or "cancelled" (`cancel()` was honored — also
    partial). Failure finishes carry reason None and an error that
    `result()` re-raises."""

    def __init__(self, prompt: List[int], max_new_tokens: int,
                 eos_id: Optional[int],
                 deadline_s: Optional[float] = None,
                 request_id: Optional[str] = None,
                 tenant: Optional[str] = None,
                 trace: Optional[str] = None):
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.eos_id = eos_id
        self.request_id = request_id
        self.tenant = tenant
        self.trace = trace
        self.finish_reason: Optional[str] = None
        self.evictions = 0
        self.replays = 0
        self.poison_strikes = 0
        # latency-attribution clock marks (perf_counter values, set by
        # the engine): submit -> first placement -> first/last emitted
        # token. TTFT = first_token - submit, ITL = successive token
        # gaps, queue wait = placed - submit; a resumed continuation
        # restarts the marks on its new engine, so attribution is
        # per-leg, never cross-process clock arithmetic
        self.t_submit = time.perf_counter()
        self.t_placed: Optional[float] = None
        self.t_first_token: Optional[float] = None
        self.t_last_token: Optional[float] = None
        # root span of this leg's span tree (engine-owned; None when
        # the engine has no tracer — the default-off zero-cost path)
        self._span = None
        self._deadline = (time.monotonic() + float(deadline_s)
                          if deadline_s is not None else None)
        self._cancel_requested = False
        self._tokens: List[int] = []
        self._cond = threading.Condition()
        self._done = False
        self._error: Optional[BaseException] = None

    # ------------------------------------------------------- consumers
    def tokens_so_far(self) -> List[int]:
        with self._cond:
            return list(self._tokens)

    def wait_for_tokens(self, n: int, timeout_s: float = 30.0) -> List[int]:
        """Block until at least `n` tokens exist (or the stream ends);
        the streaming-transport primitive."""
        with self._cond:
            self._cond.wait_for(
                lambda: self._done or len(self._tokens) >= n,
                timeout=timeout_s)
            return list(self._tokens)

    @property
    def done(self) -> bool:
        with self._cond:
            return self._done

    @property
    def failed(self) -> bool:
        """True once the stream finished WITH an error (engine
        shutdown, poison exhaustion). A failed handle is a dead end:
        a re-submit under the same request_id is a retry of work that
        never completed, not a duplicate — the idempotency dedup must
        not pin the caller to it."""
        with self._cond:
            return self._done and self._error is not None

    def cancel(self) -> None:
        """Request cancellation: the engine frees the slot at its next
        step boundary and finishes the handle with the tokens emitted
        so far and finish_reason "cancelled"."""
        with self._cond:
            self._cancel_requested = True
            self._cond.notify_all()

    def result(self, timeout_s: Optional[float] = 60.0) -> List[int]:
        with self._cond:
            if not self._cond.wait_for(lambda: self._done,
                                       timeout=timeout_s):
                raise TimeoutError(
                    f"generation not finished within {timeout_s}s "
                    f"({len(self._tokens)}/{self.max_new_tokens} tokens)")
            if self._error is not None:
                raise self._error
            return list(self._tokens)

    # ---------------------------------------------------- engine side
    def _append(self, tok: int) -> None:
        with self._cond:
            self._tokens.append(tok)
            self._cond.notify_all()

    def _preload(self, tokens: Sequence[int]) -> None:
        """Seed already-emitted tokens into a fresh handle (wire
        continuation: the stream's earlier life happened on another
        replica / connection)."""
        with self._cond:
            self._tokens.extend(int(t) for t in tokens)
            self._cond.notify_all()

    def _finish(self, reason: Optional[str],
                error: Optional[BaseException] = None) -> None:
        with self._cond:
            self.finish_reason = reason
            self._error = error
            self._done = True
            self._cond.notify_all()


class PagePool:
    """Refcounted allocator over the physical page axis of the
    DecodeProgram pool. Page 0 is scratch (never allocated). A page is
    free iff its refcount is 0 and it is not quarantined; referents
    are slot page-table entries and prefix-trie registrations — one
    retain per referent, exact by construction (the refcount-exactness
    test drains the engine and audits this)."""

    def __init__(self, n_pages: int):
        self.n_pages = int(n_pages)
        self.ref = np.zeros(self.n_pages, np.int64)
        self._free: deque = deque(range(1, self.n_pages))
        # pages written by a quarantined slot: their bytes may be
        # numeric poison — written off, never freed (the page-granular
        # analog of never reusing a quarantined slot)
        self.quarantined: set = set()

    def alloc(self) -> Optional[int]:
        if not self._free:
            return None
        p = self._free.popleft()
        self.ref[p] = 1
        return p

    def retain(self, page: int) -> None:
        self.ref[page] += 1

    def release(self, page: int) -> None:
        self.ref[page] -= 1
        if self.ref[page] == 0 and page not in self.quarantined:
            self._free.append(page)

    def quarantine(self, page: int) -> None:
        """Drop one referent's ref AND write the page off: when the
        last referent lets go it parks in the quarantined set instead
        of the free list."""
        self.quarantined.add(page)
        self.release(page)

    @property
    def free_count(self) -> int:
        return len(self._free)

    def shared_count(self) -> int:
        return int(np.sum(self.ref > 1))

    def audit(self) -> Dict:
        """Exact page accounting (the no-leak/no-double-free pin):
        every non-scratch page is free, referenced, or quarantined —
        `leaked` must be 0 and no page may appear twice."""
        free = list(self._free)
        referenced = int(np.sum(self.ref[1:] > 0))
        quarantined_parked = sum(1 for p in self.quarantined
                                 if self.ref[p] == 0)
        usable = self.n_pages - 1
        return {
            "total": usable,
            "free": len(free),
            "referenced": referenced,
            "quarantined": quarantined_parked,
            "leaked": usable - len(free) - referenced
                      - quarantined_parked,
            "double_freed": len(free) != len(set(free))
                            or any(self.ref[p] != 0 for p in free),
        }


class _TrieNode:
    __slots__ = ("children", "partials")

    def __init__(self):
        # full page_size block -> (physical page, child node)
        self.children: Dict[Tuple[int, ...], Tuple[int, "_TrieNode"]] = {}
        # partial tail block (< page_size tokens) -> physical page
        self.partials: Dict[Tuple[int, ...], int] = {}


class PrefixTrie:
    """Shared-prefix page cache: a trie over page-aligned token
    blocks, content-addressed (dict hashing of the block tuple chains
    the parent path, so equal pages are equal prompt prefixes — no
    collision risk, vLLM-style block hashing with exact keys). A node
    maps one full `page_size` block to the physical page holding its
    K/V; `partials` additionally cache a prompt's sub-page tail so the
    Kth IDENTICAL prompt skips prefill entirely. The trie holds one
    pool ref per registered page; pages it holds alone (ref==1) are
    reclaimable cache, evicted LRU when the pool runs dry."""

    def __init__(self, page_size: int):
        self.page_size = int(page_size)
        self.root = _TrieNode()
        self._tick = 0
        self._last_used: Dict[int, int] = {}
        # page -> (owning node, "child"|"partial", key) for removal
        self._where: Dict[int, Tuple[_TrieNode, str, tuple]] = {}

    def __len__(self) -> int:
        return len(self._where)

    def _touch(self, page: int) -> None:
        self._tick += 1
        self._last_used[page] = self._tick

    def match(self, prompt: Sequence[int]
              ) -> Tuple[List[int], int]:
        """Walk the prompt's block chain: returns (pages, covered) —
        the physical pages holding its longest cached prefix and how
        many tokens they cover. A partial (sub-page) entry only
        matches when it covers the prompt's ENTIRE tail, so coverage
        is always page-aligned or total."""
        ps = self.page_size
        node, pages, i = self.root, [], 0
        n = len(prompt)
        while i + ps <= n:
            ent = node.children.get(tuple(prompt[i:i + ps]))
            if ent is None:
                break
            page, node = ent
            pages.append(page)
            self._touch(page)
            i += ps
        if 0 < n - i < ps:
            page = node.partials.get(tuple(prompt[i:]))
            if page is not None:
                pages.append(page)
                self._touch(page)
                return pages, n
        return pages, i

    def register(self, prompt: Sequence[int],
                 table: Sequence[Optional[int]],
                 pool: PagePool) -> List[int]:
        """Insert the prompt's freshly computed pages (ring `table`
        entries — during prefill block b lives at table[b]) into the
        trie, one pool retain per inserted page. Blocks already cached
        (this slot's own trie hits, or a concurrent twin that
        registered first) are left untouched. Returns the pages THIS
        call inserted — the slot keeps them for poison purge."""
        ps = self.page_size
        node, i, b = self.root, 0, 0
        inserted: List[int] = []
        n = len(prompt)
        while i + ps <= n:
            blk = tuple(prompt[i:i + ps])
            ent = node.children.get(blk)
            if ent is None:
                page = table[b]
                ent = (page, _TrieNode())
                node.children[blk] = ent
                pool.retain(page)
                self._where[page] = (node, "child", blk)
                self._touch(page)
                inserted.append(page)
            node = ent[1]
            i += ps
            b += 1
        tail = tuple(prompt[i:])
        if tail and tail not in node.partials:
            page = table[b]
            node.partials[tail] = page
            pool.retain(page)
            self._where[page] = (node, "partial", tail)
            self._touch(page)
            inserted.append(page)
        return inserted

    def _drop(self, page: int, pool: PagePool,
              quarantine: bool) -> None:
        loc = self._where.pop(page, None)
        self._last_used.pop(page, None)
        if loc is None:
            return
        node, kind, key = loc
        if kind == "partial":
            node.partials.pop(key, None)
            (pool.quarantine if quarantine else pool.release)(page)
            return
        ent = node.children.pop(key, None)
        (pool.quarantine if quarantine else pool.release)(page)
        if ent is not None:
            # removing a middle block strands its subtree (a child
            # chain is only reachable through its parent) — release
            # every descendant registration too, or their refs leak
            self._drop_subtree(ent[1], pool, quarantine)

    def _drop_subtree(self, node: _TrieNode, pool: PagePool,
                      quarantine: bool) -> None:
        for key, page in list(node.partials.items()):
            node.partials.pop(key, None)
            self._where.pop(page, None)
            self._last_used.pop(page, None)
            (pool.quarantine if quarantine else pool.release)(page)
        for key, (page, child) in list(node.children.items()):
            node.children.pop(key, None)
            self._where.pop(page, None)
            self._last_used.pop(page, None)
            (pool.quarantine if quarantine else pool.release)(page)
            self._drop_subtree(child, pool, quarantine)

    def purge(self, pages: Sequence[int], pool: PagePool) -> None:
        """Poison purge: a quarantined slot's registrations must never
        be served to a later prefix hit — remove them (and any chains
        through them), quarantining pages the trie held alone."""
        for p in pages:
            self._drop(p, pool, quarantine=True)

    def evict_lru(self, pool: PagePool) -> bool:
        """Reclaim ONE least-recently-used trie-only page (ref==1 —
        no slot maps it) whose entry is a leaf (evicting a middle
        block would strand the cached chain below it). Returns True if
        a page went back to the free list."""
        best, best_tick = None, None
        for page, loc in self._where.items():
            if pool.ref[page] != 1:
                continue
            node, kind, key = loc
            if kind == "child":
                child = node.children[key][1]
                if child.children or child.partials:
                    continue
            tick = self._last_used.get(page, 0)
            if best_tick is None or tick < best_tick:
                best, best_tick = page, tick
        if best is None:
            return False
        self._drop(best, pool, quarantine=False)
        return True

    def clear(self, pool: PagePool) -> None:
        """Release every registration (disable/reset path)."""
        self._drop_subtree(self.root, pool, quarantine=False)


class DecodeEngine:
    """Slot-based continuous-batching server for one decoder model.

    `submit()` is non-blocking admission; a background loop (or
    explicit `step_once()` calls — the deterministic-test drive)
    advances every resident stream one token per compiled dispatch.
    One DecodeProgram = one decode compile serves arbitrary join/leave
    traffic; `stats()["trace_counts"]` is the pin.

    `watchdog_timeout_s=` supervises the loop thread: heartbeats feed
    a StepWatchdog whose escalation tears the engine down and restarts
    it (bounded by `max_engine_restarts`), recovering every live
    request via replay."""

    def __init__(self, model=None, max_slots: int = 8,
                 page_size: int = 16, queue_limit: Optional[int] = None,
                 admission=None, model_name: str = "decoder",
                 program=None, max_prefills_per_step: int = 1,
                 watchdog_timeout_s: Optional[float] = None,
                 max_engine_restarts: int = 3,
                 poison_strike_limit: int = 2,
                 n_pages: Optional[int] = None,
                 prefix_cache: bool = True,
                 journal=None, tracer=None,
                 flight_dir: Optional[str] = None,
                 flight_capacity: int = 512):
        from deeplearning4j_tpu.engine.decode_program import (
            DecodeProgram,
        )

        if program is None:
            if model is None:
                raise ValueError("DecodeEngine needs a model or a "
                                 "DecodeProgram")
            program = DecodeProgram(model, max_slots=max_slots,
                                    page_size=page_size,
                                    n_pages=n_pages)
        self.program = program
        self.max_slots = program.max_slots
        self.prefix_cache = bool(prefix_cache)
        self.admission = admission
        self.model_name = model_name
        self.queue_limit = (int(queue_limit) if queue_limit is not None
                            else 2 * self.max_slots)
        # a join costs one prefill dispatch between decode steps; cap
        # how many joins one step pays for so an admission burst can't
        # stall resident streams (the prefill-vs-decode phase split)
        self.max_prefills_per_step = max(1, int(max_prefills_per_step))
        self.watchdog_timeout_s = watchdog_timeout_s
        self.max_engine_restarts = int(max_engine_restarts)
        self.poison_strike_limit = int(poison_strike_limit)
        self.kv = program.init_kv()
        s = self.max_slots
        self._tokens = np.zeros(s, np.int32)
        self._positions = np.zeros(s, np.int32)
        self._active = np.zeros(s, bool)
        self._quarantined = np.zeros(s, bool)
        self._slot_req: List[Optional[GenerationHandle]] = [None] * s
        self._slot_replay: List[Optional[deque]] = [None] * s
        # ---- paged KV virtual memory (host side) ----
        # per-slot ring page table: logical page (pos // page_size)
        # lives at ring index (pos // page_size) % pages_per_slot, so
        # positions wrap through the table past max_ctx
        p = program.pages_per_slot
        self._pool = PagePool(program.n_pages)
        self._trie: Optional[PrefixTrie] = (
            PrefixTrie(program.page_size) if self.prefix_cache
            else None)
        self._table: List[List[Optional[int]]] = [[None] * p
                                                  for _ in range(s)]
        # -1 = not filling; else the next prompt position to chunk
        self._fill_next = np.full(s, -1, np.int64)
        # True while the slot's NEXT decode dispatch is the uniform
        # first-token step: position len(prompt)-1, write suppressed
        # (the prompt's cells are already paged in), emitting the
        # first generated token — shared and unshared twins run the
        # exact same step, which is what makes prefix sharing bitwise
        self._first_step = np.zeros(s, bool)
        # pages each slot registered into the trie (poison purge set)
        self._trie_owned: List[List[int]] = [[] for _ in range(s)]
        # pending entries: (handle, replay_tokens or None)
        self._pending: deque = deque()
        # requests popped from pending but not yet resident (prefill
        # in flight) — still counted against capacity, so admission
        # can't oversubscribe through the placement window
        self._placing = 0
        self._cond = threading.Condition()
        self._step_lock = threading.Lock()
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._watchdog = None
        # restart epoch: a loop thread abandoned by a watchdog restart
        # sees the bumped epoch when it wakes and exits without
        # touching the rebuilt state
        self._epoch = 0
        self._zombies: List[threading.Thread] = []
        self._t0 = time.monotonic()
        self._tokens_emitted = 0
        self._steps = 0
        self._prefills = 0
        self._prefill_chunks = 0
        self._prefix_hits = 0          # joins that mapped >=1 page
        self._prefix_page_hits = 0     # pages mapped from the trie
        self._ctx_wraps = 0            # page recycles past the window
        self._cow_copies = 0
        self._evictions = 0
        self._completed = 0
        self._quarantines = 0
        self._replays = 0
        self._deadline_expired = 0
        self._cancelled = 0
        self._restarts = 0
        # ---- durable serving (serving/journal.py) ----
        # idempotency keys: live AND recently-done handles by request
        # id (bounded retention), so a client retry after an ambiguous
        # disconnect joins the original stream instead of
        # double-executing; the journal (when attached) is the
        # disk-backed leg of the same contract
        self._journal = None
        self._handles_by_id: Dict[str, GenerationHandle] = {}
        self._done_ids: deque = deque()
        self._done_retention = 1024
        self._recovered = 0
        # journal events collected under the step lock, written after
        # it (file I/O is never a step-lock holder)
        self._jevents: List[tuple] = []
        # ---- tracing + latency attribution + flight recorder ----
        # `tracer=None` is the zero-cost default: every span/record
        # site is gated on it. Latency events (queue wait, TTFT, ITL,
        # prefill chunks, span ends) ride the _jevents pattern: cheap
        # tuples collected under the step lock, metrics/spans emitted
        # after it.
        self.tracer = tracer
        self._lat: List[tuple] = []
        self._ttft_ring: deque = deque(maxlen=512)
        self._itl_ring: deque = deque(maxlen=512)
        self._queue_ring: deque = deque(maxlen=512)
        self._flight = FlightRecorder(capacity=flight_capacity,
                                      dump_dir=flight_dir,
                                      name=model_name)
        # dump reason flagged under the step lock, dumped after it
        # (the dump does file I/O — never a step-lock holder)
        self._flight_dump_reason: Optional[str] = None
        _LIVE_ENGINES.add(self)
        if journal is not None:
            self.attach_journal(journal)

    # -------------------------------------------------------- lifecycle
    def start(self) -> "DecodeEngine":
        with self._cond:
            if self._running:
                return self
            self._running = True
            epoch = self._epoch
        if self.watchdog_timeout_s and self._watchdog is None:
            from deeplearning4j_tpu.resilience.supervisor import (
                StepWatchdog,
            )

            self._watchdog = StepWatchdog(
                timeout_s=self.watchdog_timeout_s,
                on_hang=self._on_hang)
            self._watchdog.start()
        self._spawn_loop(epoch)
        return self

    def _spawn_loop(self, epoch: int) -> None:
        name = ("DecodeEngine-loop" if not self._restarts
                else f"DecodeEngine-loop-r{self._restarts}")
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=name, args=(epoch,))
        self._thread.start()

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def ensure_started(self) -> "DecodeEngine":
        if not self.running:
            return self.start()
        return self

    def stop(self) -> None:
        with self._cond:
            self._running = False
            pending = list(self._pending)
            self._pending.clear()
            self._cond.notify_all()
        if self._watchdog is not None:
            self._watchdog.stop()
            self._watchdog = None
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        for z in self._zombies:
            z.join(timeout=2.0)
        self._zombies = []
        # fail whatever never reached a slot; resident streams keep
        # their partial output readable (tokens_so_far) but never
        # finish — mark them failed too so result() callers unblock
        err = ShutdownError("decode engine stopped")
        for handle, _ in pending:
            handle._finish(None, error=err)
            self._end_span(handle, "shutdown")
        for s in range(self.max_slots):
            if self._active[s] and self._slot_req[s] is not None:
                handle = self._slot_req[s]
                handle._finish(None, error=err)
                self._end_span(handle, "shutdown")
                self._free_slot(s)

    def _loop(self, epoch: int) -> None:
        while True:
            with self._cond:
                if not self._running or epoch != self._epoch:
                    return
            try:
                # `decode.hang` chaos site: a `delay` spec wedges the
                # loop HERE — outside the step lock, before the beat —
                # so the watchdog sees heartbeats go stale exactly as
                # it would for a dispatch stuck in the runtime
                _fire("decode.hang")
            except FaultInjectedError:
                pass
            with self._cond:
                # a watchdog restart may have replaced this thread
                # while it was wedged: leave without touching state
                if not self._running or epoch != self._epoch:
                    return
            if self._watchdog is not None:
                self._watchdog.beat("decode", self._steps)
            worked = self.step_once()
            if not worked:
                with self._cond:
                    if self._running and epoch == self._epoch:
                        self._cond.wait(timeout=0.02)

    # --------------------------------------------------- hang recovery
    def _on_hang(self, phase: str, age_s: float) -> None:
        """StepWatchdog escalation (runs on the watchdog monitor
        thread): the loop thread went silent — tear the engine down
        and restart it with every live request recovered via replay,
        up to `max_engine_restarts`."""
        self._restart_engine(f"decode loop hung in phase {phase!r} "
                             f"({age_s:.1f}s without a heartbeat)")

    def _restart_engine(self, reason: str) -> None:
        with self._cond:
            if not self._running:
                return
            self._epoch += 1        # abandoned thread exits on wake
            epoch = self._epoch
            exhausted = self._restarts >= self.max_engine_restarts
            if not exhausted:
                self._restarts += 1
            if self._thread is not None:
                self._zombies.append(self._thread)
                self._thread = None
            if exhausted:
                self._running = False
            pending = list(self._pending)
            self._pending.clear()
        err = (RestartsExhaustedError(
            f"decode engine gave up after {self.max_engine_restarts} "
            f"restarts: {reason}") if exhausted else None)
        # rebuild slot state under the step lock. A loop thread wedged
        # INSIDE a dispatch would still hold it — bounded wait, then
        # abandon the lock object with the thread (the stale thread
        # releases a lock nothing else uses, and its epoch check stops
        # it before it can touch the rebuilt state).
        got = self._step_lock.acquire(timeout=2.0)
        try:
            live: List[Tuple[GenerationHandle, List[int]]] = []
            for s in range(self.max_slots):
                if self._active[s] and self._slot_req[s] is not None:
                    h = self._slot_req[s]
                    live.append((h, h.tokens_so_far()))
            self.kv = self.program.init_kv()
            self._tokens[:] = 0
            self._positions[:] = 0
            self._active[:] = False
            self._quarantined[:] = False   # fresh KV clears quarantine
            self._slot_req = [None] * self.max_slots
            self._slot_replay = [None] * self.max_slots
            self._placing = 0
            # fresh pool => fresh virtual memory: page table, trie,
            # refcounts, and page quarantine all restart from zero
            p = self.program.pages_per_slot
            self._pool = PagePool(self.program.n_pages)
            self._trie = (PrefixTrie(self.program.page_size)
                          if self.prefix_cache else None)
            self._table = [[None] * p for _ in range(self.max_slots)]
            self._fill_next[:] = -1
            self._first_step[:] = False
            self._trie_owned = [[] for _ in range(self.max_slots)]
        finally:
            if got:
                self._step_lock.release()
            else:
                self._step_lock = threading.Lock()
        self._flight.note("restart", self._steps,
                          reason=str(reason)[:120],
                          exhausted=exhausted)
        self._flight.dump("restart")
        if err is not None:
            for handle, _ in live:
                handle._finish(None, error=err)
                self._end_span(handle, "restarts_exhausted")
            for handle, _ in pending:
                handle._finish(None, error=err)
                self._end_span(handle, "restarts_exhausted")
            return
        with self._cond:
            self._pending.extend(pending)
            for handle, recorded in reversed(live):
                handle.replays += 1
                self._pending.appendleft((handle, recorded or None))
            self._cond.notify_all()
        _obs.count("dl4j_decode_engine_restarts_total")
        if self._watchdog is not None:
            self._watchdog.beat("restart", self._steps)
        self._spawn_loop(epoch)

    # -------------------------------------------------------- admission
    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               eos_id: Optional[int] = None,
               tenant: Optional[str] = None,
               deadline_s: Optional[float] = None,
               resume_tokens: Optional[Sequence[int]] = None,
               request_id: Optional[str] = None,
               trace: Optional[str] = None
               ) -> GenerationHandle:
        """Admit one generation request (non-blocking). Raises
        QuotaExceededError (HTTP 429 + Retry-After) on tenant quota /
        priority shed (AdmissionController) or on slot exhaustion —
        every slot resident and the wait queue full.

        `resume_tokens` re-enters a stream that already emitted tokens
        elsewhere (cross-replica migration / reconnect): the engine
        re-prefills the ORIGINAL prompt and force-replays the recorded
        tokens through the shared loop, so the continuation is
        byte-identical to an uninterrupted run. `max_new_tokens` is
        the request's ORIGINAL budget (resume tokens count toward it).

        `deadline_s` bounds the request's wall-clock life from this
        submit: past it, the slot is freed and the handle finishes
        with its partial tokens and finish_reason "deadline".

        `request_id` is the idempotency key: re-submitting an id the
        engine already knows (live, recently done, or recovered from
        the journal) returns the ORIGINAL handle — nothing is
        double-journaled or double-executed. With a journal attached,
        the admitted record is written BEFORE the request becomes
        visible to the step loop (write-ahead).

        `trace` is the request's cross-process trace id (rode the wire
        meta next to request_id). It is journaled with the admitted
        record so a cold-restart recovery leg carries the original id;
        with a tracer attached and no id supplied, the engine mints
        one."""
        prompt = [int(t) for t in np.asarray(prompt, np.int64).ravel()]
        if not prompt:
            raise ValueError("prompt must carry at least one token")
        max_new_tokens = int(max_new_tokens)
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        # the prompt must fit the attention window; the GENERATION may
        # run past it — logical positions wrap through the page table
        # (ring wrap), attending over the last `window` positions
        if len(prompt) > self.program.window:
            raise ValueError(
                f"prompt ({len(prompt)}) exceeds the attention "
                f"window {self.program.window}")
        resume = [int(t) for t in resume_tokens or []]
        if len(resume) > max_new_tokens:
            raise ValueError(
                f"resume_tokens ({len(resume)}) exceeds "
                f"max_new_tokens ({max_new_tokens})")
        rid = str(request_id) if request_id else uuid.uuid4().hex
        # idempotency: join the id's existing stream — live, finished,
        # or recovered — EXCEPT one that failed (engine shutdown): the
        # retry after such a failure (the resume-on-disconnect leg)
        # must get a fresh life, not the dead handle back
        with self._cond:
            existing = self._handles_by_id.get(rid)
        if existing is not None and not existing.failed:
            return existing
        tid = str(trace) if trace else None
        if tid is None and self.tracer is not None:
            from deeplearning4j_tpu.observability.tracing import (
                new_trace_id,
            )

            tid = new_trace_id()
        handle = GenerationHandle(prompt, max_new_tokens, eos_id,
                                  deadline_s=deadline_s,
                                  request_id=rid, tenant=tenant,
                                  trace=tid)
        if self.tracer is not None:
            # the leg's root span: opened on the submitting thread (an
            # enclosing server span parents it implicitly), closed by
            # the post-step-lock drain when the stream finishes
            handle._span = self.tracer.begin(
                "generate", cat="decode",
                args={"trace": tid, "request_id": rid,
                      "tenant": tenant or "default",
                      "model": self.model_name,
                      "resumed": bool(resume)})
        if resume:
            handle._preload(resume)
            handle.replays += 1
            # the earlier life may already have finished the stream
            finished = None
            if eos_id is not None and resume[-1] == eos_id:
                finished = "eos"
            elif len(resume) >= max_new_tokens:
                finished = "length"
            if finished is not None:
                handle._finish(finished)
                self._end_span(handle, finished)
                with self._cond:
                    cur = self._handles_by_id.get(rid)
                    if cur is None or cur.failed:
                        self._handles_by_id[rid] = handle
                self._journal_safe(
                    lambda: self._journal.append_admitted(
                        rid, prompt, max_new_tokens, eos_id=eos_id,
                        tenant=tenant, deadline_s=deadline_s,
                        trace=handle.trace))
                self._journal_safe(
                    lambda: self._journal.record_progress(rid, resume))
                self._journal_safe(
                    lambda: self._journal.append_done(rid, finished))
                self._note_done_id(rid)
                return handle
        capacity = self.max_slots + self.queue_limit
        depth = self._in_flight()
        if self.admission is not None:
            self.admission.admit(tenant, self.model_name, depth,
                                 capacity)
        # WRITE-AHEAD: the admitted record (and any resume progress)
        # lands on disk before the step loop can see the request; a
        # shed below appends done("shed") so the journal stays clean
        self._journal_safe(lambda: self._journal.append_admitted(
            rid, prompt, max_new_tokens, eos_id=eos_id, tenant=tenant,
            deadline_s=deadline_s, trace=handle.trace))
        if resume:
            self._journal_safe(
                lambda: self._journal.record_progress(rid, resume))
        with self._cond:
            racer = self._handles_by_id.get(rid)
            if racer is not None and not racer.failed:
                return racer
            if (int(self._active.sum()) + len(self._pending)
                    + self._placing) >= capacity:
                shed = True
            else:
                shed = False
                self._handles_by_id[rid] = handle
                self._pending.append((handle, resume or None))
                self._cond.notify_all()
        if shed:
            self._journal_safe(
                lambda: self._journal.append_done(rid, "shed"))
            self._end_span(handle, "shed")
            raise QuotaExceededError(
                f"decode slots exhausted ({self.max_slots} resident, "
                f"{self.queue_limit} waiting)", tenant=tenant or "",
                retry_after_s=0.5)
        return handle

    def generate(self, prompt: Sequence[int], max_new_tokens: int,
                 eos_id: Optional[int] = None,
                 tenant: Optional[str] = None,
                 timeout_s: float = 60.0,
                 deadline_s: Optional[float] = None,
                 resume_tokens: Optional[Sequence[int]] = None
                 ) -> GenerationHandle:
        """submit + wait: returns the FINISHED handle (tokens via
        `.tokens_so_far()` / `.result()`)."""
        handle = self.submit(prompt, max_new_tokens, eos_id=eos_id,
                             tenant=tenant, deadline_s=deadline_s,
                             resume_tokens=resume_tokens)
        handle.result(timeout_s=timeout_s)
        return handle

    def _in_flight(self) -> int:
        with self._cond:
            return (int(self._active.sum()) + len(self._pending)
                    + self._placing)

    # ------------------------------------------ durability (journal)
    def attach_journal(self, journal,
                       recover: bool = True) -> "DecodeEngine":
        """Arm the write-ahead journal. With `recover=True` (the
        default), every request the journal holds LIVE — a previous
        process's crash — is re-submitted as a resume_tokens
        continuation through the bitwise replay path, under its
        original request id (so a client's idempotent re-submit joins
        the recovered stream). A live request a FRESH engine cannot
        carry (stale journal: prompt past this engine's window, or
        recovery overflowing capacity) is marked done("unrecoverable")
        instead of wedging recovery forever."""
        self._journal = journal
        if not recover:
            return self
        recovered = 0
        live = journal.live()
        for rid in sorted(live):
            req = live[rid]
            try:
                # the journaled trace id rides into the recovery leg,
                # so the cold-restart continuation merges into the
                # request's original timeline
                self.submit(req["prompt"], req["max_new_tokens"],
                            eos_id=req.get("eos_id"),
                            tenant=req.get("tenant"),
                            deadline_s=req.get("deadline_s"),
                            resume_tokens=req.get("tokens") or None,
                            request_id=rid,
                            trace=req.get("trace"))
                recovered += 1
            except (ValueError, QuotaExceededError):
                journal.append_done(rid, "unrecoverable")
        self._recovered += recovered
        if recovered:
            _obs.count("dl4j_journal_recovered_requests_total",
                       n=recovered)
        return self

    def _journal_safe(self, fn) -> None:
        """Run one journal operation, swallowing its failure: a sick
        journal degrades durability, it never takes the data plane
        down (the same guarded-telemetry discipline as _obs)."""
        if self._journal is None:
            return
        try:
            fn()
        except Exception:  # noqa — durability degrades, serving continues; journal failures must not poison the data plane
            pass

    def _end_span(self, handle: GenerationHandle,
                  reason: str) -> None:
        """Close a handle's leg-root span (no-op without a tracer).
        Only ever called OUTSIDE the step lock — span completion takes
        the tracer lock and may flush."""
        sp = handle._span
        if sp is not None:
            sp.end(finish_reason=reason)

    def _emit_latency(self, lat: List[tuple]) -> None:
        """Drain one step's latency events OUTSIDE the step lock:
        TTFT/ITL/queue-wait histogram observations (labeled by tenant
        class) plus — with a tracer attached — the matching span
        records (`Tracer.record` over the pre-measured intervals; no
        span objects ever exist on the locked path)."""
        tracer = self.tracer
        for kind, handle, a, b in lat:
            tenant = handle.tenant or "default"
            targs = None
            if tracer is not None:
                targs = {"trace": handle.trace,
                         "request_id": handle.request_id}
            if kind == "queue_wait":
                self._queue_ring.append(b - a)
                _obs.observe("dl4j_decode_queue_wait_seconds", b - a,
                             labels={"tenant": tenant})
                if tracer is not None:
                    tracer.record("admission_wait", a, b, cat="decode",
                                  parent=handle._span, args=targs)
            elif kind == "ttft":
                dt = b - handle.t_submit
                self._ttft_ring.append(dt)
                _obs.observe("dl4j_decode_ttft_seconds", dt,
                             labels={"tenant": tenant})
                if tracer is not None:
                    targs["first"] = True
                    tracer.record("token", a, b, cat="decode",
                                  parent=handle._span, args=targs)
            elif kind == "itl":
                self._itl_ring.append(b - a)
                _obs.observe("dl4j_decode_itl_seconds", b - a,
                             labels={"tenant": tenant})
                if tracer is not None:
                    tracer.record("token", a, b, cat="decode",
                                  parent=handle._span, args=targs)
            elif kind == "chunk":
                if tracer is not None:
                    tracer.record("prefill_chunk", a, b, cat="decode",
                                  parent=handle._span, args=targs)
            elif kind == "end":
                self._end_span(handle, a)

    def _note_done_id(self, rid: Optional[str]) -> None:
        """Bounded retention for finished idempotency keys: keep the
        last `_done_retention` done handles findable (a retry joins
        them) without growing the map forever."""
        if not rid:
            return
        with self._cond:
            self._done_ids.append(rid)
            while len(self._done_ids) > self._done_retention:
                self._handles_by_id.pop(self._done_ids.popleft(), None)

    def _write_journal(self, events: List[tuple]) -> None:
        """Drain one step's journal events OUTSIDE the step lock:
        progress deltas first (the journal computes the delta from the
        handle's full token list — absolute positions keep replays
        idempotent), then terminal records, then a group-commit
        checkpoint under the journal's fsync policy. Crash-shaped
        finishes (engine stop, restart exhaustion, evictions) are
        never in `events` — those streams must stay live on disk."""
        j = self._journal
        if j is None or not events:
            return
        progressed = set()
        for ev in events:
            kind, handle = ev[0], ev[1]
            rid = handle.request_id
            if rid is None:
                continue
            if kind == "progress":
                if rid in progressed:
                    continue
                progressed.add(rid)
                self._journal_safe(lambda h=handle: j.record_progress(
                    h.request_id, h.tokens_so_far()))
            else:
                # the final tokens land before the done marker
                self._journal_safe(lambda h=handle: j.record_progress(
                    h.request_id, h.tokens_so_far()))
                self._journal_safe(lambda h=handle, r=ev[2]:
                                   j.append_done(h.request_id, r))
                self._note_done_id(rid)
        self._journal_safe(lambda: j.flush(force=False))

    # ------------------------------------------------------------- step
    def step_once(self) -> bool:
        """One engine iteration: deadline/cancel sweep, chaos check,
        admit/advance chunked prefills to free healthy slots (bounded
        chunk dispatches), one shared decode dispatch over the
        translated page table, per-slot finite-verdict quarantine,
        harvest. Returns False when there was nothing to do. Public so
        tests drive churn deterministically without the loop thread.
        Telemetry (fault points aside, counters, gauges) fires OUTSIDE
        the step lock — emission is never a blocking op under a
        lock."""
        try:
            _fire("serving.slot_evict")
            evict = False
        except FaultInjectedError:
            evict = True
        prefill_s: List[float] = []
        quar_before = self._quarantines
        replays_before = self._replays
        chunks_before = self._prefill_chunks
        hits_before = self._prefix_page_hits
        wraps_before = self._ctx_wraps
        with self._step_lock:
            n_deadline, n_cancel = self._sweep_deadlines()
            evicted = self._evict_lowest_active() if evict else 0
            admitted, emitted = self._admit_pending(prefill_s)
            # slots still mid-prefill sit out the decode dispatch
            # (their rows compute scratch-backed garbage the harvest
            # ignores); everyone else needs a writable cell for the
            # current position — alloc / ring wrap / copy-on-write
            self._prepare_write_cells()
            decoding = self._active & (self._fill_next < 0)
            stepped = bool(decoding.any())
            if stepped:
                cp, co, wp, wo = self._step_tables(decoding)
                self.kv, nxt, ok = self.program.step(
                    self.kv, self._tokens, self._positions, cp, co,
                    wp, wo)
                nxt_host = np.asarray(nxt)
                ok_host = np.asarray(ok)
                try:
                    # `decode.nonfinite` chaos site: force a poison
                    # verdict on the lowest decoding slot — the NaN
                    # drill without corrupting the shared weights. A
                    # hit must mean "this decode step" (the verdict it
                    # corrupts), so the fire cannot move outside the
                    # step lock; the injector is a flag check, not I/O.
                    # analyze: allow=thr-blocking-under-lock — chaos hit must align with the decode step it poisons
                    _fire("decode.nonfinite")
                except FaultInjectedError:
                    victims = np.flatnonzero(decoding)
                    if victims.size:
                        ok_host = ok_host.copy()
                        ok_host[victims[0]] = False
                self._steps += 1
                self._quarantine_poisoned(ok_host, decoding)
                emitted += self._harvest(nxt_host, decoding)
            jevents, self._jevents = self._jevents, []
            lat, self._lat = self._lat, []
            dump_reason, self._flight_dump_reason = (
                self._flight_dump_reason, None)
        chunks = self._prefill_chunks - chunks_before
        if chunks:
            _obs.count("dl4j_decode_prefill_chunks_total", n=chunks)
        hits = self._prefix_page_hits - hits_before
        if hits:
            _obs.count("dl4j_decode_prefix_hits_total", n=hits)
        wraps = self._ctx_wraps - wraps_before
        if wraps:
            _obs.count("dl4j_decode_ctx_wraps_total", n=wraps)
        if evicted:
            _obs.count("dl4j_decode_slot_evictions_total", n=evicted)
        if n_deadline:
            _obs.count("dl4j_decode_deadline_expired_total",
                       n=n_deadline)
        quar = self._quarantines - quar_before
        if quar:
            _obs.count("dl4j_decode_slot_quarantines_total", n=quar)
        replays = self._replays - replays_before
        if replays:
            _obs.count("dl4j_decode_replays_total", n=replays)
        for dt in prefill_s:
            _obs.observe("dl4j_decode_prefill_seconds", dt)
        if emitted:
            _obs.count("dl4j_decode_tokens_total", n=emitted)
        self._emit_latency(lat)
        if dump_reason is not None:
            self._flight.dump(dump_reason)
        self._publish_gauges()
        self._write_journal(jevents)
        return bool(stepped or admitted or chunks or evicted
                    or n_deadline or n_cancel)

    def _sweep_deadlines(self) -> Tuple[int, int]:
        """Finish expired/cancelled streams with their PARTIAL tokens
        (explicit finish_reason) and free their slots. Runs at the top
        of every step — a deadline costs at most one step of slack."""
        now = time.monotonic()

        def _verdict(handle: GenerationHandle) -> Optional[str]:
            if handle._cancel_requested:
                return "cancelled"
            if handle._deadline is not None and now >= handle._deadline:
                return "deadline"
            return None

        n_deadline = n_cancel = 0
        with self._cond:
            if self._pending:
                kept: deque = deque()
                for handle, replay in self._pending:
                    reason = _verdict(handle)
                    if reason is None:
                        kept.append((handle, replay))
                        continue
                    handle._finish(reason)
                    self._jevents.append(("done", handle, reason))
                    if self.tracer is not None:
                        self._lat.append(("end", handle, reason, None))
                    n_deadline += reason == "deadline"
                    n_cancel += reason == "cancelled"
                self._pending = kept
        for s in range(self.max_slots):
            if not self._active[s] or self._slot_req[s] is None:
                continue
            reason = _verdict(self._slot_req[s])
            if reason is None:
                continue
            handle = self._slot_req[s]
            handle._finish(reason)
            self._jevents.append(("done", handle, reason))
            if self.tracer is not None:
                self._lat.append(("end", handle, reason, None))
            self._flight.note("leave", self._steps, slot=s,
                              reason=reason)
            self._free_slot(s)
            n_deadline += reason == "deadline"
            n_cancel += reason == "cancelled"
        self._deadline_expired += n_deadline
        self._cancelled += n_cancel
        return n_deadline, n_cancel

    def _admit_pending(self, prefill_s: List[float]):
        """Spend this step's chunk budget: advance in-flight chunked
        prefills first (oldest slot first — a resident prompt finishes
        before a new one starts competing), then place waiting
        requests onto free healthy slots. A placement whose prompt is
        FULLY covered by the prefix trie costs zero chunk dispatches —
        the Kth same-prompt request skips prefill entirely (bounded
        only by free slots)."""
        admitted = False
        emitted = 0
        budget = self.max_prefills_per_step
        for s in range(self.max_slots):
            if budget <= 0:
                break
            if self._active[s] and self._fill_next[s] >= 0:
                budget -= self._advance_fill(s, prefill_s)
        while budget > 0:
            free = [s for s in range(self.max_slots)
                    if not self._active[s] and not self._quarantined[s]]
            if not free:
                break
            with self._cond:
                if not self._pending:
                    break
                handle, replay = self._pending.popleft()
                self._placing += 1
            try:
                budget -= self._place(handle, replay, free[0],
                                      prefill_s)
            finally:
                with self._cond:
                    self._placing -= 1
            admitted = True
        return admitted, emitted

    def _place(self, handle: GenerationHandle,
               replay: Optional[List[int]], slot: int,
               prefill_s: List[float]) -> int:
        """Make `handle` resident on `slot`: map its longest cached
        prefix from the trie (refcounted read-only pages — the
        shared-prefix capacity win), then start chunked prefill of
        whatever the trie did not cover. `replay`
        (eviction/quarantine/migration recovery) carries the
        already-emitted tokens: the uniform first-token step
        regenerates the first one (same programs, same cells —
        bitwise the same token) and the recorded stream is force-fed
        through the decode loop instead of re-emitted, so the output
        is unaffected by the recovery. Returns the chunk dispatches
        spent (0 on a full prefix hit)."""
        self._slot_req[slot] = handle
        self._active[slot] = True
        self._slot_replay[slot] = deque(replay) if replay else None
        if handle.t_placed is None:
            # first placement only: a re-placement after eviction is
            # recovery churn, not admission wait
            handle.t_placed = time.perf_counter()
            self._lat.append(("queue_wait", handle, handle.t_submit,
                              handle.t_placed))
        self._flight.note("join", self._steps, slot=slot,
                          req=handle.request_id, replay=bool(replay))
        if replay:
            # forced replay: the recorded token stream IS the truth
            # (greedy decode would regenerate it; forcing makes the
            # recovery independent of it)
            self._replays += 1
        covered = 0
        if self._trie is not None:
            pages, covered = self._trie.match(handle.prompt)
            for i, p in enumerate(pages):
                self._pool.retain(p)
                self._table[slot][i] = p
            if pages:
                self._prefix_hits += 1
                self._prefix_page_hits += len(pages)
        if covered >= len(handle.prompt):
            self._fill_next[slot] = -1
            self._fill_done(slot)
            return 0
        self._fill_next[slot] = covered
        return self._advance_fill(slot, prefill_s)

    def _advance_fill(self, slot: int, prefill_s: List[float]) -> int:
        """Dispatch ONE prompt chunk for a filling slot (page_size
        tokens into one freshly allocated page). Returns the chunk
        dispatches spent; 0 means the pool is exhausted beyond
        recovery this step — the fill resumes next step."""
        handle = self._slot_req[slot]
        prompt = handle.prompt
        ps = self.program.page_size
        start = int(self._fill_next[slot])
        page = self._alloc_page(slot)
        if page is None:
            return 0
        t0 = time.perf_counter()
        ring = (start // ps) % self.program.pages_per_slot
        self._table[slot][ring] = page
        cp, co = self.program.window_cells(self._table[slot],
                                           start - 1)
        self.kv = self.program.prefill_chunk(
            self.kv, prompt[start:start + ps], start, cp, co, page)
        self._prefill_chunks += 1
        t1 = time.perf_counter()
        prefill_s.append(t1 - t0)
        if self.tracer is not None:
            self._lat.append(("chunk", handle, t0, t1))
        self._flight.note("chunk", self._steps, slot=slot, start=start)
        nxt = start + ps
        if nxt >= len(prompt):
            self._fill_next[slot] = -1
            self._fill_done(slot)
        else:
            self._fill_next[slot] = nxt
        return 1

    def _fill_done(self, slot: int) -> None:
        """The slot's prompt K/V is fully paged in (computed, shared,
        or both): register its freshly computed pages into the trie
        and arm the uniform first-token step — a decode dispatch at
        position len(prompt)-1 with its WRITE SUPPRESSED (the cell
        already holds the prefill's K/V), emitting the first generated
        token. Shared and unshared twins run this exact step over
        identical cell values, which is why prefix sharing is
        bitwise-safe."""
        handle = self._slot_req[slot]
        if self._trie is not None:
            self._trie_owned[slot] = self._trie.register(
                handle.prompt, self._table[slot], self._pool)
        self._prefills += 1
        self._positions[slot] = len(handle.prompt) - 1
        self._tokens[slot] = handle.prompt[-1]
        self._first_step[slot] = True

    # ------------------------------------------------ page allocation
    def _alloc_page(self, for_slot: int) -> Optional[int]:
        """Allocate one physical page for `for_slot`, reclaiming under
        pressure: first LRU-evict trie-only cached pages, then evict
        other resident requests (youngest slot first — they requeue
        with replay, byte-identity preserved). Returns None only when
        nothing more can be reclaimed this step."""
        page = self._pool.alloc()
        if page is not None:
            return page
        while self._trie is not None and self._trie.evict_lru(
                self._pool):
            page = self._pool.alloc()
            if page is not None:
                return page
        victims = [s for s in range(self.max_slots)
                   if self._active[s] and s != for_slot]
        for v in reversed(victims):
            self._evict_slot(v)
            while (self._pool.free_count == 0
                   and self._trie is not None
                   and self._trie.evict_lru(self._pool)):
                pass
            page = self._pool.alloc()
            if page is not None:
                return page
        return None

    def _prepare_write_cells(self) -> None:
        """Before the decode dispatch, every decoding slot (first-token
        steps excepted — their write is suppressed) needs exclusive
        ownership of the page holding its current position's cell:
        alloc fresh territory, recycle its own ring entry past the
        window (ctx wrap), or copy-on-write a page something else
        still references (a trie registration or a prefix twin). A
        slot the pool cannot serve even after reclaim is evicted —
        it requeues with replay, losing nothing."""
        ps = self.program.page_size
        c = self.program.window
        p = self.program.pages_per_slot
        for s in range(self.max_slots):
            if (not self._active[s] or self._fill_next[s] >= 0
                    or self._first_step[s]):
                continue
            pos = int(self._positions[s])
            ring = (pos // ps) % p
            page = self._table[s][ring]
            if pos >= c and pos % ps == 0:
                # the ring entry comes back around: this slot starts
                # recycling its own oldest page (sliding the window)
                self._ctx_wraps += 1
            if page is None:
                page = self._alloc_page(s)
                if page is None:
                    self._evict_slot(s)
                    continue
                self._table[s][ring] = page
            elif self._pool.ref[page] > 1:
                # copy-on-write divergence: someone else (trie entry /
                # prefix twin) still reads this page — fork it before
                # the first private write lands
                fresh = self._alloc_page(s)
                if fresh is None:
                    self._evict_slot(s)
                    continue
                self.kv = self.program.copy_page(self.kv, page, fresh)
                self._pool.release(page)
                self._table[s][ring] = fresh
                self._cow_copies += 1

    def _step_tables(self, decoding: np.ndarray):
        """Translate the page table into the decode dispatch's cell
        index arrays: [S, window] (page, offset) pairs in logical
        token order per slot, plus each slot's write cell
        (first-token steps and non-decoding rows write scratch)."""
        from deeplearning4j_tpu.engine.decode_program import (
            SCRATCH_PAGE,
        )

        s_n = self.max_slots
        c = self.program.window
        ps = self.program.page_size
        p = self.program.pages_per_slot
        cp = np.full((s_n, c), SCRATCH_PAGE, np.int32)
        co = np.zeros((s_n, c), np.int32)
        wp = np.full(s_n, SCRATCH_PAGE, np.int32)
        wo = np.zeros(s_n, np.int32)
        for s in np.flatnonzero(decoding):
            pos = int(self._positions[s])
            cp[s], co[s] = self.program.window_cells(self._table[s],
                                                     pos)
            if not self._first_step[s]:
                wp[s] = self._table[s][(pos // ps) % p]
                wo[s] = pos % ps
        return cp, co, wp, wo

    def _harvest(self, nxt_host: np.ndarray,
                 decoding: np.ndarray) -> int:
        emitted = 0
        # one clock read per step: every slot's token materialized in
        # the same dispatch, so they share a timestamp (TTFT/ITL marks
        # are tuples into _lat — emission happens after the step lock)
        now = time.perf_counter()
        for s in range(self.max_slots):
            if not decoding[s] or not self._active[s]:
                continue
            self._positions[s] += 1
            self._first_step[s] = False
            replay = self._slot_replay[s]
            if replay is not None:
                forced = replay.popleft()
                if not replay:
                    self._slot_replay[s] = None
                self._tokens[s] = forced
                continue
            tok = int(nxt_host[s])
            self._tokens[s] = tok
            handle = self._slot_req[s]
            handle._append(tok)
            self._jevents.append(("progress", handle))
            if handle.t_first_token is None:
                handle.t_first_token = now
                self._lat.append((
                    "ttft", handle,
                    (handle.t_placed if handle.t_placed is not None
                     else handle.t_submit), now))
            else:
                self._lat.append(("itl", handle,
                                  handle.t_last_token, now))
            handle.t_last_token = now
            emitted += 1
            self._tokens_emitted += 1
            self._maybe_finish(s, tok)
        return emitted

    def _maybe_finish(self, slot: int, tok: int) -> None:
        handle = self._slot_req[slot]
        if handle.eos_id is not None and tok == handle.eos_id:
            reason = "eos"
        elif len(handle.tokens_so_far()) >= handle.max_new_tokens:
            reason = "length"
        else:
            return
        handle._finish(reason)
        self._jevents.append(("done", handle, reason))
        if self.tracer is not None:
            self._lat.append(("end", handle, reason, None))
        self._flight.note("leave", self._steps, slot=slot,
                          reason=reason)
        self._free_slot(slot)
        self._completed += 1

    def _free_slot(self, slot: int) -> None:
        for ring, page in enumerate(self._table[slot]):
            if page is not None:
                self._pool.release(page)
                self._table[slot][ring] = None
        self._trie_owned[slot] = []
        self._fill_next[slot] = -1
        self._first_step[slot] = False
        self._active[slot] = False
        self._slot_req[slot] = None
        self._slot_replay[slot] = None
        self._positions[slot] = 0
        self._tokens[slot] = 0

    # --------------------------------------------------------- eviction
    def _evict_slot(self, s: int) -> None:
        """Rip slot `s`'s request out mid-flight and queue it — FRONT
        of the line — for re-prefill + replay on the next free slot.
        Its mapped pages drop back to the pool (trie-cached copies of
        a shared prefix survive, so the replay often costs nothing).
        Replay-in-progress streams requeue with their full recorded
        output; nothing is emitted twice."""
        handle = self._slot_req[s]
        recorded = handle.tokens_so_far()
        self._flight.note("evict", self._steps, slot=s,
                          req=handle.request_id)
        self._free_slot(s)
        handle.evictions += 1
        self._evictions += 1
        with self._cond:
            self._pending.appendleft((handle, recorded))
            self._cond.notify_all()

    def _evict_lowest_active(self) -> int:
        """Forced mid-generation eviction (the serving.slot_evict
        drill): evict the lowest-indexed active request. Returns the
        eviction count (the caller emits the metric outside the step
        lock)."""
        victims = [s for s in range(self.max_slots) if self._active[s]]
        if not victims:
            return 0
        self._evict_slot(victims[0])
        return 1

    # ------------------------------------------------------- quarantine
    def _quarantine_poisoned(self, ok_host: np.ndarray,
                             decoding: np.ndarray) -> None:
        """Apply the per-slot finite-logits verdict: a non-finite slot
        is quarantined — never offered to `_admit_pending` again — and
        its request replayed on a healthy slot exactly like an
        eviction. Quarantine is PAGE-granular against the pool: the
        slot's privately-owned pages (nothing else references them)
        are written off with it, but pages a trie entry or a prefix
        twin still reads merely drop this slot's reference — the
        poison wrote into the slot's private write cell, never into a
        shared read-only page. The victim's own trie registrations ARE
        suspect (it computed them) and are purged with quarantine
        semantics. A request that poisons `poison_strike_limit`+1
        slots carries the poison in its own tokens: abort it with
        GenerationPoisonedError instead of quarantining the whole
        batch one slot at a time."""
        for s in range(self.max_slots):
            if (not self._active[s] or not decoding[s]
                    or bool(ok_host[s])):
                continue
            handle = self._slot_req[s]
            recorded = handle.tokens_so_far()
            if self._trie is not None and self._trie_owned[s]:
                self._trie.purge(self._trie_owned[s], self._pool)
                self._trie_owned[s] = []
            for ring, page in enumerate(self._table[s]):
                if page is None:
                    continue
                if int(self._pool.ref[page]) <= 1:
                    self._pool.quarantine(page)
                else:
                    self._pool.release(page)
                self._table[s][ring] = None
            self._free_slot(s)
            self._quarantined[s] = True
            self._quarantines += 1
            self._flight.note("quarantine", self._steps, slot=s,
                              req=handle.request_id,
                              strikes=handle.poison_strikes + 1)
            self._flight_dump_reason = "quarantine"
            handle.poison_strikes += 1
            if handle.poison_strikes > self.poison_strike_limit:
                handle._finish(None, error=GenerationPoisonedError(
                    f"generation poisoned {handle.poison_strikes} "
                    f"slots (limit {self.poison_strike_limit}) — "
                    f"aborting instead of replaying further",
                    model=self.model_name,
                    strikes=handle.poison_strikes))
                self._jevents.append(("done", handle, "poisoned"))
                if self.tracer is not None:
                    self._lat.append(("end", handle, "poisoned",
                                      None))
                continue
            with self._cond:
                self._pending.appendleft((handle, recorded or None))
                self._cond.notify_all()

    # ------------------------------------------------------------ stats
    def _publish_gauges(self) -> None:
        active = int(self._active.sum())
        _obs.set_gauge("dl4j_decode_active_slots", active)
        elapsed = max(time.monotonic() - self._t0, 1e-9)
        _obs.set_gauge("dl4j_decode_tokens_per_s",
                       self._tokens_emitted / elapsed)
        _obs.set_gauge("dl4j_decode_pages_free", self._pool.free_count)
        _obs.set_gauge("dl4j_decode_prefix_pages_shared",
                       self._pool.shared_count())

    def tokens_per_s(self) -> float:
        return self._tokens_emitted / max(time.monotonic() - self._t0,
                                          1e-9)

    def latency_stats(self) -> Dict:
        """Per-engine latency attribution over the recent-observation
        rings (p50/p99 — the /status decode facts; the fleet-wide
        histograms live in the metrics registry)."""
        return {
            "ttft_p50_s": _ring_quantile(self._ttft_ring, 0.5),
            "ttft_p99_s": _ring_quantile(self._ttft_ring, 0.99),
            "itl_p50_s": _ring_quantile(self._itl_ring, 0.5),
            "itl_p99_s": _ring_quantile(self._itl_ring, 0.99),
            "queue_wait_p50_s": _ring_quantile(self._queue_ring, 0.5),
            "queue_wait_p99_s": _ring_quantile(self._queue_ring, 0.99),
        }

    def stats(self) -> Dict:
        with self._cond:
            pending = len(self._pending)
        return {
            "model": self.model_name,
            "max_slots": self.max_slots,
            "active_slots": int(self._active.sum()),
            "pending": pending,
            "queue_limit": self.queue_limit,
            "page_size": self.program.page_size,
            "window": self.program.window,
            "pages": {
                "total": self.program.n_pages - 1,
                "free": self._pool.free_count,
                "shared": self._pool.shared_count(),
                "quarantined": len(self._pool.quarantined),
            },
            "prefix_hits": self._prefix_page_hits,
            "prefix_requests_hit": self._prefix_hits,
            "prefill_chunks": self._prefill_chunks,
            "ctx_wraps": self._ctx_wraps,
            "cow_copies": self._cow_copies,
            "trie_blocks": (len(self._trie)
                            if self._trie is not None else 0),
            "steps": self._steps,
            "prefills": self._prefills,
            "tokens_total": self._tokens_emitted,
            "completed": self._completed,
            "evictions": self._evictions,
            "quarantined_slots": int(self._quarantined.sum()),
            "quarantines": self._quarantines,
            "replays": self._replays,
            "deadline_expired": self._deadline_expired,
            "cancelled": self._cancelled,
            "engine_restarts": self._restarts,
            "tokens_per_s": round(self.tokens_per_s(), 3),
            "trace_counts": self.program.trace_stats()["trace_counts"],
            "dispatches": self.program.trace_stats().get("dispatches"),
            "latency": self.latency_stats(),
            "flight": self._flight.stats(),
            "tracing": (self.tracer.stats()
                        if self.tracer is not None else None),
            "journal": (dict(self._journal.stats(),
                             recovered=self._recovered)
                        if self._journal is not None else None),
        }


def sequential_decode(program, prompt: Sequence[int],
                      max_new_tokens: int,
                      eos_id: Optional[int] = None, kv=None,
                      slot: int = 0):
    """The per-request ORACLE: chunked prefill + one-stream decode on
    the same compiled programs the engine runs, one request at a time,
    through a trivially deterministic page allocator (pages handed out
    in order, the ring reusing each slot page in place — no trie, no
    sharing, no CoW). Returns (kv, tokens). Continuous-batched output
    must equal this bitwise for every request regardless of slot
    churn, prefix sharing, or context wrap — the correctness bar that
    makes the paged virtual address space trustworthy."""
    from deeplearning4j_tpu.engine.decode_program import SCRATCH_PAGE

    if kv is None:
        kv = program.init_kv()
    prompt = list(prompt)
    ps = program.page_size
    pps = program.pages_per_slot
    table: List[Optional[int]] = [None] * pps
    next_free = 1  # page 0 is scratch

    def alloc() -> int:
        nonlocal next_free
        if next_free >= program.n_pages:
            raise RuntimeError("oracle page pool exhausted")
        next_free += 1
        return next_free - 1

    for start in program.chunk_starts(len(prompt)):
        ring = (start // ps) % pps
        if table[ring] is None:
            table[ring] = alloc()
        cp, co = program.window_cells(table, start - 1)
        kv = program.prefill_chunk(kv, prompt[start:start + ps],
                                   start, cp, co, table[ring])
    out: List[int] = []
    pos = len(prompt) - 1
    tok = prompt[-1]
    suppress = True  # first step: the prefill already wrote this cell
    s_n = program.max_slots
    c = program.window
    tokens = np.zeros(s_n, np.int32)
    positions = np.zeros(s_n, np.int32)
    while len(out) < max_new_tokens and (eos_id is None or not out
                                         or out[-1] != eos_id):
        cp = np.full((s_n, c), SCRATCH_PAGE, np.int32)
        co = np.zeros((s_n, c), np.int32)
        wp = np.full(s_n, SCRATCH_PAGE, np.int32)
        wo = np.zeros(s_n, np.int32)
        ring = (pos // ps) % pps
        if not suppress:
            if table[ring] is None:
                table[ring] = alloc()
            wp[slot] = table[ring]
            wo[slot] = pos % ps
        tokens[slot] = tok
        positions[slot] = pos
        cp[slot], co[slot] = program.window_cells(table, pos)
        kv, nxt, _ = program.step(kv, tokens, positions, cp, co,
                                  wp, wo)
        tok = int(np.asarray(nxt)[slot])
        out.append(tok)
        pos += 1
        suppress = False
    return kv, out
