"""Continuous batching: the slot-based autoregressive decode engine.

ROADMAP item 3a — THE serving regime for autoregressive traffic at
"millions of users" scale. The fixed-shape request pipeline
(ParallelInference) coalesces one-shot requests into pow2 buckets;
generation is different: a request is ALIVE for many steps, and naive
per-request serving pays a full program dispatch per token for ONE
stream. The DecodeEngine instead runs ONE compiled decode step over a
fixed `max_slots` batch (engine/decode_program.DecodeProgram) and
treats request lifecycle as pure data:

  join    an admitted request claims a free slot at ANY step: one
          bucketed prefill dispatch parks its prompt's K/V pages and
          yields its first token, then the slot rides the shared
          decode loop — running streams never wait out a long prompt
          token-by-token, and nothing recompiles;
  leave   EOS or max-tokens frees the slot between two steps; the
          program never learns a request ended (per-slot active masks
          are host state — the compiled shape is invariant);
  evict   the `serving.slot_evict` fault point (chaos drills) can rip
          an active request out mid-generation: its recovery is
          re-prefill of the ORIGINAL prompt on a free slot + forced
          replay of the already-emitted tokens through the shared
          decode loop. Replay recomputes the exact K/V the evicted
          slot held (same programs, same inputs), so the continuation
          is byte-identical to a never-evicted run — the property
          `sequential_decode` oracles pin.

Byte-identity contract: greedy decoding + per-slot independence of the
compiled step mean every emitted token is a deterministic function of
the request's own tokens — independent of which slot it lands in, who
its neighbors are, and when it joins. tests/test_decode.py pins
engine output == sequential per-request oracle under staggered churn
AND mid-soak eviction chaos.

Admission rides the same vocabulary as the fixed-shape plane: an
optional AdmissionController (tenant quotas / priority shed) in front,
and a hard capacity bound (`max_slots` resident + `queue_limit`
waiting) that rejects with QuotaExceededError -> HTTP 429 +
Retry-After on slot exhaustion.

Per-token accumulation is streaming-capable: tokens land in the
handle under a condition variable as they are emitted
(`tokens_so_far()` / `wait_for_tokens(n)`), so a streaming transport
can drain mid-generation; `result()` blocks for the final sequence.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.observability import metrics as _obs
from deeplearning4j_tpu.resilience.errors import (
    FaultInjectedError,
    QuotaExceededError,
    ShutdownError,
)
from deeplearning4j_tpu.resilience.faults import fire as _fire


class GenerationHandle:
    """One generation stream: prompt in, tokens accumulating out.

    Thread-safe: the engine loop appends, any number of consumers
    read. `finish_reason` is "eos" (the eos token was emitted — it IS
    included in the output) or "length" (max_new_tokens reached)."""

    def __init__(self, prompt: List[int], max_new_tokens: int,
                 eos_id: Optional[int]):
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.eos_id = eos_id
        self.finish_reason: Optional[str] = None
        self.evictions = 0
        self._tokens: List[int] = []
        self._cond = threading.Condition()
        self._done = False
        self._error: Optional[BaseException] = None

    # ------------------------------------------------------- consumers
    def tokens_so_far(self) -> List[int]:
        with self._cond:
            return list(self._tokens)

    def wait_for_tokens(self, n: int, timeout_s: float = 30.0) -> List[int]:
        """Block until at least `n` tokens exist (or the stream ends);
        the streaming-transport primitive."""
        with self._cond:
            self._cond.wait_for(
                lambda: self._done or len(self._tokens) >= n,
                timeout=timeout_s)
            return list(self._tokens)

    @property
    def done(self) -> bool:
        with self._cond:
            return self._done

    def result(self, timeout_s: Optional[float] = 60.0) -> List[int]:
        with self._cond:
            if not self._cond.wait_for(lambda: self._done,
                                       timeout=timeout_s):
                raise TimeoutError(
                    f"generation not finished within {timeout_s}s "
                    f"({len(self._tokens)}/{self.max_new_tokens} tokens)")
            if self._error is not None:
                raise self._error
            return list(self._tokens)

    # ---------------------------------------------------- engine side
    def _append(self, tok: int) -> None:
        with self._cond:
            self._tokens.append(tok)
            self._cond.notify_all()

    def _finish(self, reason: Optional[str],
                error: Optional[BaseException] = None) -> None:
        with self._cond:
            self.finish_reason = reason
            self._error = error
            self._done = True
            self._cond.notify_all()


class DecodeEngine:
    """Slot-based continuous-batching server for one decoder model.

    `submit()` is non-blocking admission; a background loop (or
    explicit `step_once()` calls — the deterministic-test drive)
    advances every resident stream one token per compiled dispatch.
    One DecodeProgram = one decode compile serves arbitrary join/leave
    traffic; `stats()["trace_counts"]` is the pin."""

    def __init__(self, model=None, max_slots: int = 8,
                 page_size: int = 16, queue_limit: Optional[int] = None,
                 admission=None, model_name: str = "decoder",
                 program=None, max_prefills_per_step: int = 1):
        from deeplearning4j_tpu.engine.decode_program import (
            DecodeProgram,
        )

        if program is None:
            if model is None:
                raise ValueError("DecodeEngine needs a model or a "
                                 "DecodeProgram")
            program = DecodeProgram(model, max_slots=max_slots,
                                    page_size=page_size)
        self.program = program
        self.max_slots = program.max_slots
        self.admission = admission
        self.model_name = model_name
        self.queue_limit = (int(queue_limit) if queue_limit is not None
                            else 2 * self.max_slots)
        # a join costs one prefill dispatch between decode steps; cap
        # how many joins one step pays for so an admission burst can't
        # stall resident streams (the prefill-vs-decode phase split)
        self.max_prefills_per_step = max(1, int(max_prefills_per_step))
        self.kv = program.init_kv()
        s = self.max_slots
        self._tokens = np.zeros(s, np.int32)
        self._positions = np.zeros(s, np.int32)
        self._active = np.zeros(s, bool)
        self._slot_req: List[Optional[GenerationHandle]] = [None] * s
        self._slot_replay: List[Optional[deque]] = [None] * s
        # pending entries: (handle, replay_tokens or None)
        self._pending: deque = deque()
        # requests popped from pending but not yet resident (prefill
        # in flight) — still counted against capacity, so admission
        # can't oversubscribe through the placement window
        self._placing = 0
        self._cond = threading.Condition()
        self._step_lock = threading.Lock()
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._t0 = time.monotonic()
        self._tokens_emitted = 0
        self._steps = 0
        self._prefills = 0
        self._evictions = 0
        self._completed = 0

    # -------------------------------------------------------- lifecycle
    def start(self) -> "DecodeEngine":
        with self._cond:
            if self._running:
                return self
            self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="DecodeEngine-loop")
        self._thread.start()
        return self

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def ensure_started(self) -> "DecodeEngine":
        if not self.running:
            return self.start()
        return self

    def stop(self) -> None:
        with self._cond:
            self._running = False
            pending = list(self._pending)
            self._pending.clear()
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        # fail whatever never reached a slot; resident streams keep
        # their partial output readable (tokens_so_far) but never
        # finish — mark them failed too so result() callers unblock
        err = ShutdownError("decode engine stopped")
        for handle, _ in pending:
            handle._finish(None, error=err)
        for s in range(self.max_slots):
            if self._active[s] and self._slot_req[s] is not None:
                self._slot_req[s]._finish(None, error=err)
                self._free_slot(s)

    def _loop(self) -> None:
        while True:
            with self._cond:
                if not self._running:
                    return
            worked = self.step_once()
            if not worked:
                with self._cond:
                    if self._running:
                        self._cond.wait(timeout=0.02)

    # -------------------------------------------------------- admission
    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               eos_id: Optional[int] = None,
               tenant: Optional[str] = None) -> GenerationHandle:
        """Admit one generation request (non-blocking). Raises
        QuotaExceededError (HTTP 429 + Retry-After) on tenant quota /
        priority shed (AdmissionController) or on slot exhaustion —
        every slot resident and the wait queue full."""
        prompt = [int(t) for t in np.asarray(prompt, np.int64).ravel()]
        if not prompt:
            raise ValueError("prompt must carry at least one token")
        max_new_tokens = int(max_new_tokens)
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt) + max_new_tokens > self.program.model.max_ctx:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_ctx "
                f"{self.program.model.max_ctx}")
        capacity = self.max_slots + self.queue_limit
        depth = self._in_flight()
        if self.admission is not None:
            self.admission.admit(tenant, self.model_name, depth,
                                 capacity)
        handle = GenerationHandle(prompt, max_new_tokens, eos_id)
        with self._cond:
            if (int(self._active.sum()) + len(self._pending)
                    + self._placing) >= capacity:
                shed = True
            else:
                shed = False
                self._pending.append((handle, None))
                self._cond.notify_all()
        if shed:
            raise QuotaExceededError(
                f"decode slots exhausted ({self.max_slots} resident, "
                f"{self.queue_limit} waiting)", tenant=tenant or "",
                retry_after_s=0.5)
        return handle

    def generate(self, prompt: Sequence[int], max_new_tokens: int,
                 eos_id: Optional[int] = None,
                 tenant: Optional[str] = None,
                 timeout_s: float = 60.0) -> GenerationHandle:
        """submit + wait: returns the FINISHED handle (tokens via
        `.tokens_so_far()` / `.result()`)."""
        handle = self.submit(prompt, max_new_tokens, eos_id=eos_id,
                             tenant=tenant)
        handle.result(timeout_s=timeout_s)
        return handle

    def _in_flight(self) -> int:
        with self._cond:
            return (int(self._active.sum()) + len(self._pending)
                    + self._placing)

    # ------------------------------------------------------------- step
    def step_once(self) -> bool:
        """One engine iteration: chaos check, admit waiting requests to
        free slots (bounded prefills), one shared decode dispatch,
        harvest. Returns False when there was nothing to do. Public so
        tests drive churn deterministically without the loop thread.
        Telemetry (fault point, counters, gauges) fires OUTSIDE the
        step lock — emission is never a blocking op under a lock."""
        try:
            _fire("serving.slot_evict")
            evict = False
        except FaultInjectedError:
            evict = True
        prefill_s: List[float] = []
        with self._step_lock:
            evicted = self._evict_lowest_active() if evict else 0
            admitted, emitted = self._admit_pending(prefill_s)
            stepped = bool(self._active.any())
            if stepped:
                self.kv, nxt = self.program.step(self.kv, self._tokens,
                                                 self._positions)
                nxt_host = np.asarray(nxt)
                self._steps += 1
                emitted += self._harvest(nxt_host)
        if evicted:
            _obs.count("dl4j_decode_slot_evictions_total", n=evicted)
        for dt in prefill_s:
            _obs.observe("dl4j_decode_prefill_seconds", dt)
        if emitted:
            _obs.count("dl4j_decode_tokens_total", n=emitted)
        self._publish_gauges()
        return stepped or admitted

    def _admit_pending(self, prefill_s: List[float]):
        admitted = False
        emitted = 0
        for _ in range(self.max_prefills_per_step):
            free = [s for s in range(self.max_slots)
                    if not self._active[s]]
            if not free:
                break
            with self._cond:
                if not self._pending:
                    break
                handle, replay = self._pending.popleft()
                self._placing += 1
            try:
                emitted += self._place(handle, replay, free[0],
                                       prefill_s)
            finally:
                with self._cond:
                    self._placing -= 1
            admitted = True
        return admitted, emitted

    def _place(self, handle: GenerationHandle,
               replay: Optional[List[int]], slot: int,
               prefill_s: List[float]) -> int:
        """Prefill `handle`'s prompt into `slot` and make it resident.
        `replay` (eviction recovery) carries the already-emitted
        tokens: the re-prefill regenerates the first one (same
        bucketed program, same prompt — bitwise the same token) and
        the rest are force-fed through the decode loop instead of
        re-emitted, so the stream's output is unaffected by the
        eviction. Returns how many tokens were emitted (0 or 1)."""
        t0 = time.perf_counter()
        self.kv, first_dev = self.program.prefill(self.kv,
                                                  handle.prompt, slot)
        first = int(np.asarray(first_dev))
        self._prefills += 1
        prefill_s.append(time.perf_counter() - t0)
        self._positions[slot] = len(handle.prompt)
        self._slot_req[slot] = handle
        self._active[slot] = True
        if replay:
            # forced replay: the recorded token stream IS the truth
            # (greedy decode would regenerate it; forcing makes the
            # recovery independent of it)
            self._tokens[slot] = replay[0]
            self._slot_replay[slot] = deque(replay[1:]) or None
            return 0
        self._slot_replay[slot] = None
        self._tokens[slot] = first
        handle._append(first)
        self._tokens_emitted += 1
        self._maybe_finish(slot, first)
        return 1

    def _harvest(self, nxt_host: np.ndarray) -> int:
        emitted = 0
        for s in range(self.max_slots):
            if not self._active[s]:
                continue
            self._positions[s] += 1
            replay = self._slot_replay[s]
            if replay is not None:
                forced = replay.popleft()
                if not replay:
                    self._slot_replay[s] = None
                self._tokens[s] = forced
                continue
            tok = int(nxt_host[s])
            self._tokens[s] = tok
            handle = self._slot_req[s]
            handle._append(tok)
            emitted += 1
            self._tokens_emitted += 1
            self._maybe_finish(s, tok)
        return emitted

    def _maybe_finish(self, slot: int, tok: int) -> None:
        handle = self._slot_req[slot]
        if handle.eos_id is not None and tok == handle.eos_id:
            handle._finish("eos")
        elif len(handle.tokens_so_far()) >= handle.max_new_tokens:
            handle._finish("length")
        else:
            return
        self._free_slot(slot)
        self._completed += 1

    def _free_slot(self, slot: int) -> None:
        self._active[slot] = False
        self._slot_req[slot] = None
        self._slot_replay[slot] = None
        self._positions[slot] = 0
        self._tokens[slot] = 0

    # --------------------------------------------------------- eviction
    def _evict_lowest_active(self) -> int:
        """Forced mid-generation eviction (the serving.slot_evict
        drill): rip the lowest-indexed active request out of its slot
        and queue it — FRONT of the line — for re-prefill + replay on
        the next free slot. Replay-in-progress streams requeue with
        their full recorded output; nothing is emitted twice. Returns
        the eviction count (the caller emits the metric outside the
        step lock)."""
        victims = [s for s in range(self.max_slots) if self._active[s]]
        if not victims:
            return 0
        s = victims[0]
        handle = self._slot_req[s]
        recorded = handle.tokens_so_far()
        self._free_slot(s)
        handle.evictions += 1
        self._evictions += 1
        with self._cond:
            self._pending.appendleft((handle, recorded))
            self._cond.notify_all()
        return 1

    # ------------------------------------------------------------ stats
    def _publish_gauges(self) -> None:
        active = int(self._active.sum())
        _obs.set_gauge("dl4j_decode_active_slots", active)
        elapsed = max(time.monotonic() - self._t0, 1e-9)
        _obs.set_gauge("dl4j_decode_tokens_per_s",
                       self._tokens_emitted / elapsed)

    def tokens_per_s(self) -> float:
        return self._tokens_emitted / max(time.monotonic() - self._t0,
                                          1e-9)

    def stats(self) -> Dict:
        with self._cond:
            pending = len(self._pending)
        return {
            "model": self.model_name,
            "max_slots": self.max_slots,
            "active_slots": int(self._active.sum()),
            "pending": pending,
            "queue_limit": self.queue_limit,
            "page_size": self.program.page_size,
            "max_ctx": self.program.model.max_ctx,
            "steps": self._steps,
            "prefills": self._prefills,
            "tokens_total": self._tokens_emitted,
            "completed": self._completed,
            "evictions": self._evictions,
            "tokens_per_s": round(self.tokens_per_s(), 3),
            "trace_counts": self.program.trace_stats()["trace_counts"],
        }


def sequential_decode(program, prompt: Sequence[int],
                      max_new_tokens: int,
                      eos_id: Optional[int] = None, kv=None,
                      slot: int = 0):
    """The per-request ORACLE: prefill + one-stream decode on the same
    compiled programs the engine runs, one request at a time. Returns
    (kv, tokens). Continuous-batched output must equal this bitwise
    for every request regardless of slot churn — the correctness bar
    that makes slot join/leave (and eviction replay) trustworthy."""
    if kv is None:
        kv = program.init_kv()
    tokens = np.zeros(program.max_slots, np.int32)
    positions = np.zeros(program.max_slots, np.int32)
    kv, first = program.prefill(kv, prompt, slot)
    out = [int(np.asarray(first))]
    tokens[slot] = out[0]
    positions[slot] = len(list(prompt))
    while len(out) < max_new_tokens and (eos_id is None
                                         or out[-1] != eos_id):
        kv, nxt = program.step(kv, tokens, positions)
        positions[slot] += 1
        tok = int(np.asarray(nxt)[slot])
        out.append(tok)
        tokens[slot] = tok
    return kv, out
