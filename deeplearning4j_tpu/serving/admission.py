"""Tenant admission: token-bucket quotas + priority load-shedding.

The admission layer sits BETWEEN the HTTP surface and the bounded
request queue of a model's ParallelInference — it decides *whose*
request is allowed to contend for queue space, so the existing
backpressure/deadline machinery (OverloadedError when the queue fills,
per-call deadlines inside `output()`) keeps doing the mechanics while
this layer does the policy:

  quota      every tenant owns a token bucket (`rate` tokens/s, burst
             capacity `burst`); an empty bucket rejects with
             QuotaExceededError -> HTTP 429 + Retry-After, computed
             from the bucket's actual refill horizon;
  priority   each tenant carries a priority class (high/normal/low).
             When the model's queue is under pressure, LOW classes are
             shed first: a class is admitted only while queue depth is
             below its shed threshold (low 50%, normal 85%, high 100%
             of queue_limit by default). High-priority traffic is only
             ever rejected by the bounded queue itself — the
             "shed lowest class first" discipline of the ISSUE/SLO.

Every decision emits through the MetricsRegistry with per-tenant and
per-priority labels (`dl4j_serving_admitted_total`,
`dl4j_serving_shed_total{reason=quota|pressure}`), so a /metrics scrape
shows exactly who is being shed and why.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from deeplearning4j_tpu.observability import metrics as _obs
from deeplearning4j_tpu.resilience.errors import (
    FaultInjectedError,
    QuotaExceededError,
)
from deeplearning4j_tpu.resilience.faults import fire as _fire

# priority classes, lowest number = most important = shed last
PRIORITY_CLASSES = {"high": 0, "normal": 1, "low": 2}

# fraction of queue_limit at which a class stops being admitted;
# high is 1.0: only the bounded queue itself can reject it
DEFAULT_SHED_THRESHOLDS = {"high": 1.0, "normal": 0.85, "low": 0.5}


class TokenBucket:
    """Classic token bucket on the monotonic clock (thread-safe).

    `rate` tokens/s refill up to `burst` capacity; `try_take` is
    non-blocking — admission never queues, it admits or sheds."""

    def __init__(self, rate: float, burst: Optional[float] = None):
        self.rate = float(rate)
        self.burst = float(burst if burst is not None
                           else max(1.0, self.rate))
        self._tokens = self.burst
        self._t = time.monotonic()
        self._lock = threading.Lock()

    def try_take(self, n: float = 1.0) -> bool:
        with self._lock:
            now = time.monotonic()
            self._tokens = min(
                self.burst, self._tokens + (now - self._t) * self.rate)
            self._t = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def retry_after_s(self, n: float = 1.0) -> float:
        """How long until `n` tokens will have refilled (advisory)."""
        with self._lock:
            missing = max(0.0, n - self._tokens)
        if missing <= 0.0 or self.rate <= 0.0:
            return 1.0
        return max(0.05, missing / self.rate)

    def available(self) -> float:
        with self._lock:
            now = time.monotonic()
            self._tokens = min(
                self.burst, self._tokens + (now - self._t) * self.rate)
            self._t = now
            return self._tokens


class TenantConfig:
    """One tenant's contract: rate/burst quota + priority class.

    `rate=None` means unmetered (no token bucket) — priority shedding
    still applies."""

    def __init__(self, name: str, rate: Optional[float] = None,
                 burst: Optional[float] = None,
                 priority: str = "normal"):
        if priority not in PRIORITY_CLASSES:
            raise ValueError(
                f"priority must be one of {sorted(PRIORITY_CLASSES)}: "
                f"{priority!r}")
        self.name = name
        self.rate = rate
        self.burst = burst
        self.priority = priority
        self.bucket = (TokenBucket(rate, burst)
                       if rate is not None else None)

    @classmethod
    def from_dict(cls, name: str, d: dict) -> "TenantConfig":
        return cls(name, rate=d.get("rate"), burst=d.get("burst"),
                   priority=d.get("priority", "normal"))

    def to_dict(self) -> dict:
        return {"rate": self.rate, "burst": self.burst,
                "priority": self.priority}


class AdmissionController:
    """Per-tenant quota + priority shedding in front of every model's
    bounded queue. Unknown tenants get `default` (unmetered, normal
    priority, sheddable under pressure) so the layer is zero-config
    until an operator writes a tenant table."""

    def __init__(self, tenants: Optional[Dict[str, TenantConfig]] = None,
                 default: Optional[TenantConfig] = None,
                 shed_thresholds: Optional[Dict[str, float]] = None):
        self.tenants: Dict[str, TenantConfig] = dict(tenants or {})
        self.default = default or TenantConfig("default",
                                               priority="normal")
        self.shed_thresholds = dict(DEFAULT_SHED_THRESHOLDS)
        if shed_thresholds:
            self.shed_thresholds.update(shed_thresholds)
        self.counters = {"admitted": 0, "shed_quota": 0,
                         "shed_pressure": 0}
        self._lock = threading.Lock()

    @classmethod
    def from_config(cls, tenants: Dict[str, dict],
                    **kwargs) -> "AdmissionController":
        """Build from a plain {tenant: {rate, burst, priority}} table
        (the JSON an operator would ship)."""
        return cls({name: TenantConfig.from_dict(name, d)
                    for name, d in tenants.items()}, **kwargs)

    def config_for(self, tenant: Optional[str]) -> TenantConfig:
        if tenant is None:
            return self.default
        return self.tenants.get(tenant, self.default)

    def admit(self, tenant: Optional[str], model: str,
              queue_depth: int, queue_limit: int) -> TenantConfig:
        """Admit or shed one request. Raises QuotaExceededError (the
        HTTP 429) when the tenant's bucket is empty or its priority
        class is under pressure-shed; returns the tenant's config on
        admission so the caller can tag downstream accounting."""
        cfg = self.config_for(tenant)
        tname = tenant or cfg.name
        labels = {"tenant": tname, "priority": cfg.priority}
        # chaos drill: an armed `admission.quota_storm` raise is
        # consumed as a forced quota shed for METERED tenants only —
        # the synthetic storm drains token buckets, so unmetered
        # classes (gold) ride through it untouched
        storm = False
        try:
            _fire("admission.quota_storm")
        except FaultInjectedError:
            storm = cfg.bucket is not None
        if storm or (cfg.bucket is not None
                     and not cfg.bucket.try_take()):
            with self._lock:
                self.counters["shed_quota"] += 1
            _obs.count("dl4j_serving_shed_total",
                       labels={**labels, "reason": "quota"})
            raise QuotaExceededError(
                f"tenant {tname!r} quota exhausted "
                f"({cfg.rate:g} req/s)", tenant=tname,
                retry_after_s=cfg.bucket.retry_after_s())
        threshold = self.shed_thresholds.get(cfg.priority, 1.0)
        if queue_limit > 0 and threshold < 1.0 \
                and queue_depth >= threshold * queue_limit:
            with self._lock:
                self.counters["shed_pressure"] += 1
            _obs.count("dl4j_serving_shed_total",
                       labels={**labels, "reason": "pressure"})
            raise QuotaExceededError(
                f"queue under pressure ({queue_depth}/{queue_limit}); "
                f"priority class {cfg.priority!r} is being shed",
                tenant=tname, retry_after_s=0.5)
        with self._lock:
            self.counters["admitted"] += 1
        _obs.count("dl4j_serving_admitted_total", labels=labels)
        return cfg

    def stats(self) -> dict:
        with self._lock:
            counters = dict(self.counters)
        return {
            "tenants": {name: cfg.to_dict()
                        for name, cfg in self.tenants.items()},
            "default": self.default.to_dict(),
            "shed_thresholds": dict(self.shed_thresholds),
            **counters,
        }
