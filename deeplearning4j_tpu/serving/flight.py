"""Crash flight recorder: the last-N-steps story the journal can't tell.

The write-ahead journal is request-level (admitted/progress/done) —
enough to recover work, useless for answering "what was the engine
DOING in the seconds before it quarantined a slot / tripped the
watchdog / restarted." A `FlightRecorder` is a bounded in-memory ring
of cheap step-event tuples (slot joins/leaves, prefill-chunk
dispatches, step verdicts, evictions, quarantines, restarts) appended
by the decode engine as it works; recording costs one tuple append, so
it stays on even in production.

On a crash-adjacent event (quarantine, watchdog restart, engine
restart) — or on `SIGUSR2` for a live postmortem — `dump()` writes the
ring ATOMICALLY (tmp file + `os.replace`) as a JSON document next to
whatever `dump_dir` the engine was given, so a half-written dump can
never masquerade as a whole one. Dump paths are tracked module-wide
and `reap_stray_flight_dumps()` removes them (tests/conftest.py calls
it on teardown, mirroring the journal-reaping fixture).

`install_signal_dump()` is opt-in (never installed implicitly): it
hooks SIGUSR2 to dump every live recorder, chaining any previous
handler.
"""

from __future__ import annotations

import json
import os
import signal
import tempfile
import threading
import time
import weakref
from collections import deque
from typing import List, Optional

from deeplearning4j_tpu.observability import metrics as _obs

# every recorder constructed in this process (weak — dead recorders
# drop out); the SIGUSR2 handler dumps whatever is still live
_LIVE_RECORDERS: "weakref.WeakSet[FlightRecorder]" = weakref.WeakSet()
# dump files written by any recorder — reaped by tests/conftest.py so
# an interrupted drill leaks no postmortem litter into later runs
_FLIGHT_DUMPS: List[str] = []
_DUMPS_LOCK = threading.Lock()


def reap_stray_flight_dumps() -> None:
    """Remove every flight-recorder dump file written in this process.
    Teardown backstop for chaos tests — idempotent, touches nothing if
    no recorder ever dumped."""
    with _DUMPS_LOCK:
        paths, _FLIGHT_DUMPS[:] = list(_FLIGHT_DUMPS), []
    for p in paths:
        try:
            os.unlink(p)
        except OSError:
            pass


def install_signal_dump(signum: int = getattr(signal, "SIGUSR2", 0)):
    """Hook `signum` (default SIGUSR2) to dump every live recorder —
    the kill -USR2 live-postmortem path. Chains the previous handler;
    returns it so callers/tests can restore. Main thread only (signal
    module requirement); returns None when unavailable."""
    if not signum:
        return None

    prev = signal.getsignal(signum)

    def _dump_all(sig, frame):
        for rec in list(_LIVE_RECORDERS):
            rec.dump("sigusr2")
        if callable(prev):
            prev(sig, frame)

    signal.signal(signum, _dump_all)
    return prev


class FlightRecorder:
    """Bounded ring of recent engine step events + atomic crash dump.

    `note()` is called under the engine's step lock, so it must stay
    O(1) and allocation-light: one tuple append into a deque(maxlen).
    `dump()` does file I/O and is only ever called OUTSIDE the step
    lock (the engine collects a dump *reason* under the lock and dumps
    after releasing it)."""

    def __init__(self, capacity: int = 512,
                 dump_dir: Optional[str] = None,
                 name: str = "decoder"):
        self.capacity = max(16, int(capacity))
        self.name = str(name)
        self.dump_dir = dump_dir or tempfile.gettempdir()
        self._ring: deque = deque(maxlen=self.capacity)
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()
        self._dumps = 0
        self._last_dump: Optional[str] = None
        self._last_reason: Optional[str] = None
        self._seq = 0
        _LIVE_RECORDERS.add(self)

    # ---------------------------------------------------------- record
    def note(self, kind: str, step: int, **fields) -> None:
        """One ring entry: (t_rel_s, step, kind, fields-or-None)."""
        self._ring.append((time.perf_counter() - self._t0, int(step),
                           kind, fields or None))

    # ----------------------------------------------------------- reads
    def events(self) -> List[dict]:
        out = []
        for t, step, kind, fields in list(self._ring):
            ev = {"t_s": round(t, 6), "step": step, "kind": kind}
            if fields:
                ev.update(fields)
            out.append(ev)
        return out

    def stats(self) -> dict:
        with self._lock:
            return {"events": len(self._ring),
                    "capacity": self.capacity,
                    "dumps": self._dumps,
                    "last_dump": self._last_dump,
                    "last_reason": self._last_reason}

    # ------------------------------------------------------------ dump
    def dump(self, reason: str) -> Optional[str]:
        """Atomically write the ring as JSON; returns the dump path, or
        None when the write failed (a full disk must not cascade into
        the decode loop). Never called under the step lock."""
        with self._lock:
            self._seq += 1
            seq = self._seq
        doc = {"name": self.name, "reason": str(reason),
               "pid": os.getpid(), "wall_time_s": time.time(),
               "uptime_s": round(time.perf_counter() - self._t0, 6),
               "events": self.events()}
        path = os.path.join(
            self.dump_dir,
            f"flight-{self.name}-{os.getpid()}-{seq:03d}.json")
        try:
            fd, tmp = tempfile.mkstemp(dir=self.dump_dir,
                                       prefix=".flight-", suffix=".tmp")
            try:
                with os.fdopen(fd, "w") as f:
                    json.dump(doc, f)
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        except OSError:
            return None
        with _DUMPS_LOCK:
            _FLIGHT_DUMPS.append(path)
        with self._lock:
            self._dumps += 1
            self._last_dump = path
            self._last_reason = str(reason)
        _obs.count("dl4j_decode_flight_dumps_total",
                   labels={"reason": str(reason)})
        return path


def load_dump(path: str) -> dict:
    """Read a dump back (inspection workflow: `python -m json.tool`
    works too — this helper just keeps tests honest about the shape)."""
    with open(path) as f:
        return json.load(f)
