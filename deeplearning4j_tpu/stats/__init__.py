from deeplearning4j_tpu.stats.report import StatsReport  # noqa: F401
from deeplearning4j_tpu.stats.storage import (  # noqa: F401
    FileStatsStorage,
    InMemoryStatsStorage,
    RemoteStatsStorageRouter,
    StatsStorage,
)
from deeplearning4j_tpu.stats.listener import StatsListener  # noqa: F401
from deeplearning4j_tpu.stats.dashboard import (  # noqa: F401
    UIServer,
    collect_conv_activations,
    collect_network_flow,
    embedding_scatter,
    render_html,
    telemetry_lines,
)
