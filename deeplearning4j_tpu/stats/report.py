"""StatsReport: one observation of training state.

Parity: the reference's SBE-encoded StatsReport
(ui/stats/impl/SbeStatsReport.java; collected fields per
BaseStatsListener.java:106 — score, timing, memory, histograms and mean
magnitudes of params/updates). TPU-native difference: plain dataclass +
JSON (SBE codecs are unnecessary — reports are small and collected every
N iterations, off the hot path)."""

from __future__ import annotations

import dataclasses
import json
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class Histogram:
    """Fixed-bin histogram of one tensor group."""
    min: float
    max: float
    counts: list

    def to_dict(self):
        return dataclasses.asdict(self)


@dataclass
class StatsReport:
    session_id: str
    worker_id: str = "local"
    iteration: int = 0
    epoch: int = 0
    timestamp: float = field(default_factory=time.time)
    score: Optional[float] = None
    samples_per_sec: Optional[float] = None
    batches_per_sec: Optional[float] = None
    iter_ms: Optional[float] = None
    etl_ms: Optional[float] = None
    mem: Dict[str, Any] = field(default_factory=dict)
    # per parameter-group ("0/W", "conv1/b", ...) summaries
    param_mean_magnitudes: Dict[str, float] = field(default_factory=dict)
    update_mean_magnitudes: Dict[str, float] = field(default_factory=dict)
    param_histograms: Dict[str, Histogram] = field(default_factory=dict)
    update_histograms: Dict[str, Histogram] = field(default_factory=dict)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "StatsReport":
        d = dict(d)
        for k in ("param_histograms", "update_histograms"):
            d[k] = {name: Histogram(**h) for name, h in (d.get(k) or {}).items()}
        return cls(**d)

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @classmethod
    def from_json(cls, s: str) -> "StatsReport":
        return cls.from_dict(json.loads(s))
