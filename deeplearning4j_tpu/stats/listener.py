"""StatsListener: collects training telemetry into a StatsStorage.

Parity: ui/stats/BaseStatsListener.java:106 — score, throughput, ETL
time, memory, and histograms + mean magnitudes of parameters and
updates, sampled every `frequency` iterations.

TPU-native design: summaries are computed ON DEVICE by one jitted
reduction program (per-group histogram counts + mean |x|), so only
tiny arrays cross the host boundary, and only on collection
iterations — the train step itself is untouched. "Updates" are the
parameter deltas across the collection window (the reference records
per-iteration updater output; the window delta is the same signal
sampled at the listener's own frequency, without forcing the step to
emit 100MB of per-iteration gradients). Gradient histograms are
intentionally not collected for that reason.
"""

from __future__ import annotations

import resource
import time
import uuid
from typing import Any, Dict, Optional

from deeplearning4j_tpu.stats.report import Histogram, StatsReport
from deeplearning4j_tpu.stats.storage import StatsStorage


def jnp_array(a):
    import jax.numpy as jnp

    return jnp.array(a)


def _score_once(model):
    """At most ONE score() call per report: score() pays a device->host
    sync, and the old `None if model.score() is None else
    float(model.score())` paid it twice (dl4j-analyze jit-host-sync)."""
    s = model.score()
    return None if s is None else float(s)


def _named_leaves(params):
    """Flatten params into [(group_name, leaf), ...] with stable names
    like '0/W' (list container) or 'conv1/gamma' (dict container)."""
    import jax

    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        parts = []
        for p in path:
            if hasattr(p, "key"):
                parts.append(str(p.key))
            elif hasattr(p, "idx"):
                parts.append(str(p.idx))
            else:
                parts.append(str(p))
        out.append(("/".join(parts), leaf))
    return out


class StatsListener:
    """Attach with `net.listeners.append(StatsListener(storage))`.

    collect_histograms/collect_updates mirror the reference's
    DefaultStatsUpdateConfiguration toggles."""

    def __init__(self, storage: StatsStorage, frequency: int = 10,
                 session_id: Optional[str] = None,
                 worker_id: str = "local",
                 collect_histograms: bool = True,
                 collect_updates: bool = True,
                 num_bins: int = 32):
        self.storage = storage
        self.frequency = max(1, frequency)
        self.session_id = session_id or f"session-{uuid.uuid4().hex[:8]}"
        self.worker_id = worker_id
        self.collect_histograms = collect_histograms
        self.collect_updates = collect_updates
        self.num_bins = num_bins
        self._stats_fn = None
        self._prev_params = None
        self._last_time = None
        self._last_iter = None

    # ------------------------------------------------------------ device side
    def _build_stats_fn(self):
        import jax
        import jax.numpy as jnp

        bins = self.num_bins

        def summarize(tree):
            out = {}
            for name, leaf in _named_leaves(tree):
                x = leaf.reshape(-1).astype(jnp.float32)
                lo = jnp.min(x)
                hi = jnp.max(x)
                counts, _ = jnp.histogram(x, bins=bins, range=None)
                out[name] = (lo, hi, counts, jnp.mean(jnp.abs(x)))
            return out

        def fn(params, prev):
            res = {"params": summarize(params)}
            if prev is not None:
                delta = jax.tree_util.tree_map(
                    lambda a, b: a - b, params, prev)
                res["updates"] = summarize(delta)
            return res

        return jax.jit(fn, static_argnames=())

    def _collect_summaries(self, net) -> Dict[str, Any]:
        import jax

        if self._stats_fn is None:
            self._stats_fn = self._build_stats_fn()
        prev = self._prev_params if self.collect_updates else None
        res = self._stats_fn(net.params, prev)
        out = {}
        for kind, groups in res.items():
            hists = {}
            means = {}
            for name, (lo, hi, counts, mean_abs) in groups.items():
                means[name] = float(mean_abs)
                if self.collect_histograms:
                    hists[name] = Histogram(
                        min=float(lo), max=float(hi),
                        counts=[int(c) for c in counts])
            out[kind] = (means, hists)
        if self.collect_updates:
            # deep copy: the train step donates its param buffers, so a
            # bare reference would be deleted by the next step
            self._prev_params = jax.tree_util.tree_map(
                jnp_array, net.params)
        return out

    # -------------------------------------------------------------- listener
    def iteration_done(self, model, iteration: int):
        now = time.perf_counter()
        if self._last_time is None:
            self._last_time = now
            self._last_iter = iteration
            # baseline snapshot so the first collected window has updates
            if self.collect_updates and model.params is not None:
                import jax
                self._prev_params = jax.tree_util.tree_map(
                    jnp_array, model.params)
            return
        if iteration % self.frequency != 0:
            return

        dt = now - self._last_time
        n = max(iteration - self._last_iter, 1)
        batches_per_sec = n / dt if dt > 0 else None
        batch = getattr(model, "_last_batch_size", None)
        report = StatsReport(
            session_id=self.session_id,
            worker_id=self.worker_id,
            iteration=iteration,
            epoch=getattr(model, "epoch", 0),
            score=_score_once(model),
            batches_per_sec=batches_per_sec,
            samples_per_sec=(batches_per_sec * batch
                             if batches_per_sec and batch else None),
            iter_ms=dt / n * 1e3,
            etl_ms=getattr(model, "_last_etl_ms", None),
            mem=self._memory(),
        )
        summaries = self._collect_summaries(model)
        report.param_mean_magnitudes, report.param_histograms = \
            summaries["params"]
        if "updates" in summaries:
            (report.update_mean_magnitudes,
             report.update_histograms) = summaries["updates"]
        self.storage.put_report(report)
        self._last_time = time.perf_counter()
        self._last_iter = iteration

    @staticmethod
    def _memory() -> Dict[str, Any]:
        mem = {"host_rss_mb": resource.getrusage(
            resource.RUSAGE_SELF).ru_maxrss / 1024.0}
        try:
            import jax

            st = jax.devices()[0].memory_stats()
            if st:
                mem["device_in_use_mb"] = st.get(
                    "bytes_in_use", 0) / 1e6
                mem["device_limit_mb"] = st.get(
                    "bytes_limit", 0) / 1e6
        except Exception:   # noqa: BLE001 - device memory stats are
            pass            # best-effort (no backend / no stats API)
        return mem
