"""Stats storage SPI + in-memory and file backends.

Parity: api/storage/StatsStorage.java (SPI shared by UI & Spark),
ui/storage/InMemoryStatsStorage.java:21, FileStatsStorage.java /
MapDBStatsStorage.java:22 (persistent). The file backend is append-only
JSONL — durable, tail-able, and diff-friendly; MapDB is a JVM-ism."""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, List, Optional

from deeplearning4j_tpu.stats.report import StatsReport


class StatsStorage:
    """SPI: put/list/get reports + change listeners
    (ref: StatsStorage.java / StatsStorageRouter.java)."""

    def put_report(self, report: StatsReport) -> None:
        raise NotImplementedError

    def session_ids(self) -> List[str]:
        raise NotImplementedError

    def reports(self, session_id: str) -> List[StatsReport]:
        raise NotImplementedError

    def latest(self, session_id: str) -> Optional[StatsReport]:
        rs = self.reports(session_id)
        return rs[-1] if rs else None

    def add_listener(self, fn: Callable[[StatsReport], None]) -> None:
        self._listeners().append(fn)

    def _listeners(self) -> list:
        if not hasattr(self, "_cbs"):
            self._cbs = []
        return self._cbs

    def _notify(self, report: StatsReport) -> None:
        for fn in self._listeners():
            fn(report)

    def close(self) -> None:
        pass


class InMemoryStatsStorage(StatsStorage):
    """ref: InMemoryStatsStorage.java:21."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_session: Dict[str, List[StatsReport]] = {}

    def put_report(self, report: StatsReport) -> None:
        with self._lock:
            self._by_session.setdefault(report.session_id, []).append(report)
        self._notify(report)

    def session_ids(self) -> List[str]:
        with self._lock:
            return list(self._by_session)

    def reports(self, session_id: str) -> List[StatsReport]:
        with self._lock:
            return list(self._by_session.get(session_id, []))


class FileStatsStorage(StatsStorage):
    """Append-only JSONL file storage (ref: FileStatsStorage.java /
    MapDBStatsStorage.java:22 persistent role). Reopening the same path
    loads previously recorded reports."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._mem = InMemoryStatsStorage()
        if os.path.exists(path):
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        self._mem.put_report(StatsReport.from_json(line))
        self._fh = open(path, "a")

    def put_report(self, report: StatsReport) -> None:
        with self._lock:
            self._fh.write(report.to_json() + "\n")
            self._fh.flush()
        self._mem.put_report(report)
        self._notify(report)

    def session_ids(self) -> List[str]:
        return self._mem.session_ids()

    def reports(self, session_id: str) -> List[StatsReport]:
        return self._mem.reports(session_id)

    def close(self) -> None:
        with self._lock:
            self._fh.close()


class RemoteStatsStorageRouter(StatsStorage):
    """POSTs reports as JSON to a remote UIServer's /remote endpoint
    (ref: deeplearning4j-core api/storage/impl/
    RemoteUIStatsStorageRouter.java:33 -> RemoteReceiverModule). Write
    path only; reads raise (query the receiving server instead)."""

    def __init__(self, url: str, timeout: float = 10.0,
                 retry_count: int = 3):
        if not url.rstrip("/").endswith("/remote"):
            url = url.rstrip("/") + "/remote"
        self.url = url
        self.timeout = timeout
        self.retry_count = retry_count

    def put_report(self, report: StatsReport) -> None:
        import urllib.request

        body = report.to_json().encode()
        req = urllib.request.Request(
            self.url, data=body,
            headers={"Content-Type": "application/json"})
        last = None
        for _ in range(max(1, self.retry_count)):
            try:
                urllib.request.urlopen(req, timeout=self.timeout)
                self._notify(report)
                return
            except Exception as e:   # noqa: BLE001 - retried
                last = e
        raise IOError(f"failed to POST stats report to {self.url}: {last}")

    def session_ids(self):
        raise NotImplementedError(
            "RemoteStatsStorageRouter is write-only; query the receiving "
            "UIServer's storage")

    def reports(self, session_id):
        raise NotImplementedError(
            "RemoteStatsStorageRouter is write-only; query the receiving "
            "UIServer's storage")
