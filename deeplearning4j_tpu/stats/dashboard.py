"""Training dashboard: static HTML export + minimal HTTP server.

Parity: the reference's Play UI train module (ui/play/PlayUIServer.java,
ui/module/train/TrainModule.java — score chart, mean-magnitude
timelines, histograms, system tab; conv-activation grids via the
activations view, and the t-SNE tab ui/module/tsne/). TPU-native
difference: a dependency-free self-contained HTML file (inline SVG
charts, data embedded as JSON) — no Play framework, no websockets; the
UIServer re-renders on each GET, which at listener frequencies is
milliseconds. `collect_conv_activations` + `embedding_scatter` build
the two extra tabs' data from a live net; pass them to render_html.
"""

from __future__ import annotations

import html
import json
import threading
from typing import Optional

from deeplearning4j_tpu.stats.storage import StatsStorage

_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>deeplearning4j_tpu — training</title>
<style>
 body {{ font-family: system-ui, sans-serif; margin: 24px; color: #222; }}
 h1 {{ font-size: 20px; }} h2 {{ font-size: 16px; margin-top: 28px; }}
 .meta {{ color: #666; font-size: 13px; }}
 .row {{ display: flex; flex-wrap: wrap; gap: 24px; }}
 .chart {{ border: 1px solid #ddd; border-radius: 6px; padding: 8px; }}
 .lbl {{ font-size: 12px; color: #555; text-anchor: middle; }}
</style></head>
<body>
<h1>Training session <code>{session}</code></h1>
<p class="meta">{n} reports · final score {final_score} ·
 {sps} samples/sec · ETL {etl} ms · device mem {dev_mem} MB</p>
<div id="telemetry"></div>
<div id="charts" class="row"></div>
<h2>Parameter mean magnitudes (log10)</h2>
<div id="pmm" class="row"></div>
<h2>Update mean magnitudes (log10)</h2>
<div id="umm" class="row"></div>
<h2>Latest parameter histograms</h2>
<div id="hists" class="row"></div>
<h2>Network graph</h2>
<div id="flow" class="row"></div>
<h2>Convolutional activations</h2>
<div id="acts" class="row"></div>
<h2>Embedding t-SNE</h2>
<div id="tsne" class="row"></div>
<script>
const DATA = {data};
if (DATA.telemetry_lines && DATA.telemetry_lines.length) {{
  // one substrate: the self-healing / cluster / serving lines are
  // derived (in Python, telemetry_lines) from a MetricsRegistry
  // snapshot instead of per-component stats dicts; the raw snapshot
  // rides along as DATA.telemetry for programmatic consumers
  document.getElementById('telemetry').innerHTML = DATA.telemetry_lines
    .map(l => '<p class="meta">' + l + '</p>').join('');
}}
function svgLine(pts, w, h, color) {{
  if (pts.length === 0) return '';
  const xs = pts.map(p => p[0]), ys = pts.map(p => p[1]);
  const x0 = Math.min(...xs), x1 = Math.max(...xs);
  const y0 = Math.min(...ys), y1 = Math.max(...ys);
  const sx = v => 40 + (w - 50) * (x1 === x0 ? 0 : (v - x0) / (x1 - x0));
  const sy = v => (h - 20) - (h - 35) * (y1 === y0 ? 0.5 : (v - y0) / (y1 - y0));
  const d = pts.map((p, i) => (i ? 'L' : 'M') + sx(p[0]).toFixed(1) + ' ' + sy(p[1]).toFixed(1)).join(' ');
  return `<path d="${{d}}" fill="none" stroke="${{color}}" stroke-width="1.5"/>` +
    `<text class="lbl" x="8" y="18" text-anchor="start">${{y1.toPrecision(4)}}</text>` +
    `<text class="lbl" x="8" y="${{h - 22}}" text-anchor="start">${{y0.toPrecision(4)}}</text>`;
}}
function chart(title, pts, color) {{
  const w = 420, h = 180;
  return `<div class="chart"><svg width="${{w}}" height="${{h}}">` +
    svgLine(pts, w, h, color) +
    `<text class="lbl" x="${{w / 2}}" y="${{h - 4}}">${{title}}</text></svg></div>`;
}}
function bars(title, hist) {{
  const w = 320, h = 140, n = hist.counts.length;
  const m = Math.max(...hist.counts, 1);
  let rects = '';
  for (let i = 0; i < n; i++) {{
    const bh = (h - 30) * hist.counts[i] / m;
    rects += `<rect x="${{5 + i * (w - 10) / n}}" y="${{h - 22 - bh}}"` +
      ` width="${{(w - 10) / n - 1}}" height="${{bh}}" fill="#4a7fb5"/>`;
  }}
  return `<div class="chart"><svg width="${{w}}" height="${{h}}">` + rects +
    `<text class="lbl" x="${{w / 2}}" y="${{h - 8}}">${{title}}` +
    ` [${{hist.min.toPrecision(3)}}, ${{hist.max.toPrecision(3)}}]</text></svg></div>`;
}}
const reps = DATA.reports;
const iters = reps.map(r => r.iteration);
const sc = reps.filter(r => r.score != null).map(r => [r.iteration, r.score]);
document.getElementById('charts').innerHTML =
  chart('score vs iteration', sc, '#c0392b') +
  chart('samples/sec', reps.filter(r => r.samples_per_sec != null)
        .map(r => [r.iteration, r.samples_per_sec]), '#27ae60') +
  chart('ETL ms', reps.filter(r => r.etl_ms != null)
        .map(r => [r.iteration, r.etl_ms]), '#8e44ad');
function mmCharts(el, key) {{
  const names = new Set();
  reps.forEach(r => Object.keys(r[key] || {{}}).forEach(k => names.add(k)));
  let htmlStr = '';
  for (const name of Array.from(names).slice(0, 24)) {{
    const pts = reps.filter(r => (r[key] || {{}})[name] > 0)
      .map(r => [r.iteration, Math.log10(r[key][name])]);
    htmlStr += chart(name, pts, '#2c6fad');
  }}
  document.getElementById(el).innerHTML = htmlStr || '<p class="meta">none collected</p>';
}}
mmCharts('pmm', 'param_mean_magnitudes');
mmCharts('umm', 'update_mean_magnitudes');
const last = reps[reps.length - 1] || {{}};
let hh = '';
for (const [name, hist] of Object.entries(last.param_histograms || {{}}).slice(0, 24))
  hh += bars(name, hist);
document.getElementById('hists').innerHTML = hh || '<p class="meta">none collected</p>';
const flow = DATA.flow;
if (flow && flow.nodes.length) {{
  const byDepth = {{}};
  flow.nodes.forEach(n => (byDepth[n.depth] = byDepth[n.depth] || []).push(n));
  const depths = Object.keys(byDepth).map(Number).sort((a, b) => a - b);
  const colW = 180, rowH = 46;
  const maxRows = Math.max(...depths.map(d => byDepth[d].length));
  const w = depths.length * colW + 20, h = maxRows * rowH + 30;
  const pos = {{}};
  depths.forEach((d, di) => byDepth[d].forEach((n, ri) => {{
    pos[n.name] = [20 + di * colW, 20 + ri * rowH];
  }}));
  let svg = '';
  flow.edges.forEach(e => {{
    const a = pos[e[0]], b = pos[e[1]];
    if (a && b) svg += `<line x1="${{a[0] + 120}}" y1="${{a[1] + 14}}"` +
      ` x2="${{b[0]}}" y2="${{b[1] + 14}}" stroke="#aaa"/>`;
  }});
  flow.nodes.forEach(n => {{
    const [x, y] = pos[n.name];
    svg += `<rect x="${{x}}" y="${{y}}" width="120" height="28" rx="5"` +
      ` fill="${{n.params ? '#eaf1f8' : '#f4f4f4'}}" stroke="#7a9cc0"/>` +
      `<text class="lbl" x="${{x + 60}}" y="${{y + 12}}">${{n.name.slice(0, 18)}}</text>` +
      `<text class="lbl" x="${{x + 60}}" y="${{y + 24}}">${{n.type.slice(0, 16)}}` +
      `${{n.params ? ' · ' + n.params.toLocaleString() : ''}}</text>`;
  }});
  document.getElementById('flow').innerHTML =
    `<div class="chart" style="overflow-x:auto"><svg width="${{w}}" height="${{h}}">${{svg}}</svg></div>`;
}} else {{
  document.getElementById('flow').innerHTML = '<p class="meta">none collected</p>';
}}
function actGrid(name, ch) {{
  // one channel: rows x cols intensity grid (TrainModule activations view)
  const g = ch.grid, rows = g.length, cols = g[0].length, cell = 6;
  const w = cols * cell + 2, h = rows * cell + 16;
  let mn = Infinity, mx = -Infinity;
  g.forEach(r => r.forEach(v => {{ mn = Math.min(mn, v); mx = Math.max(mx, v); }}));
  let rects = '';
  for (let r = 0; r < rows; r++) for (let c = 0; c < cols; c++) {{
    const t = mx === mn ? 0 : (g[r][c] - mn) / (mx - mn);
    const lum = Math.round(255 * t);
    rects += `<rect x="${{c * cell}}" y="${{r * cell}}" width="${{cell}}"` +
      ` height="${{cell}}" fill="rgb(${{lum}},${{lum}},${{lum}})"/>`;
  }}
  return `<svg width="${{w}}" height="${{h}}">${{rects}}` +
    `<text class="lbl" x="${{w / 2}}" y="${{h - 3}}">${{name}}</text></svg>`;
}}
let ah = '';
for (const layer of (DATA.activations || [])) {{
  ah += `<div class="chart"><div class="meta">${{layer.name}} ` +
    `${{JSON.stringify(layer.shape)}}</div>`;
  layer.channels.forEach((ch, i) => {{ ah += actGrid('ch' + ch.index, ch); }});
  ah += '</div>';
}}
document.getElementById('acts').innerHTML = ah || '<p class="meta">none collected</p>';
const emb = DATA.embedding;
if (emb && emb.points.length) {{
  const w = 480, h = 420;
  const xs = emb.points.map(p => p[0]), ys = emb.points.map(p => p[1]);
  const x0 = Math.min(...xs), x1 = Math.max(...xs);
  const y0 = Math.min(...ys), y1 = Math.max(...ys);
  const palette = ['#c0392b','#27ae60','#2c6fad','#8e44ad','#f39c12',
                   '#16a085','#d35400','#7f8c8d','#2c3e50','#e84393'];
  let dots = '';
  emb.points.forEach((pt, i) => {{
    const sx = 10 + (w - 20) * (x1 === x0 ? 0.5 : (pt[0] - x0) / (x1 - x0));
    const sy = 10 + (h - 40) * (y1 === y0 ? 0.5 : (pt[1] - y0) / (y1 - y0));
    const lab = (emb.labels || [])[i];
    const col = lab == null ? '#2c6fad' : palette[Math.abs(lab) % palette.length];
    dots += `<circle cx="${{sx.toFixed(1)}}" cy="${{sy.toFixed(1)}}" r="2.5"` +
      ` fill="${{col}}" fill-opacity="0.7"/>`;
  }});
  document.getElementById('tsne').innerHTML =
    `<div class="chart"><svg width="${{w}}" height="${{h}}">${{dots}}` +
    `<text class="lbl" x="${{w / 2}}" y="${{h - 6}}">` +
    `${{emb.points.length}} points (kl=${{emb.kl}})</text></svg></div>`;
}} else {{
  document.getElementById('tsne').innerHTML = '<p class="meta">none collected</p>';
}}
</script>
</body></html>
"""


def collect_conv_activations(net, x, max_layers: int = 6,
                             max_channels: int = 8, max_hw: int = 14):
    """Per-conv-layer activation grids for a sample batch (the
    TrainModule activations view's data): runs net.feed_forward on
    x[:1] and average-pools each 4-D activation down to <= max_hw per
    side, keeping the first max_channels channels. Returns the
    `activations` structure render_html embeds."""
    import numpy as np

    acts = net.feed_forward(x[:1])
    layer_names = [type(l).__name__ for l in net.conf.layers]
    out = []
    for i, a in enumerate(acts[1:]):
        a = np.asarray(a)
        if a.ndim != 4:       # NHWC conv outputs only
            continue
        _, h, w, c = a.shape
        sh = max(1, -(-h // max_hw))
        sw = max(1, -(-w // max_hw))
        hp, wp = -(-h // sh) * sh, -(-w // sw) * sw
        padded = np.zeros((hp, wp, c), np.float64)
        padded[:h, :w] = a[0]
        valid = np.zeros((hp, wp, 1), np.float64)
        valid[:h, :w] = 1.0
        sums = padded.reshape(hp // sh, sh, wp // sw, sw, c).sum((1, 3))
        counts = valid.reshape(hp // sh, sh, wp // sw, sw, 1).sum((1, 3))
        pooled = sums / np.maximum(counts, 1.0)
        channels = [{"index": int(ci),
                     "grid": np.round(pooled[:, :, ci], 4).tolist()}
                    for ci in range(min(c, max_channels))]
        out.append({"name": f"{i}:{layer_names[i]}",
                    "shape": [int(h), int(w), int(c)],
                    "channels": channels})
        if len(out) >= max_layers:
            break
    return out


def collect_network_flow(net):
    """Topology data for the flow/network renderer tab (the reference
    TrainModule's model-graph view): nodes (name, type, depth, param
    count) + directed edges. Works for MultiLayerNetwork (a chain) and
    ComputationGraph (the conf DAG)."""
    import jax
    import numpy as np

    def n_params(tree):
        return sum(int(np.prod(np.asarray(a).shape))
                   for a in jax.tree_util.tree_leaves(tree))

    nodes, edges = [], []
    conf = net.conf
    if hasattr(conf, "network_inputs"):      # ComputationGraph
        depth_of = {}
        for name in conf.network_inputs:
            depth_of[name] = 0
            nodes.append({"name": name, "type": "Input", "depth": 0,
                          "params": 0})
        for gn in conf.topological_order():
            depth = max((depth_of.get(i, 0) for i in gn.inputs),
                        default=0) + 1
            depth_of[gn.name] = depth
            kind = type(gn.obj).__name__
            p = (n_params(net.params[gn.name])
                 if net.params and gn.name in net.params else 0)
            nodes.append({"name": gn.name, "type": str(kind),
                          "depth": depth, "params": p})
            for src in gn.inputs:
                edges.append([src, gn.name])
    else:                                    # MultiLayerNetwork chain
        prev = "input"
        nodes.append({"name": "input", "type": "Input", "depth": 0,
                      "params": 0})
        for i, layer in enumerate(conf.layers):
            name = f"{i}:{type(layer).__name__}"
            p = n_params(net.params[i]) if net.params else 0
            nodes.append({"name": name, "type": type(layer).__name__,
                          "depth": i + 1, "params": p})
            edges.append([prev, name])
            prev = name
    return {"nodes": nodes, "edges": edges}


def embedding_scatter(vectors, labels=None, perplexity: float = 20.0,
                      max_points: int = 2000, max_iter: int = 300,
                      seed: int = 0):
    """2-D t-SNE of an embedding matrix for the dashboard's t-SNE tab
    (ref ui/module/tsne/): subsamples to max_points, runs
    clustering.Tsne (auto tier), returns the `embedding` structure
    render_html embeds."""
    import numpy as np

    from deeplearning4j_tpu.clustering.tsne import Tsne

    vectors = np.asarray(vectors, np.float32)
    n = vectors.shape[0]
    if n < 8:        # too few points for any valid perplexity
        return {"points": [], "labels": None, "kl": None}
    if n > max_points:
        sel = np.random.default_rng(seed).choice(n, max_points,
                                                 replace=False)
        vectors = vectors[sel]
        labels = None if labels is None else np.asarray(labels)[sel]
    # keep within Tsne's n-1 >= 3*perplexity guard
    perplexity = min(perplexity, (vectors.shape[0] - 1) / 3.0)
    t = Tsne(perplexity=perplexity, max_iter=max_iter, seed=seed)
    pts = t.fit_transform(vectors)
    if labels is None:
        lab_idx = None
    else:
        # palette indices for ANY label type (ints, strings, ...)
        uniq = {v: i for i, v in enumerate(dict.fromkeys(labels))}
        lab_idx = [uniq[v] for v in labels]
    return {"points": np.round(pts, 3).tolist(),
            "labels": lab_idx,
            "kl": round(t.kl_, 4) if t.kl_ is not None else None}


def telemetry_lines(snapshot) -> list:
    """Human-readable status lines derived from a
    `MetricsRegistry.snapshot()` (or a registry itself) — the
    single-substrate replacement for the per-component stats dicts the
    dashboard used to reach into. Returns [] when the snapshot carries
    none of the relevant metrics; the self-healing, cluster, and
    serving lines are pinned by test."""
    if snapshot is None:
        return []
    if hasattr(snapshot, "snapshot"):   # a MetricsRegistry
        snapshot = snapshot.snapshot()
    c = {name: int(sum(series.values()))
         for name, series in snapshot.get("counters", {}).items()}
    hists = snapshot.get("histograms", {})

    def gauge(name):
        series = snapshot.get("gauges", {}).get(name)
        if not series:
            return None
        return list(series.values())[-1]

    lines = []
    heal = []
    if any(k.startswith("dl4j_train_guard_") for k in c):
        heal.append(
            f"guard: {c.get('dl4j_train_guard_checks_total', 0)} "
            f"checks, {c.get('dl4j_train_guard_nonfinite_total', 0)} "
            f"non-finite, {c.get('dl4j_train_guard_spikes_total', 0)} "
            f"spikes, "
            f"{c.get('dl4j_train_guard_skipped_steps_total', 0)} "
            f"skipped, "
            f"{c.get('dl4j_train_guard_rollbacks_total', 0)} rollbacks")
    if "dl4j_train_watchdog_hangs_total" in c:
        heal.append(f"watchdog: {c['dl4j_train_watchdog_hangs_total']} "
                    "hangs detected")
    if "dl4j_train_preemptions_total" in c:
        heal.append(
            f"preemptions: {c['dl4j_train_preemptions_total']}")
    if "dl4j_train_supervisor_restarts_total" in c:
        heal.append(f"supervisor restarts: "
                    f"{c['dl4j_train_supervisor_restarts_total']}")
    if "dl4j_train_data_skipped_steps_total" in c:
        heal.append(f"data-skipped steps: "
                    f"{c['dl4j_train_data_skipped_steps_total']}")
    if heal:
        lines.append("self-healing — " + " · ".join(heal))
    if ("dl4j_cluster_gang_restarts_total" in c
            or "dl4j_cluster_quarantined_workers_total" in c):
        lines.append(
            "cluster — "
            f"{c.get('dl4j_cluster_gang_restarts_total', 0)} gang "
            "restarts · "
            f"{c.get('dl4j_cluster_quarantined_workers_total', 0)} "
            "quarantined workers")
    # device-mesh sharding (engine/mesh.py): live world, reshard count,
    # checkpoint all-gather cost — the ZeRO-1 scale-out status line
    mesh_world = gauge("dl4j_mesh_world_size")
    if mesh_world is not None or "dl4j_mesh_reshard_total" in c:
        mesh = []
        if mesh_world is not None:
            mesh.append(f"world {int(mesh_world)}")
        mesh.append(f"{c.get('dl4j_mesh_reshard_total', 0)} reshards")
        ag = hists.get("dl4j_mesh_allgather_seconds")
        if ag and ag.get("count"):
            mesh.append(
                f"allgather {ag['sum'] / ag['count'] * 1e3:.1f}ms avg")
        lines.append("mesh — " + " · ".join(mesh))
    # fleet rollout controller (serving/controller.py): pool size,
    # rollout state-machine position, rollback count
    fleet_n = gauge("dl4j_fleet_replicas")
    rollout_state = gauge("dl4j_rollout_state")
    if fleet_n is not None or rollout_state is not None \
            or "dl4j_rollout_rollbacks_total" in c:
        # mirror of serving.controller.ROLLOUT_STATES (equality pinned
        # by test) — importing the serving package here would drag the
        # whole data plane into every dashboard render
        ROLLOUT_STATES = ("idle", "canary", "ramping", "rolling_back",
                          "held", "completed")
        fleet = []
        if fleet_n is not None:
            fleet.append(f"{int(fleet_n)} replicas")
        state_i = int(rollout_state) if rollout_state is not None else 0
        if 0 <= state_i < len(ROLLOUT_STATES):
            fleet.append(f"rollout {ROLLOUT_STATES[state_i]}")
        fleet.append(
            f"{c.get('dl4j_rollout_rollbacks_total', 0)} rollbacks")
        lines.append("fleet — " + " · ".join(fleet))
    if "dl4j_serving_requests_total" in c:
        serv = [f"{c['dl4j_serving_requests_total']} requests "
                f"({c.get('dl4j_serving_errors_total', 0)} errors)"]
        qd = gauge("dl4j_serving_queue_depth")
        if qd is not None:
            serv.append(f"queue depth {int(qd)}")
        if "dl4j_serving_batches_total" in c:
            serv.append(f"{c['dl4j_serving_batches_total']} batches")
        occ = hists.get("dl4j_serving_batch_occupancy")
        if occ and occ.get("p50") is not None:
            serv.append(f"occupancy p50 {occ['p50']:g}")
        lines.append("serving — " + " · ".join(serv))
    # continuous-batching decode engine (serving/continuous.py):
    # resident generation streams, token throughput, chaos evictions
    decode_slots = gauge("dl4j_decode_active_slots")
    if decode_slots is not None or "dl4j_decode_tokens_total" in c:
        dec = [f"{int(decode_slots or 0)} slots"]
        rate = gauge("dl4j_decode_tokens_per_s")
        if rate is not None:
            dec.append(f"{rate:.1f} tok/s")
        dec.append(f"{c.get('dl4j_decode_tokens_total', 0)} tokens")
        if "dl4j_decode_slot_evictions_total" in c:
            dec.append(f"{c['dl4j_decode_slot_evictions_total']} "
                       "evictions")
        # paged KV virtual memory: prefix-hit rate (pages served from
        # the trie vs pages computed by chunk prefill) + pool headroom
        hits = c.get("dl4j_decode_prefix_hits_total", 0)
        chunks = c.get("dl4j_decode_prefill_chunks_total", 0)
        if hits + chunks:
            rate = 100.0 * hits / (hits + chunks)
            dec.append(f"prefix hit {rate:.0f}%")
        pages_free = gauge("dl4j_decode_pages_free")
        if pages_free is not None:
            dec.append(f"{int(pages_free)} pages free")
        lines.append("decode — " + " · ".join(dec))

    # per-request latency attribution (TTFT / inter-token / queue-wait
    # histograms, labeled by tenant): the worst label set is shown —
    # an SLO eye wants the slowest tenant, not the average
    def hquant(name, q):
        worst = None
        for key, h in hists.items():
            if key != name and not key.startswith(name + "{"):
                continue
            v = h.get(q)
            if v is not None and (worst is None or v > worst):
                worst = v
        return worst

    ttft99 = hquant("dl4j_decode_ttft_seconds", "p99")
    itl99 = hquant("dl4j_decode_itl_seconds", "p99")
    if ttft99 is not None or itl99 is not None:
        lat = []
        if ttft99 is not None:
            ttft50 = hquant("dl4j_decode_ttft_seconds", "p50")
            lat.append(f"ttft p50 {(ttft50 or 0) * 1e3:.1f}ms "
                       f"p99 {ttft99 * 1e3:.1f}ms")
        if itl99 is not None:
            itl50 = hquant("dl4j_decode_itl_seconds", "p50")
            lat.append(f"itl p50 {(itl50 or 0) * 1e3:.1f}ms "
                       f"p99 {itl99 * 1e3:.1f}ms")
        qw99 = hquant("dl4j_decode_queue_wait_seconds", "p99")
        if qw99 is not None:
            lat.append(f"queue wait p99 {qw99 * 1e3:.1f}ms")
        lines.append("decode latency — " + " · ".join(lat))
    # decode durability (quarantine / migration / watchdog restart /
    # deadline sweep) — shown once any of its counters has moved
    if any(k in c for k in ("dl4j_decode_slot_quarantines_total",
                            "dl4j_decode_migrations_total",
                            "dl4j_decode_engine_restarts_total",
                            "dl4j_decode_deadline_expired_total")):
        lines.append(
            "decode resilience — "
            f"{c.get('dl4j_decode_slot_quarantines_total', 0)} "
            "quarantines · "
            f"{c.get('dl4j_decode_migrations_total', 0)} migrations · "
            f"{c.get('dl4j_decode_engine_restarts_total', 0)} "
            "engine restarts · "
            f"{c.get('dl4j_decode_deadline_expired_total', 0)} "
            "deadline expiries")
    # durable serving journal (serving/journal.py): live WAL occupancy,
    # cold-restart recoveries, torn tails truncated
    journal_live = gauge("dl4j_journal_live")
    if journal_live is not None or any(k in c for k in (
            "dl4j_journal_records_total",
            "dl4j_journal_recovered_requests_total",
            "dl4j_journal_torn_tails_total")):
        lines.append(
            "journal — "
            f"{int(journal_live or 0)} live · "
            f"{c.get('dl4j_journal_recovered_requests_total', 0)} "
            "recovered · "
            f"{c.get('dl4j_journal_torn_tails_total', 0)} torn tails")
    # performance introspection (observability/perf.py): cost-model
    # MFU gauge, top phases by attributed share, recompile count
    perf = []
    mfu = gauge("dl4j_perf_mfu")
    if mfu is not None:
        perf.append(f"MFU {mfu:.3f}")
    phase_prefix = "dl4j_train_phase_seconds{phase="
    shares = {}
    for key, h in hists.items():
        if key.startswith(phase_prefix):
            phase = key[len(phase_prefix):].strip('"}')
            shares[phase] = shares.get(phase, 0.0) + float(h["sum"])
    total = sum(shares.values())
    if total > 0:
        top = sorted(shares.items(), key=lambda kv: -kv[1])[:2]
        perf.append("phases " + ", ".join(
            f"{p} {s / total:.0%}" for p, s in top))
    if "dl4j_jit_compiles_total" in c:
        perf.append(f"{c['dl4j_jit_compiles_total']} recompiles")
    if perf:
        lines.append("perf — " + " · ".join(perf))
    return lines


def render_html(storage: StatsStorage, session_id: Optional[str] = None,
                path: Optional[str] = None, activations=None,
                embedding=None, flow=None, telemetry=None) -> str:
    """Render a self-contained HTML report; write to `path` if given.
    Defaults to the storage's only (or first) session. `activations`
    (collect_conv_activations), `embedding` (embedding_scatter) and
    `flow` (collect_network_flow) fill the conv-activation, t-SNE and
    network-graph tabs; `telemetry` (a MetricsRegistry — typically
    `observability.get_registry()` — or its `.snapshot()`) renders the
    self-healing / cluster / serving status lines from the ONE metrics
    substrate instead of per-component stats dicts, and embeds the raw
    snapshot as DATA.telemetry."""
    sessions = storage.session_ids()
    if not sessions:
        raise ValueError("storage has no sessions")
    if session_id is None:
        session_id = sessions[0]
    if telemetry is not None and hasattr(telemetry, "snapshot"):
        telemetry = telemetry.snapshot()
    reports = storage.reports(session_id)
    latest = reports[-1] if reports else None
    fmt = lambda v, nd=1: "–" if v is None else f"{v:.{nd}f}"
    page = _PAGE.format(
        session=html.escape(session_id),
        n=len(reports),
        final_score="–" if latest is None or latest.score is None
        else f"{latest.score:.4f}",
        sps=fmt(latest.samples_per_sec if latest else None),
        etl=fmt(latest.etl_ms if latest else None, 2),
        dev_mem=fmt((latest.mem or {}).get("device_in_use_mb")
                    if latest else None),
        data=json.dumps({"reports": [r.to_dict() for r in reports],
                         "activations": activations,
                         "embedding": embedding,
                         "flow": flow,
                         "telemetry": telemetry,
                         "telemetry_lines": telemetry_lines(telemetry)}),
    )
    if path:
        with open(path, "w") as f:
            f.write(page)
    return page


class UIServer:
    """Minimal HTTP dashboard (ref: UIServer.getInstance().attach(storage),
    ui/api/UIServer.java:24,42). Serves the rendered report at / and
    per-session at /session/<id>; re-renders per request."""

    def __init__(self, port: int = 9000, host: str = "127.0.0.1"):
        self.host = host
        self.port = port
        self._storage: Optional[StatsStorage] = None
        self._httpd = None
        self._thread = None

    def attach(self, storage: StatsStorage) -> "UIServer":
        self._storage = storage
        return self

    def start(self) -> "UIServer":
        import http.server

        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                # remote stats receiver (ref RemoteReceiverModule):
                # RemoteStatsStorageRouter POSTs StatsReport JSON here
                from deeplearning4j_tpu.stats.report import StatsReport

                try:
                    if self.path.rstrip("/") != "/remote" \
                            or server._storage is None:
                        raise ValueError(f"no receiver at {self.path}")
                    n = int(self.headers.get("Content-Length", 0))
                    report = StatsReport.from_json(
                        self.rfile.read(n).decode())
                    server._storage.put_report(report)
                    body = b"{}"
                    self.send_response(200)
                except Exception as e:
                    body = str(e).encode()
                    self.send_response(400)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    if server._storage is None:
                        raise ValueError("no storage attached")
                    sid = None
                    if self.path.startswith("/session/"):
                        sid = self.path.split("/session/", 1)[1] or None
                    # live dashboard auto-attaches the process-global
                    # registry: self-healing / cluster / serving lines
                    # render from whatever this process has emitted
                    from deeplearning4j_tpu.observability import (
                        get_registry,
                    )

                    body = render_html(server._storage, sid,
                                       telemetry=get_registry()).encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/html; charset=utf-8")
                except Exception as e:  # pragma: no cover - error path
                    body = f"<html><body>{html.escape(str(e))}" \
                           f"</body></html>".encode()
                    self.send_response(503)
                    self.send_header("Content-Type",
                                     "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet
                pass

        import socketserver

        class _Server(socketserver.ThreadingMixIn, http.server.HTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._httpd = _Server((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="UIServer-http")
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
