"""Training dashboard: static HTML export + minimal HTTP server.

Parity: the reference's Play UI train module (ui/play/PlayUIServer.java,
ui/module/train/TrainModule.java — score chart, mean-magnitude
timelines, histograms, system tab). TPU-native difference: a
dependency-free self-contained HTML file (inline SVG charts, data
embedded as JSON) — no Play framework, no websockets; the UIServer
re-renders on each GET, which at listener frequencies is milliseconds.
"""

from __future__ import annotations

import html
import json
import threading
from typing import Optional

from deeplearning4j_tpu.stats.storage import StatsStorage

_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>deeplearning4j_tpu — training</title>
<style>
 body {{ font-family: system-ui, sans-serif; margin: 24px; color: #222; }}
 h1 {{ font-size: 20px; }} h2 {{ font-size: 16px; margin-top: 28px; }}
 .meta {{ color: #666; font-size: 13px; }}
 .row {{ display: flex; flex-wrap: wrap; gap: 24px; }}
 .chart {{ border: 1px solid #ddd; border-radius: 6px; padding: 8px; }}
 .lbl {{ font-size: 12px; color: #555; text-anchor: middle; }}
</style></head>
<body>
<h1>Training session <code>{session}</code></h1>
<p class="meta">{n} reports · final score {final_score} ·
 {sps} samples/sec · ETL {etl} ms · device mem {dev_mem} MB</p>
<div id="charts" class="row"></div>
<h2>Parameter mean magnitudes (log10)</h2>
<div id="pmm" class="row"></div>
<h2>Update mean magnitudes (log10)</h2>
<div id="umm" class="row"></div>
<h2>Latest parameter histograms</h2>
<div id="hists" class="row"></div>
<script>
const DATA = {data};
function svgLine(pts, w, h, color) {{
  if (pts.length === 0) return '';
  const xs = pts.map(p => p[0]), ys = pts.map(p => p[1]);
  const x0 = Math.min(...xs), x1 = Math.max(...xs);
  const y0 = Math.min(...ys), y1 = Math.max(...ys);
  const sx = v => 40 + (w - 50) * (x1 === x0 ? 0 : (v - x0) / (x1 - x0));
  const sy = v => (h - 20) - (h - 35) * (y1 === y0 ? 0.5 : (v - y0) / (y1 - y0));
  const d = pts.map((p, i) => (i ? 'L' : 'M') + sx(p[0]).toFixed(1) + ' ' + sy(p[1]).toFixed(1)).join(' ');
  return `<path d="${{d}}" fill="none" stroke="${{color}}" stroke-width="1.5"/>` +
    `<text class="lbl" x="8" y="18" text-anchor="start">${{y1.toPrecision(4)}}</text>` +
    `<text class="lbl" x="8" y="${{h - 22}}" text-anchor="start">${{y0.toPrecision(4)}}</text>`;
}}
function chart(title, pts, color) {{
  const w = 420, h = 180;
  return `<div class="chart"><svg width="${{w}}" height="${{h}}">` +
    svgLine(pts, w, h, color) +
    `<text class="lbl" x="${{w / 2}}" y="${{h - 4}}">${{title}}</text></svg></div>`;
}}
function bars(title, hist) {{
  const w = 320, h = 140, n = hist.counts.length;
  const m = Math.max(...hist.counts, 1);
  let rects = '';
  for (let i = 0; i < n; i++) {{
    const bh = (h - 30) * hist.counts[i] / m;
    rects += `<rect x="${{5 + i * (w - 10) / n}}" y="${{h - 22 - bh}}"` +
      ` width="${{(w - 10) / n - 1}}" height="${{bh}}" fill="#4a7fb5"/>`;
  }}
  return `<div class="chart"><svg width="${{w}}" height="${{h}}">` + rects +
    `<text class="lbl" x="${{w / 2}}" y="${{h - 8}}">${{title}}` +
    ` [${{hist.min.toPrecision(3)}}, ${{hist.max.toPrecision(3)}}]</text></svg></div>`;
}}
const reps = DATA.reports;
const iters = reps.map(r => r.iteration);
const sc = reps.filter(r => r.score != null).map(r => [r.iteration, r.score]);
document.getElementById('charts').innerHTML =
  chart('score vs iteration', sc, '#c0392b') +
  chart('samples/sec', reps.filter(r => r.samples_per_sec != null)
        .map(r => [r.iteration, r.samples_per_sec]), '#27ae60') +
  chart('ETL ms', reps.filter(r => r.etl_ms != null)
        .map(r => [r.iteration, r.etl_ms]), '#8e44ad');
function mmCharts(el, key) {{
  const names = new Set();
  reps.forEach(r => Object.keys(r[key] || {{}}).forEach(k => names.add(k)));
  let htmlStr = '';
  for (const name of Array.from(names).slice(0, 24)) {{
    const pts = reps.filter(r => (r[key] || {{}})[name] > 0)
      .map(r => [r.iteration, Math.log10(r[key][name])]);
    htmlStr += chart(name, pts, '#2c6fad');
  }}
  document.getElementById(el).innerHTML = htmlStr || '<p class="meta">none collected</p>';
}}
mmCharts('pmm', 'param_mean_magnitudes');
mmCharts('umm', 'update_mean_magnitudes');
const last = reps[reps.length - 1] || {{}};
let hh = '';
for (const [name, hist] of Object.entries(last.param_histograms || {{}}).slice(0, 24))
  hh += bars(name, hist);
document.getElementById('hists').innerHTML = hh || '<p class="meta">none collected</p>';
</script>
</body></html>
"""


def render_html(storage: StatsStorage, session_id: Optional[str] = None,
                path: Optional[str] = None) -> str:
    """Render a self-contained HTML report; write to `path` if given.
    Defaults to the storage's only (or first) session."""
    sessions = storage.session_ids()
    if not sessions:
        raise ValueError("storage has no sessions")
    if session_id is None:
        session_id = sessions[0]
    reports = storage.reports(session_id)
    latest = reports[-1] if reports else None
    fmt = lambda v, nd=1: "–" if v is None else f"{v:.{nd}f}"
    page = _PAGE.format(
        session=html.escape(session_id),
        n=len(reports),
        final_score="–" if latest is None or latest.score is None
        else f"{latest.score:.4f}",
        sps=fmt(latest.samples_per_sec if latest else None),
        etl=fmt(latest.etl_ms if latest else None, 2),
        dev_mem=fmt((latest.mem or {}).get("device_in_use_mb")
                    if latest else None),
        data=json.dumps({"reports": [r.to_dict() for r in reports]}),
    )
    if path:
        with open(path, "w") as f:
            f.write(page)
    return page


class UIServer:
    """Minimal HTTP dashboard (ref: UIServer.getInstance().attach(storage),
    ui/api/UIServer.java:24,42). Serves the rendered report at / and
    per-session at /session/<id>; re-renders per request."""

    def __init__(self, port: int = 9000, host: str = "127.0.0.1"):
        self.host = host
        self.port = port
        self._storage: Optional[StatsStorage] = None
        self._httpd = None
        self._thread = None

    def attach(self, storage: StatsStorage) -> "UIServer":
        self._storage = storage
        return self

    def start(self) -> "UIServer":
        import http.server

        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                # remote stats receiver (ref RemoteReceiverModule):
                # RemoteStatsStorageRouter POSTs StatsReport JSON here
                from deeplearning4j_tpu.stats.report import StatsReport

                try:
                    if self.path.rstrip("/") != "/remote" \
                            or server._storage is None:
                        raise ValueError(f"no receiver at {self.path}")
                    n = int(self.headers.get("Content-Length", 0))
                    report = StatsReport.from_json(
                        self.rfile.read(n).decode())
                    server._storage.put_report(report)
                    body = b"{}"
                    self.send_response(200)
                except Exception as e:
                    body = str(e).encode()
                    self.send_response(400)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    if server._storage is None:
                        raise ValueError("no storage attached")
                    sid = None
                    if self.path.startswith("/session/"):
                        sid = self.path.split("/session/", 1)[1] or None
                    body = render_html(server._storage, sid).encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/html; charset=utf-8")
                except Exception as e:  # pragma: no cover - error path
                    body = f"<html><body>{html.escape(str(e))}" \
                           f"</body></html>".encode()
                    self.send_response(503)
                    self.send_header("Content-Type",
                                     "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet
                pass

        import socketserver

        class _Server(socketserver.ThreadingMixIn, http.server.HTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._httpd = _Server((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
