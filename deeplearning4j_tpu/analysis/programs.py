"""The representative compiled-program set `dl4j-analyze --programs`
lints.

One small instance of every registered compiled-program family, built
the same way production builds them (same cache paths, same policy
registration) but at CPU-lintable dims:

  engine_single / _group_k4   StepProgram on a bf16 mixed-precision MLP
  engine_graph                StepProgram on a ComputationGraph (the
                              flat-chain train program)
  engine_tbptt                the train_c program with donated carries
  engine_zero1                the ZeRO-1 mesh-sharded step over the
                              CPU device mesh, example args staged
                              sharded — the prog-unsharded-optimizer-
                              state record (the CLI forces 8 virtual
                              CPU devices so the dp axis is real)
  serving_predict / buckets   ParallelInference warmup + a short driven
                              load, so bucket fill is MEASURED
  decode_step / decode_prefill  the continuous-batching decode engine
                              (engine/decode_program.py): the shared
                              [max_slots] decode step and one pow2
                              prefill bucket, KV-cache donation
                              DECLARED so prog-unhonored-donation
                              verifies no silent per-token copy of the
                              [n_layers, 2, max_slots, max_ctx, ...]
                              buffer
  clustering_kmeans_lloyd     the donated Lloyd iteration
  clustering_tsne_step        the donated embedding step (the program
                              whose dropped donation the first audit
                              run caught — PERF.md)
  bench_flagship_k_steps      the bench's ResNet50 k-step program at
                              reduced dims, lower-only (XLA-compiling
                              it takes minutes on CPU; the dtype and
                              alias-map rules only need the lowering)
  graft_entry_forward         the published __graft_entry__ forward,
                              pinned to the flagship bf16 policy (the
                              fp32-default the first audit run caught)

Everything here imports jax — it is loaded lazily by the runner ONLY
in `--programs` mode, so the default AST-only CLI keeps its zero-
dependency contract. The CLI pins JAX_PLATFORMS=cpu before anything
imports jax; the whole set builds + lints in well under 60s on CPU.
"""

from __future__ import annotations

import importlib.util
import os
import sys
from pathlib import Path
from typing import List

from deeplearning4j_tpu.analysis.program_lint import ProgramRecord

_ROOT = Path(__file__).resolve().parents[2]


def _engine_records() -> List[ProgramRecord]:
    import jax.numpy as jnp

    from deeplearning4j_tpu import (
        MultiLayerNetwork,
        NeuralNetConfiguration,
    )
    from deeplearning4j_tpu.engine import StepProgram
    from deeplearning4j_tpu.nn.conf import InputType
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.layers import (
        LSTM,
        DenseLayer,
        OutputLayer,
        RnnOutputLayer,
    )

    records: List[ProgramRecord] = []

    # single step + k-group on the bf16 mixed-precision MLP
    conf = (NeuralNetConfiguration.Builder().seed(7).updater("adam")
            .learning_rate(1e-3).activation("relu")
            .weight_init("xavier").list()
            .layer(DenseLayer(n_out=32))
            .layer(OutputLayer(n_out=8, loss="mcxent"))
            .set_input_type(InputType.feed_forward(16)).build())
    net = MultiLayerNetwork(conf, compute_dtype="bfloat16").init()
    records += StepProgram(net).lint_records(
        jnp.zeros((8, 16), jnp.float32), jnp.zeros((8, 8), jnp.float32),
        k=4)

    # ComputationGraph variant (flat-chain train program)
    gconf = (NeuralNetConfiguration.Builder().seed(5).updater("adam")
             .learning_rate(1e-3).activation("relu")
             .weight_init("xavier").graph_builder()
             .add_inputs("in")
             .add_layer("d1", DenseLayer(n_out=16), "in")
             .add_layer("out", OutputLayer(n_out=4, loss="mcxent"),
                        "d1")
             .set_outputs("out")
             .set_input_types(**{"in": InputType.feed_forward(8)})
             .build())
    g = ComputationGraph(gconf, compute_dtype="bfloat16").init()
    records += StepProgram(g).lint_records(
        jnp.zeros((8, 8), jnp.float32), jnp.zeros((8, 4), jnp.float32))

    # truncated-BPTT LSTM (the train_c program with donated carries)
    rconf = (NeuralNetConfiguration.Builder().seed(3).updater("adam")
             .learning_rate(1e-3).weight_init("xavier").list()
             .layer(LSTM(n_out=16))
             .layer(RnnOutputLayer(n_out=4, loss="mcxent"))
             .set_input_type(InputType.recurrent(8))
             .backprop_type("truncated_bptt")
             .t_bptt_forward_length(4).t_bptt_backward_length(4)
             .build())
    rnet = MultiLayerNetwork(rconf, compute_dtype="bfloat16").init()
    records += StepProgram(rnet).lint_records(
        jnp.zeros((2, 4, 8), jnp.float32),
        jnp.zeros((2, 4, 4), jnp.float32))
    return records


def _serving_records() -> List[ProgramRecord]:
    import numpy as np

    from deeplearning4j_tpu import (
        MultiLayerNetwork,
        NeuralNetConfiguration,
    )
    from deeplearning4j_tpu.nn.conf import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.parallel.inference import ParallelInference

    conf = (NeuralNetConfiguration.Builder().seed(11).updater("sgd")
            .learning_rate(0.05).activation("tanh")
            .weight_init("xavier").list()
            .layer(DenseLayer(n_out=32))
            .layer(OutputLayer(n_out=8, loss="mcxent"))
            .set_input_type(InputType.feed_forward(16)).build())
    net = MultiLayerNetwork(conf, compute_dtype="bfloat16").init()
    pi = ParallelInference(net, batch_limit=8, queue_limit=16,
                           max_wait_ms=1.0, warmup=True,
                           pipeline_depth=0)
    try:
        # drive a short load so bucket fill is measured, not assumed
        for rows in (8, 8, 4):
            pi.output(np.zeros((rows, 16), np.float32), timeout_s=60.0)
        return pi.lint_records()
    finally:
        pi.shutdown()


def _clustering_records() -> List[ProgramRecord]:
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.clustering import kmeans, tsne

    rng = np.random.default_rng(0)
    pts = jnp.asarray(rng.normal(size=(32, 4)).astype(np.float32))
    records = [ProgramRecord(
        name="clustering_kmeans_lloyd", fn=kmeans._lloyd_step,
        example_args=(pts, pts[:4]),
        example_kwargs={"metric": "euclidean"},
        precision_policy="f32",
        source="deeplearning4j_tpu/clustering/kmeans.py")]

    n, k, blk, c = 6, 3, 4, 2
    n_pad = -(-n // blk) * blk      # 8: pad-mismatch donation case
    y = jnp.zeros((n_pad, c), jnp.float32)
    records.append(ProgramRecord(
        name="clustering_tsne_step", fn=tsne._chunked_step,
        example_args=(y, jnp.zeros_like(y),
                      jnp.zeros((n, k), jnp.int32),
                      jnp.full((n, k), 1e-3, jnp.float32),
                      jnp.zeros((n, k), bool),
                      jnp.float32(4.0), jnp.float32(0.5),
                      jnp.float32(100.0)),
        example_kwargs={"row_block": blk, "n_real": n},
        precision_policy="f32",
        source="deeplearning4j_tpu/clustering/tsne.py"))
    return records


def _flagship_records() -> List[ProgramRecord]:
    if str(_ROOT) not in sys.path:
        sys.path.insert(0, str(_ROOT))
    spec = importlib.util.spec_from_file_location(
        "dl4j_bench", _ROOT / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    jit_k, args, _, _ = bench.make_flagship_program(
        batch=2, hw=32, n_classes=8, unroll=2)
    records = [ProgramRecord(
        name="bench_flagship_k_steps", fn=jit_k, example_args=args,
        precision_policy="bf16", compile=False, source="bench.py",
        consumed_outputs=(0, 1, 2, 3))]

    from __graft_entry__ import entry

    fwd, fargs = entry(hw=32, n_classes=8)
    records.append(ProgramRecord(
        name="graft_entry_forward", fn=fwd, example_args=fargs,
        precision_policy="bf16", compile=False,
        source="__graft_entry__.py"))
    return records


def build_default_records() -> List[ProgramRecord]:
    """Build the whole representative set. Pins JAX_PLATFORMS=cpu when
    nothing chose a platform yet — the lint must behave identically on
    a TPU host and in CI."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    records: List[ProgramRecord] = []
    records += _engine_records()
    records += _mesh_records()
    records += _serving_records()
    records += _decode_records()
    records += _clustering_records()
    records += _flagship_records()
    return records


def _decode_records() -> List[ProgramRecord]:
    """The continuous-batching decode programs at CPU-lintable dims —
    paged decode step, chunked prefill, and the copy-on-write page
    copy — built through the same JitCache paths DecodeEngine runs
    (policy registered, donation of the physical page pool DECLARED so
    prog-unhonored-donation checks the executable alias map)."""
    from deeplearning4j_tpu.engine.decode_program import DecodeProgram
    from deeplearning4j_tpu.zoo.decoder import CausalTransformer

    model = CausalTransformer(vocab_size=64, d_model=32, n_heads=4,
                              n_layers=2, max_ctx=64, seed=17).init()
    prog = DecodeProgram(model, max_slots=4, page_size=16)
    return prog.lint_records()


def _mesh_records() -> List[ProgramRecord]:
    """The ZeRO-1 mesh-sharded StepProgram (engine/sharding.py) over
    the CPU device mesh, with example args staged exactly as the live
    path stages them (optimizer state SHARDED) — the record
    `prog-unsharded-optimizer-state` verifies. Empty when the platform
    exposes a single device (the rule is vacuous without a dp axis;
    the CLI forces 8 virtual CPU devices)."""
    import jax
    import jax.numpy as jnp

    if len(jax.devices()) < 2:
        return []

    from deeplearning4j_tpu import (
        MultiLayerNetwork,
        NeuralNetConfiguration,
    )
    from deeplearning4j_tpu.engine import MeshManager, StepProgram
    from deeplearning4j_tpu.nn.conf import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer

    n_dev = len(jax.devices())
    conf = (NeuralNetConfiguration.Builder().seed(13).updater("adam")
            .learning_rate(1e-3).activation("relu")
            .weight_init("xavier").list()
            .layer(DenseLayer(n_out=4 * n_dev))
            .layer(OutputLayer(n_out=n_dev, loss="mcxent"))
            .set_input_type(InputType.feed_forward(2 * n_dev))
            .build())
    net = MultiLayerNetwork(conf).init()
    mgr = MeshManager()
    net.params = mgr.replicate_tree(net.params)
    net.updater_states = mgr.shard_tree(net.updater_states)
    net.states = mgr.replicate_tree(net.states)
    prog = StepProgram(net).attach_mesh(mgr)
    return [prog.lint_record_zero1(
        jnp.zeros((2 * n_dev, 2 * n_dev), jnp.float32),
        jnp.zeros((2 * n_dev, n_dev), jnp.float32))]
