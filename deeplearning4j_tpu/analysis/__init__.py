"""dl4j-analyze: static invariant checker + runtime sanitizers.

Three static passes (AST-only — analyzed code is parsed, never
imported), a compiled-program pass (jaxpr/HLO — the one pass that
imports jax, only when invoked), plus an opt-in runtime lock-order
sanitizer:

  jit          recompile hygiene on the step/serving hot paths
  concurrency  thread/lock discipline + the thread/lock catalog
  conformance  fault-point / metric / program-rule registries,
               swallow discipline, test coverage of registered names
  programs     compiled-program lint below the AST: declared precision
               policy vs jaxpr dtypes, donation vs the executable's
               alias map, transpose churn, hidden host transfers,
               dead outputs, serving bucket fill (program_lint.py,
               `--programs` mode)

Entry points:

  python tools/analyze.py            # full run vs the baseline
  python tools/analyze.py --diff     # changed files only
  python tools/analyze.py --rules    # the rule catalog
  python tools/analyze.py --programs # compiled-program lint (jax, CPU)
  DL4J_TPU_SANITIZE=locks pytest …   # runtime lock-order sanitizer

Module scope stays import-light everywhere (program_lint included) so
the default analyzer still runs in a bare interpreter without jax.
"""

from deeplearning4j_tpu.analysis.findings import (  # noqa: F401
    RULES,
    Baseline,
    Finding,
    Rule,
)
from deeplearning4j_tpu.analysis.program_lint import (  # noqa: F401
    REGISTERED_PROGRAM_RULES,
    ProgramRecord,
    Thresholds,
)
from deeplearning4j_tpu.analysis.runner import (  # noqa: F401
    AnalysisResult,
    analyze,
    main,
)
from deeplearning4j_tpu.analysis.sanitizers import (  # noqa: F401
    LockOrderSanitizer,
    active_sanitizer,
    install_from_env,
)

__all__ = [
    "RULES", "Rule", "Finding", "Baseline", "AnalysisResult",
    "analyze", "main", "LockOrderSanitizer", "active_sanitizer",
    "install_from_env", "ProgramRecord", "Thresholds",
    "REGISTERED_PROGRAM_RULES",
]
