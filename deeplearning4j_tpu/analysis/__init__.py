"""dl4j-analyze: static invariant checker + runtime sanitizers.

Three static passes (AST-only — analyzed code is parsed, never
imported) plus an opt-in runtime lock-order sanitizer:

  jit          recompile hygiene on the step/serving hot paths
  concurrency  thread/lock discipline + the thread/lock catalog
  conformance  fault-point / metric registries, swallow discipline,
               test coverage of registered names

Entry points:

  python tools/analyze.py            # full run vs the baseline
  python tools/analyze.py --diff     # changed files only
  python tools/analyze.py --rules    # the rule catalog
  DL4J_TPU_SANITIZE=locks pytest …   # runtime lock-order sanitizer

This package deliberately avoids importing jax or any sibling
subsystem so the analyzer runs in a bare interpreter.
"""

from deeplearning4j_tpu.analysis.findings import (  # noqa: F401
    RULES,
    Baseline,
    Finding,
    Rule,
)
from deeplearning4j_tpu.analysis.runner import (  # noqa: F401
    AnalysisResult,
    analyze,
    main,
)
from deeplearning4j_tpu.analysis.sanitizers import (  # noqa: F401
    LockOrderSanitizer,
    active_sanitizer,
    install_from_env,
)

__all__ = [
    "RULES", "Rule", "Finding", "Baseline", "AnalysisResult",
    "analyze", "main", "LockOrderSanitizer", "active_sanitizer",
    "install_from_env",
]
