"""Pass 2 — static concurrency lint + thread/lock catalog.

Catalogs every `threading.Thread` / `Lock` / `RLock` / `Condition`
construction in the package and enforces the production-thread
discipline the shell's seven subsystems converged on:

  thr-unnamed-thread       every thread is named (hang forensics)
  thr-non-daemon-thread    every background thread is a daemon
  thr-orphan-thread        every thread has a join-or-ledger shutdown
  thr-blocking-under-lock  no blocking I/O or metric/fault emission
                           while holding a registry lock

The runtime half of this pass is `sanitizers.LockOrderSanitizer`
(DL4J_TPU_SANITIZE=locks): the static rules keep the thread population
legible; the sanitizer proves the lock *orders* those threads use stay
acyclic.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from deeplearning4j_tpu.analysis.findings import Finding, pragma_allows
from deeplearning4j_tpu.analysis.source import (
    SourceFile,
    call_name,
    dotted,
)

# callables that block (or can block unboundedly) — forbidden while a
# registry lock is held; file/io-named locks are exempt (their entire
# job is serializing the blocking resource itself)
BLOCKING_CALLS = {"sleep", "open", "fsync", "urlopen", "join",
                  "wait_for", "check_output", "run", "Popen",
                  "connect", "recv", "send", "sendall", "accept"}
EMISSION_HELPERS = {"count", "observe", "set_gauge", "gauge_fn",
                    "count_observe", "fire", "_fire"}
LOCKISH = re.compile(r"lock", re.IGNORECASE)
FILE_LOCK = re.compile(r"file|io", re.IGNORECASE)


@dataclass
class ThreadSite:
    file: str
    line: int
    named: bool
    name_literal: Optional[str]
    daemon: bool
    bound_to: Optional[str]
    joined: bool
    symbol: str


@dataclass
class LockSite:
    file: str
    line: int
    kind: str                 # Lock | RLock | Condition | Semaphore
    bound_to: Optional[str]
    symbol: str


@dataclass
class Catalog:
    threads: List[ThreadSite] = field(default_factory=list)
    locks: List[LockSite] = field(default_factory=list)


def _obs_aliases(sf: SourceFile) -> Set[str]:
    """Names under which this module can emit metrics/faults: module
    aliases of observability.metrics / resilience.faults plus directly
    imported helper names."""
    aliases: Set[str] = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ImportFrom) and node.module:
            if "observability" in node.module or "faults" in node.module \
                    or "resilience" in node.module:
                for a in node.names:
                    nm = a.asname or a.name
                    if a.name in ("metrics", "faults") \
                            or nm in EMISSION_HELPERS \
                            or a.name in EMISSION_HELPERS:
                        aliases.add(nm)
    return aliases


def run(sources: List[SourceFile]) -> List[Finding]:
    findings, _ = run_with_catalog(sources)
    return findings


def run_with_catalog(sources: List[SourceFile]):
    findings: List[Finding] = []
    catalog = Catalog()
    for sf in sources:
        parents: Dict[int, ast.AST] = {}
        for node in ast.walk(sf.tree):
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node
        emit_aliases = _obs_aliases(sf)
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call):
                d = dotted(node.func)
                if d in ("threading.Thread", "Thread"):
                    findings.extend(
                        _check_thread(sf, node, parents, catalog))
                elif d in ("threading.Lock", "threading.RLock",
                           "threading.Condition", "threading.Semaphore",
                           "threading.BoundedSemaphore"):
                    catalog.locks.append(LockSite(
                        sf.rel, node.lineno, d.split(".")[-1],
                        _bound_name(parents.get(id(node))),
                        sf.qualname_of(node)))
            elif isinstance(node, ast.With):
                findings.extend(
                    _check_with_lock(sf, node, emit_aliases))
    return findings, catalog


def _bound_name(parent) -> Optional[str]:
    if isinstance(parent, ast.Assign):
        t = parent.targets[0]
        if isinstance(t, ast.Name):
            return t.id
        if isinstance(t, ast.Attribute):
            return t.attr
    return None


def _check_thread(sf: SourceFile, node: ast.Call, parents,
                  catalog: Catalog) -> List[Finding]:
    findings: List[Finding] = []
    kwargs = {kw.arg for kw in node.keywords if kw.arg}
    name_lit = None
    daemon = False
    for kw in node.keywords:
        if kw.arg == "name" and isinstance(kw.value, ast.Constant):
            name_lit = str(kw.value.value)
        if kw.arg == "daemon":
            daemon = not (isinstance(kw.value, ast.Constant)
                          and kw.value.value is False)
    symbol = sf.qualname_of(node)
    bound = _bound_name(parents.get(id(node)))

    joined = False
    if bound is not None:
        # join-or-ledger: `X.join(` on the bound name, an aliased join
        # (`t = self.X` ... `t.join(`), or membership in a joined /
        # drained ledger list (`.append(X)` plus any `.join(` in the
        # module)
        pat_direct = re.compile(re.escape(bound) + r"\.join\(")
        pat_alias = re.compile(r"=\s*self\." + re.escape(bound) + r"\b")
        pat_append = re.compile(r"\.append\(\s*" + re.escape(bound)
                                + r"\s*\)")
        has_join = ".join(" in sf.text
        joined = bool(pat_direct.search(sf.text)
                      or (pat_alias.search(sf.text) and has_join)
                      or (pat_append.search(sf.text) and has_join))

    catalog.threads.append(ThreadSite(
        sf.rel, node.lineno, "name" in kwargs, name_lit, daemon,
        bound, joined, symbol))

    if "name" not in kwargs \
            and not pragma_allows(sf.allow, node.lineno,
                                  "thr-unnamed-thread"):
        findings.append(Finding(
            "thr-unnamed-thread", sf.rel, node.lineno,
            "threading.Thread(...) without name= — anonymous threads "
            "make faulthandler/watchdog dumps unreadable",
            symbol=symbol))
    if not daemon \
            and not pragma_allows(sf.allow, node.lineno,
                                  "thr-non-daemon-thread"):
        findings.append(Finding(
            "thr-non-daemon-thread", sf.rel, node.lineno,
            "threading.Thread(...) without daemon=True — a background "
            "thread that outlives a crash turns it into a hang",
            symbol=symbol))
    if (bound is None or not joined) \
            and not pragma_allows(sf.allow, node.lineno,
                                  "thr-orphan-thread"):
        how = ("constructed fire-and-forget (never bound)"
               if bound is None else
               f"bound to '{bound}' but never joined or ledgered")
        findings.append(Finding(
            "thr-orphan-thread", sf.rel, node.lineno,
            f"thread {how} — shutdown cannot prove it exited",
            symbol=symbol))
    return findings


def _check_with_lock(sf: SourceFile, node: ast.With,
                     emit_aliases: Set[str]) -> List[Finding]:
    lock_names = []
    for item in node.items:
        d = dotted(item.context_expr)
        if d and LOCKISH.search(d) and not FILE_LOCK.search(d) \
                and "()" not in d:
            lock_names.append(d)
    if not lock_names:
        return []
    findings: List[Finding] = []
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        cn = call_name(sub)
        bad: Optional[str] = None
        f = sub.func
        if cn in BLOCKING_CALLS:
            # `join` only counts for str-join-free receivers: x.join(
            # with zero args is "".join() style — require the call to
            # have no str-literal receiver
            if cn == "join" and isinstance(f, ast.Attribute) \
                    and isinstance(f.value, ast.Constant):
                continue
            if cn in ("run", "Popen", "check_output", "connect",
                      "recv", "send", "sendall", "accept"):
                # require a dotted receiver suggesting subprocess/socket
                recv = dotted(f) if isinstance(f, ast.Attribute) else ""
                if not re.search(r"subprocess|socket|sock|conn",
                                 recv, re.IGNORECASE):
                    continue
            bad = f"blocking call '{cn}(...)'"
        if cn in EMISSION_HELPERS:
            is_emit = False
            if isinstance(f, ast.Name) and f.id in emit_aliases:
                is_emit = True
            if isinstance(f, ast.Attribute) \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id in emit_aliases:
                is_emit = True
            if is_emit:
                bad = f"metric/fault emission '{cn}(...)'"
        if bad is None:
            continue
        if pragma_allows(sf.allow, sub.lineno, "thr-blocking-under-lock"):
            continue
        findings.append(Finding(
            "thr-blocking-under-lock", sf.rel, sub.lineno,
            f"{bad} while holding {'/'.join(lock_names)} — blocks every "
            f"thread contending for the lock and invites lock-order "
            f"inversions",
            symbol=sf.qualname_of(sub)))
    return findings
