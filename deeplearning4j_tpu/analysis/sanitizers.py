"""Runtime concurrency sanitizer: lock-order + long-held-lock.

`LockOrderSanitizer.install()` replaces the `threading.Lock` /
`threading.RLock` factories with proxy-producing ones. Every proxy
knows its *creation site* (file:line), and every acquisition records
one edge per lock already held by the acquiring thread:

    held A, acquiring B   =>   edge  A -> B

Two threads acquiring the same pair in opposite orders produce the
cycle A -> B -> A — a potential deadlock even if the interleaving
never actually wedged this run. That is the point: the sanitizer turns
"we got lucky this time" into a failed test. It also flags locks held
longer than `long_hold_s` (a blocking operation living inside a
critical section — the runtime twin of thr-blocking-under-lock).

Enable for a test run (the chaos-sweep recipe) with

    DL4J_TPU_SANITIZE=locks python -m pytest tests/ -m chaos

tests/conftest.py installs the sanitizer at session start when the env
var is set and fails any test on whose watch a new cycle appeared.
Only locks created *after* install() are tracked; the production
threads (batcher/completion/watchdog/flush) all create their locks at
object construction time, so constructing the system under test with
the sanitizer armed covers them.

Edges aggregate by creation site, not lock instance, so an A→B/B→A
inversion between two *instances* of the same pair of sites is still a
cycle — exactly how native lock-order sanitizers (e.g. TSan's deadlock
detector) aggregate.

queue.Queue put/get ordering rides the SAME graph (the closed analyzer
gap): a `queue.Queue` created from an in-scope file becomes a node
(`q:file:line`). A *blocking* put on a BOUNDED queue (the only put
that can wedge) while holding lock L records the producer edge
``L -> Q``; after a blocking get returns, every lock the consumer
acquires before its next queue operation records the handoff edge
``Q -> L`` — "processing the item needs L". Together they catch the
classic coupled-queue deadlock (producer holds L blocked on a full
put; the consumer that would drain it needs L) as the cycle
``L -> Q -> L``, even on runs where the interleaving got lucky. Only
the three methods are instrumented — the queue's internal mutex and
conditions are created from stdlib frames and stay REAL C locks (see
DEFAULT_SCOPE below for why that is load-bearing).
"""

from __future__ import annotations

import os
import queue as _queue_mod
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

ENV_VAR = "DL4J_TPU_SANITIZE"

# real factories/methods, captured before any install() can patch them
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_Q_INIT = _queue_mod.Queue.__init__
_REAL_Q_PUT = _queue_mod.Queue.put
_REAL_Q_GET = _queue_mod.Queue.get

_ACTIVE: Optional["LockOrderSanitizer"] = None


def _creation_frame(skip_files: Tuple[str, ...]):
    """(path, lineno) of the first frame outside this module and
    threading.py — the lock's creation site."""
    for frame in reversed(traceback.extract_stack()):
        fn = frame.filename
        if fn.endswith(skip_files) or "threading.py" in fn:
            continue
        return fn, frame.lineno
    return "<unknown>", 0


@dataclass
class _Held:
    proxy: "_LockProxy"
    count: int
    t0: float


class _HeldStack(threading.local):
    def __init__(self):
        self.stack: List[_Held] = []
        # queue-handoff marker: the site of the tracked queue this
        # thread last blocking-got from (None once the thread performs
        # its next queue operation) — locks acquired while it is set
        # record the consumer edge Q -> L
        self.qmark: Optional[str] = None


@dataclass
class Edge:
    src: str
    dst: str
    thread: str
    stack: str = ""


@dataclass
class LongHold:
    site: str
    duration_s: float
    thread: str


class _LockProxy:
    """Wraps one real lock; reports acquisitions to the sanitizer."""

    _SAN_IS_RLOCK = False

    def __init__(self, san: "LockOrderSanitizer", inner, site: str):
        self._san = san
        self._inner = inner
        self._site = site

    # ------------------------------------------------------- lock API
    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._san._note_acquire(self)
        return got

    def release(self):
        self._san._note_release(self)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<sanitized {type(self._inner).__name__} @{self._site}>"


class _RLockProxy(_LockProxy):
    _SAN_IS_RLOCK = True

    # Condition-variable protocol: keep the sanitizer's held-stack
    # accounting exact across cond.wait()'s full release/re-acquire
    def _release_save(self):
        state = self._inner._release_save()
        self._san._note_release(self, all_levels=True)
        return state

    def _acquire_restore(self, state):
        self._inner._acquire_restore(state)
        self._san._note_acquire(self)

    def _is_owned(self):
        return self._inner._is_owned()

    def locked(self):
        locked = getattr(self._inner, "locked", None)
        return locked() if locked is not None else False


class LockOrderSanitizer:
    """Build the cross-thread lock-acquisition graph; detect cycles
    (potential deadlocks) and long-held locks."""

    # only locks created from files matching these substrings are
    # proxied. Scoping matters beyond noise: stdlib internals
    # (queue.Queue's mutex, executor work queues) are waited on by
    # daemon threads straight through interpreter finalization, where
    # a pure-Python acquire frame is a fatal error — those must stay
    # real C locks.
    DEFAULT_SCOPE = ("deeplearning4j_tpu", "test")

    def __init__(self, long_hold_s: float = 1.0,
                 scope: Tuple[str, ...] = DEFAULT_SCOPE):
        self.long_hold_s = float(long_hold_s)
        self.scope = tuple(scope)
        self._meta = _REAL_LOCK()
        self._edges: Dict[Tuple[str, str], Edge] = {}
        self._long_holds: List[LongHold] = []
        self._held = _HeldStack()
        self._installed = False
        self._skip = (os.path.abspath(__file__),)

    # -------------------------------------------------------- install
    def install(self) -> "LockOrderSanitizer":
        global _ACTIVE
        if self._installed:
            return self
        san = self

        def make_lock():
            path, lineno = _creation_frame(san._skip)
            if not any(p in path for p in san.scope):
                return _REAL_LOCK()
            return _LockProxy(san, _REAL_LOCK(),
                              f"{os.path.basename(path)}:{lineno}")

        def make_rlock():
            path, lineno = _creation_frame(san._skip)
            if not any(p in path for p in san.scope):
                return _REAL_RLOCK()
            return _RLockProxy(san, _REAL_RLOCK(),
                               f"{os.path.basename(path)}:{lineno}")

        threading.Lock = make_lock
        threading.RLock = make_rlock

        # queue.Queue: instrument the three methods IN PLACE (so
        # pre-existing subclasses stay subclasses); only instances
        # created from in-scope frames get a `_san_site` and report.
        # The queue's own mutex/conditions come from stdlib creation
        # frames and therefore stay real C locks.
        def q_init(q, maxsize: int = 0):
            _REAL_Q_INIT(q, maxsize)
            path, lineno = _creation_frame(san._skip)
            if any(p in path for p in san.scope):
                q._san_site = f"q:{os.path.basename(path)}:{lineno}"

        def q_put(q, item, block: bool = True, timeout=None):
            site = getattr(q, "_san_site", None)
            # only a blocking put on a BOUNDED queue can wedge
            if site is not None and block and q.maxsize > 0:
                san._note_queue_put(site)
            return _REAL_Q_PUT(q, item, block, timeout)

        def q_get(q, block: bool = True, timeout=None):
            item = _REAL_Q_GET(q, block, timeout)
            site = getattr(q, "_san_site", None)
            if site is not None and block:
                san._note_queue_get(site)
            return item

        _queue_mod.Queue.__init__ = q_init
        _queue_mod.Queue.put = q_put
        _queue_mod.Queue.get = q_get
        self._installed = True
        _ACTIVE = self
        return self

    def uninstall(self) -> None:
        global _ACTIVE
        if not self._installed:
            return
        threading.Lock = _REAL_LOCK
        threading.RLock = _REAL_RLOCK
        _queue_mod.Queue.__init__ = _REAL_Q_INIT
        _queue_mod.Queue.put = _REAL_Q_PUT
        _queue_mod.Queue.get = _REAL_Q_GET
        self._installed = False
        if _ACTIVE is self:
            _ACTIVE = None

    # ----------------------------------------------------- accounting
    def _record_edge(self, src: str, dst: str) -> None:
        if src == dst:
            return
        key = (src, dst)
        if key not in self._edges:
            tb = "".join(traceback.format_stack(limit=8)[:-2])
            with self._meta:
                if key not in self._edges:
                    self._edges[key] = Edge(
                        src, dst, threading.current_thread().name, tb)

    def _note_acquire(self, proxy: _LockProxy) -> None:
        stack = self._held.stack
        for held in stack:
            if held.proxy is proxy:          # RLock re-entry: no edge
                held.count += 1
                return
        now = time.perf_counter()
        if stack:
            self._record_edge(stack[-1].proxy._site, proxy._site)
        if self._held.qmark is not None:
            # consumer half of a queue handoff: processing the item
            # this thread got from Q needs this lock  =>  Q -> L
            self._record_edge(self._held.qmark, proxy._site)
        stack.append(_Held(proxy, 1, now))

    def _note_queue_put(self, site: str) -> None:
        """Blocking put on a bounded tracked queue: producer edge
        held-lock -> Q (the put can wedge while the lock is held)."""
        stack = self._held.stack
        if stack:
            self._record_edge(stack[-1].proxy._site, site)
        self._held.qmark = None       # a queue op ends the handoff window

    def _note_queue_get(self, site: str) -> None:
        """Blocking get returned: open the handoff window — locks this
        thread acquires before its next queue op record Q -> L."""
        if self._held.stack:
            # a blocking get UNDER a lock is itself a wedge hazard
            self._record_edge(self._held.stack[-1].proxy._site, site)
        self._held.qmark = site

    def _note_release(self, proxy: _LockProxy,
                      all_levels: bool = False) -> None:
        stack = self._held.stack
        for i in range(len(stack) - 1, -1, -1):
            held = stack[i]
            if held.proxy is not proxy:
                continue
            held.count -= 1
            if all_levels:
                held.count = 0
            if held.count <= 0:
                dur = time.perf_counter() - held.t0
                if dur >= self.long_hold_s:
                    with self._meta:
                        self._long_holds.append(LongHold(
                            proxy._site, dur,
                            threading.current_thread().name))
                stack.pop(i)
            return

    # -------------------------------------------------------- reports
    def edges(self) -> List[Edge]:
        with self._meta:
            return list(self._edges.values())

    def cycles(self) -> List[List[str]]:
        """Simple cycles in the site graph, each reported once in
        canonical rotation (smallest site first)."""
        with self._meta:
            adj: Dict[str, Set[str]] = {}
            for (src, dst) in self._edges:
                adj.setdefault(src, set()).add(dst)
        out: Set[Tuple[str, ...]] = set()

        def dfs(start: str, node: str, path: List[str],
                visited: Set[str]) -> None:
            for nxt in sorted(adj.get(node, ())):
                if nxt == start and len(path) > 1:
                    i = path.index(min(path))
                    out.add(tuple(path[i:] + path[:i]))
                elif nxt not in visited and len(path) < 16:
                    visited.add(nxt)
                    dfs(start, nxt, path + [nxt], visited)
                    visited.discard(nxt)

        for start in sorted(adj):
            dfs(start, start, [start], {start})
        return [list(c) for c in sorted(out)]

    def long_holds(self) -> List[LongHold]:
        with self._meta:
            return list(self._long_holds)

    def violations(self) -> List[dict]:
        """Findings-shaped dicts for the two runtime rules."""
        out = []
        for cyc in self.cycles():
            out.append({
                "rule": "san-lock-order-cycle",
                "sites": cyc,
                "message": "cyclic lock order " +
                           " -> ".join(cyc + [cyc[0]]) +
                           " — potential deadlock",
            })
        for lh in self.long_holds():
            out.append({
                "rule": "san-long-held-lock",
                "sites": [lh.site],
                "message": f"lock at {lh.site} held "
                           f"{lh.duration_s:.3f}s by {lh.thread} "
                           f"(threshold {self.long_hold_s:.3f}s)",
            })
        return out

    def reset(self) -> None:
        with self._meta:
            self._edges.clear()
            self._long_holds.clear()


# ------------------------------------------------------------- wiring
def active_sanitizer() -> Optional[LockOrderSanitizer]:
    return _ACTIVE


def enabled_modes() -> Set[str]:
    raw = os.environ.get(ENV_VAR, "")
    return {m.strip() for m in raw.split(",") if m.strip()}


def install_from_env(long_hold_s: float = 1.0
                     ) -> Optional[LockOrderSanitizer]:
    """Install the lock sanitizer iff DL4J_TPU_SANITIZE names `locks`.
    Returns the active sanitizer (new or pre-existing) or None."""
    if "locks" not in enabled_modes():
        return None
    if _ACTIVE is not None:
        return _ACTIVE
    return LockOrderSanitizer(long_hold_s=long_hold_s).install()
