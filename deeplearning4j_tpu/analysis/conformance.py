"""Pass 3 — registry conformance.

Generalizes the three hand-written pin tests (fault-point registry,
REGISTERED_METRICS, dashboard metric literals) into one pass so there
is a single source of truth:

  reg-unregistered-fault-point  fire("...") literal not registered
  reg-unfired-fault-point       registered point with no fire site
  reg-unregistered-metric       emitted/referenced dl4j_* literal not
                                registered (nor a registered prefix)
  reg-unemitted-metric          registered non-derived metric never
                                emitted
  reg-swallowed-exception       `except Exception: pass` outside the
                                guarded-telemetry annotation discipline
  reg-untested-registry-name    registered name no test ever mentions
  reg-unregistered-program-rule Rule("prog-...") catalog entry not in
                                the pinned REGISTERED_PROGRAM_RULES
  reg-unimplemented-program-rule pinned program rule with no Rule(...)
                                catalog definition

The registries themselves are read from the *AST* of the modules that
define them (frozenset literals assigned to REGISTERED_POINTS /
REGISTERED_METRICS / DERIVED_METRICS / REGISTERED_PROGRAM_RULES), so
this pass — like the other two — never imports the analyzed code. The
program-rule pin mirrors the metric discipline: the `prog-*` ids in
the findings.py catalog and the registry in program_lint.py must move
in the same commit, and every pinned id must be named by a test.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from deeplearning4j_tpu.analysis.findings import Finding, pragma_allows
from deeplearning4j_tpu.analysis.source import (
    SourceFile,
    call_name,
    const_str,
)

EMIT_HELPERS = ("count", "observe", "set_gauge", "gauge_fn")
FUSED_HELPERS = ("count_observe",)
FIRE_NAMES = ("fire", "_fire")
METRIC_NAME = re.compile(r"\bdl4j_[a-z0-9_]+\b")
# literals in these telemetry domains must be registered names (or a
# registered-name prefix — the dashboard's startswith filters); other
# dl4j_ namespaces (w2v kernel labels etc.) are not metrics
METRIC_DOMAINS = re.compile(
    r"dl4j_(train|serving|checkpoint|cluster|retry|breaker|jit|obs"
    r"|perf|pipeline|mesh|fleet|rollout|decode|journal)_")


@dataclass
class RegistryView:
    points: Set[str] = field(default_factory=set)
    points_site: Tuple[str, int] = ("", 0)
    metrics: Set[str] = field(default_factory=set)
    metrics_site: Tuple[str, int] = ("", 0)
    derived: Set[str] = field(default_factory=set)
    program_rules: Set[str] = field(default_factory=set)
    program_rules_site: Tuple[str, int] = ("", 0)

    @property
    def complete(self) -> bool:
        return bool(self.points) and bool(self.metrics)


def parse_registries(sources: List[SourceFile]) -> RegistryView:
    """Pull REGISTERED_POINTS / REGISTERED_METRICS / DERIVED_METRICS
    out of whichever analyzed files define them (frozenset literals)."""
    view = RegistryView()
    for sf in sources:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if not isinstance(t, ast.Name):
                    continue
                if t.id not in ("REGISTERED_POINTS",
                                "REGISTERED_METRICS",
                                "DERIVED_METRICS",
                                "REGISTERED_PROGRAM_RULES"):
                    continue
                names = _literal_names(node.value)
                if names is None:
                    continue
                if t.id == "REGISTERED_POINTS":
                    view.points = names
                    view.points_site = (sf.rel, node.lineno)
                elif t.id == "REGISTERED_METRICS":
                    view.metrics = names
                    view.metrics_site = (sf.rel, node.lineno)
                elif t.id == "REGISTERED_PROGRAM_RULES":
                    view.program_rules = names
                    view.program_rules_site = (sf.rel, node.lineno)
                else:
                    view.derived = names
    return view


def program_rule_sites(sources: List[SourceFile]
                       ) -> List[Tuple[str, SourceFile, int]]:
    """Every `Rule("prog-...", ...)` catalog definition in the
    analyzed sources (the findings.py catalog, read as AST)."""
    out = []
    for sf in sources:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) \
                    and call_name(node) == "Rule" and node.args:
                lit = const_str(node.args[0])
                if lit is not None and lit.startswith("prog-"):
                    out.append((lit, sf, node.lineno))
    return out


def _literal_names(value) -> Optional[Set[str]]:
    if isinstance(value, ast.Call) and call_name(value) == "frozenset" \
            and value.args:
        value = value.args[0]
    try:
        v = ast.literal_eval(value)
    except (ValueError, SyntaxError):
        return None
    if isinstance(v, (set, frozenset, list, tuple)) \
            and all(isinstance(x, str) for x in v):
        return set(v)
    return None


# ----------------------------------------------------------- fire sites
def fire_sites(sources: List[SourceFile]
               ) -> List[Tuple[str, SourceFile, int, str]]:
    out = []
    for sf in sources:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) \
                    and call_name(node) in FIRE_NAMES and node.args:
                lit = const_str(node.args[0])
                if lit is not None:
                    out.append((lit, sf, node.lineno,
                                sf.qualname_of(node)))
    return out


# ------------------------------------------------------- emission sites
def emission_sites(sources: List[SourceFile]
                   ) -> List[Tuple[str, SourceFile, int]]:
    """(metric name, file, line) for every emission call site. The
    registry-definition module itself is not a site."""
    out = []
    for sf in sources:
        if _defines_registry(sf, "REGISTERED_METRICS"):
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            cn = call_name(node)
            if cn in EMIT_HELPERS and node.args:
                lit = const_str(node.args[0])
                if lit is not None and lit.startswith("dl4j_"):
                    out.append((lit, sf, node.lineno))
            elif cn in FUSED_HELPERS and len(node.args) >= 2:
                for a in node.args[:2]:
                    lit = const_str(a)
                    if lit is not None and lit.startswith("dl4j_"):
                        out.append((lit, sf, node.lineno))
    return out


def _defines_registry(sf: SourceFile, name: str) -> bool:
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    return True
    return False


def metric_literals(sources: List[SourceFile]
                    ) -> List[Tuple[str, SourceFile, int]]:
    """Every dl4j_* name appearing in any string constant (including
    prefix literals like the dashboard's startswith filters)."""
    out = []
    for sf in sources:
        if _defines_registry(sf, "REGISTERED_METRICS"):
            continue
        for node in ast.walk(sf.tree):
            s = const_str(node)
            if s is None:
                continue
            for m in METRIC_NAME.findall(s):
                out.append((m, sf, node.lineno))
    return out


# --------------------------------------------------------------- checks
def run(sources: List[SourceFile],
        tests_dir: Optional[Path] = None) -> List[Finding]:
    findings: List[Finding] = []
    view = parse_registries(sources)

    # ---- fault points ------------------------------------------------
    fired: Dict[str, List[Tuple[SourceFile, int, str]]] = {}
    for name, sf, line, symbol in fire_sites(sources):
        fired.setdefault(name, []).append((sf, line, symbol))
    if view.points:
        for name, sites in sorted(fired.items()):
            if name in view.points:
                continue
            sf, line, symbol = sites[0]
            if pragma_allows(sf.allow, line,
                             "reg-unregistered-fault-point"):
                continue
            findings.append(Finding(
                "reg-unregistered-fault-point", sf.rel, line,
                f'fire("{name}") is not listed in REGISTERED_POINTS',
                symbol=symbol))
        for name in sorted(view.points - set(fired)):
            findings.append(Finding(
                "reg-unfired-fault-point", view.points_site[0],
                view.points_site[1],
                f'registered fault point "{name}" has no fire(...) '
                f'site in the package'))

    # ---- metrics -----------------------------------------------------
    emitted: Dict[str, List[Tuple[SourceFile, int]]] = {}
    for name, sf, line in emission_sites(sources):
        emitted.setdefault(name, []).append((sf, line))
    flagged_at_site: Set[Tuple[str, str]] = set()
    if view.metrics:
        for name, sites in sorted(emitted.items()):
            if name in view.metrics:
                continue
            sf, line = sites[0]
            if pragma_allows(sf.allow, line, "reg-unregistered-metric"):
                continue
            flagged_at_site.add((sf.rel, name))
            findings.append(Finding(
                "reg-unregistered-metric", sf.rel, line,
                f'emission of "{name}" which is not listed in '
                f'REGISTERED_METRICS'))
        for name in sorted(view.metrics - view.derived - set(emitted)):
            findings.append(Finding(
                "reg-unemitted-metric", view.metrics_site[0],
                view.metrics_site[1],
                f'registered metric "{name}" has no emission site in '
                f'the package'))
        # referenced literals in telemetry domains must resolve
        seen_msgs = set()
        for name, sf, line in metric_literals(sources):
            if not METRIC_DOMAINS.match(name):
                continue
            if name in view.metrics:
                continue
            if any(m.startswith(name) for m in view.metrics):
                continue   # prefix literal (dashboard filters)
            if pragma_allows(sf.allow, line, "reg-unregistered-metric"):
                continue
            key = (sf.rel, name)
            if key in seen_msgs or key in flagged_at_site:
                continue
            seen_msgs.add(key)
            findings.append(Finding(
                "reg-unregistered-metric", sf.rel, line,
                f'literal "{name}" is in a telemetry domain but is '
                f'neither a registered metric nor a registered-name '
                f'prefix'))

    # ---- program-rule registry pin -----------------------------------
    rule_sites = program_rule_sites(sources)
    if view.program_rules:
        for name, sf, line in sorted(rule_sites):
            if name in view.program_rules:
                continue
            if pragma_allows(sf.allow, line,
                             "reg-unregistered-program-rule"):
                continue
            findings.append(Finding(
                "reg-unregistered-program-rule", sf.rel, line,
                f'Rule("{name}") is not listed in '
                f"REGISTERED_PROGRAM_RULES"))
        declared = {n for n, _, _ in rule_sites}
        for name in sorted(view.program_rules - declared):
            findings.append(Finding(
                "reg-unimplemented-program-rule",
                view.program_rules_site[0], view.program_rules_site[1],
                f'pinned program rule "{name}" has no Rule(...) '
                f"catalog definition"))

    # ---- exception swallows ------------------------------------------
    findings.extend(swallow_sites(sources))

    # ---- test coverage -----------------------------------------------
    if tests_dir is not None and view.complete:
        blob = "\n".join(
            p.read_text() for p in sorted(Path(tests_dir).rglob("*.py"))
            if "__pycache__" not in p.parts)
        for name in sorted(view.points):
            if name not in blob:
                findings.append(Finding(
                    "reg-untested-registry-name", view.points_site[0],
                    view.points_site[1],
                    f'fault point "{name}" is named by no test'))
        for name in sorted(view.metrics):
            if name not in blob:
                findings.append(Finding(
                    "reg-untested-registry-name", view.metrics_site[0],
                    view.metrics_site[1],
                    f'metric "{name}" is named by no test'))
        for name in sorted(view.program_rules):
            if name not in blob:
                findings.append(Finding(
                    "reg-untested-registry-name",
                    view.program_rules_site[0],
                    view.program_rules_site[1],
                    f'program rule "{name}" is named by no test'))
    return findings


def swallow_sites(sources: List[SourceFile]) -> List[Finding]:
    """`except Exception:`/bare `except:` whose body is only pass/
    continue and whose except line carries no annotation (noqa with a
    reason, the repo's guarded-telemetry discipline) — silent failure
    swallowing."""
    findings: List[Finding] = []
    for sf in sources:
        lines = sf.text.splitlines()
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            broad = node.type is None or (
                isinstance(node.type, ast.Name)
                and node.type.id in ("Exception", "BaseException"))
            if not broad:
                continue
            if not all(isinstance(b, (ast.Pass, ast.Continue))
                       for b in node.body):
                continue
            src_line = lines[node.lineno - 1] \
                if node.lineno - 1 < len(lines) else ""
            if "noqa" in src_line:
                continue
            if pragma_allows(sf.allow, node.lineno,
                             "reg-swallowed-exception"):
                continue
            findings.append(Finding(
                "reg-swallowed-exception", sf.rel, node.lineno,
                "broad except swallowing every failure with no "
                "annotation — guarded-telemetry sites must carry a "
                "noqa reason",
                symbol=sf.qualname_of(node)))
    return findings
