"""Shared AST plumbing for the static passes.

Everything here is stdlib-only and import-free with respect to the
analyzed code: files are *parsed*, never executed, so `tools/analyze.py`
runs in well under a second with no jax (or any other dependency) in
the process.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterator, List, Optional

from deeplearning4j_tpu.analysis.findings import parse_pragmas


@dataclass
class SourceFile:
    path: Path
    rel: str                       # repo-relative posix path
    text: str
    tree: ast.Module
    allow: Dict[int, set] = field(default_factory=dict)
    # node -> enclosing function qualname ("" at module level)
    _qualnames: Dict[int, str] = field(default_factory=dict)

    @classmethod
    def parse(cls, path: Path, root: Path) -> Optional["SourceFile"]:
        try:
            text = path.read_text()
            tree = ast.parse(text, filename=str(path))
        except (SyntaxError, UnicodeDecodeError, OSError):
            return None
        try:
            rel = path.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = path.as_posix()
        sf = cls(path=path, rel=rel, text=text, tree=tree,
                 allow=parse_pragmas(text))
        sf._annotate_qualnames()
        return sf

    # ---------------------------------------------------------- helpers
    def _annotate_qualnames(self) -> None:
        def visit(node, stack):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    q = ".".join(stack + [child.name])
                    self._qualnames[id(child)] = q
                    visit(child, stack + [child.name])
                elif isinstance(child, ast.ClassDef):
                    visit(child, stack + [child.name])
                else:
                    self._qualnames[id(child)] = ".".join(stack)
                    visit(child, stack)
        visit(self.tree, [])

    def qualname_of(self, node) -> str:
        return self._qualnames.get(id(node), "")

    def functions(self) -> Iterator[ast.FunctionDef]:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node


def iter_py_files(pkg_dir: Path) -> List[Path]:
    return sorted(p for p in pkg_dir.rglob("*.py")
                  if "__pycache__" not in p.parts)


def load_sources(pkg_dir: Path, root: Path,
                 only: Optional[set] = None) -> List[SourceFile]:
    """Parse every .py file under `pkg_dir`. `only` (repo-relative
    posix paths) restricts the list — used by --diff mode."""
    out = []
    for p in iter_py_files(pkg_dir):
        sf = SourceFile.parse(p, root)
        if sf is None:
            continue
        if only is not None and sf.rel not in only:
            continue
        out.append(sf)
    return out


# ------------------------------------------------------- name helpers
def call_name(node: ast.Call) -> str:
    """Last identifier of the callee: foo() -> foo, a.b.foo() -> foo."""
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def dotted(node) -> str:
    """Best-effort dotted name of an expression (jax.jit, self._lock)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    if isinstance(node, ast.Call):
        return dotted(node.func) + "()"
    return ""


def const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
