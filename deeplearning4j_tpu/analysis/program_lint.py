"""Pass 4 — compiled-program lint: below the AST, into jaxpr/HLO.

The AST passes (jit/concurrency/conformance) see what the *source*
says; this pass sees what the *compiler* was actually handed. The gap
between delivered and peak flops hides in dtype/layout/fusion details
invisible at the Python level (Tensor Processing Primitives, arXiv
2104.05755; cuDNN primitives, arXiv 1410.0759) — so every registered
compiled program (the StepProgram single/graph/TBPTT/k-group variants,
the serving bucket programs, the bench flagship, the clustering steps)
is traced/lowered here and checked against its *declared* facts:

  prog-fp32-matmul-under-policy  dot/conv operand dtypes contradict the
                                 program's declared precision_policy
  prog-unhonored-donation        donate_argnums arg absent from the
                                 executable's input-output alias map
  prog-transpose-churn           transpose/copy bytes above threshold
  prog-hidden-host-transfer      outfeed/callback edges in a hot program
  prog-dead-output               computed outputs no caller consumes
  prog-excess-padding            serving pow2 bucket fill below threshold
  prog-unsharded-optimizer-state a mesh-registered (ZeRO-1) program's
                                 lowered module does not actually shard
                                 its declared optimizer-state argument
                                 (sharding annotations + alias map)

Declared facts, not guesses: the intended dtype comes from the
`precision_policy` registered on StepProgram / JitCache entries, the
intended aliasing from the jit site's own donate_argnums (read back
from `lowered.args_info`), the consumed outputs from the registration.

This module stays import-light at module scope (no jax) so the default
AST-only CLI keeps its zero-dependency contract; jax is imported only
when `run()` actually lints records (the `--programs` mode, pinned to
JAX_PLATFORMS=cpu by the CLI).

Rule ids are PINNED: `REGISTERED_PROGRAM_RULES` below is the registry
the conformance pass checks the findings.py catalog against (the same
discipline as REGISTERED_METRICS), so a rule cannot be added, renamed,
or dropped without the registry — and its tests — moving in the same
commit.
"""

from __future__ import annotations

import re
import warnings
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.analysis.findings import Finding

# pinned program-rule registry (conformance pass checks catalog == this)
REGISTERED_PROGRAM_RULES = frozenset({
    "prog-fp32-matmul-under-policy",
    "prog-unhonored-donation",
    "prog-transpose-churn",
    "prog-hidden-host-transfer",
    "prog-dead-output",
    "prog-excess-padding",
    "prog-unsharded-optimizer-state",
})

# precision policies a program can declare (JitCache.policy_name)
MIXED_POLICIES = ("bf16", "f16")

MATMUL_PRIMS = ("dot_general", "conv_general_dilated")
# jaxpr primitives that move data to the host mid-program
HOST_TRANSFER_PRIMS = ("outfeed", "infeed")
HOST_TRANSFER_MARKERS = ("callback", "host_callback")

_DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s64": 8,
                "s32": 4, "u32": 4, "s16": 2, "u16": 2, "s8": 1,
                "u8": 1, "pred": 1, "i64": 8, "i32": 4, "i16": 2,
                "i8": 1, "i1": 1, "ui32": 4, "ui8": 1}


@dataclass
class Thresholds:
    """Tunable rule thresholds. Defaults are calibrated so the shipped
    program set is clean (PERF.md records the measured margins) while
    the bad fixtures fire: real backward passes legitimately transpose
    weight matrices and lax.scan bodies copy carries, so churn flags on
    the *fraction* of program traffic, not the raw count."""

    # prog-transpose-churn: flag when BOTH hold
    transpose_min_ops: int = 8
    transpose_bytes_frac: float = 0.25
    # prog-unhonored-donation: leaves smaller than this never flag
    # (a dropped scalar alias is not "silent 2x memory")
    min_donated_bytes: int = 1024
    # prog-excess-padding: minimum average bucket fill ratio
    min_bucket_fill: float = 0.5


@dataclass
class ProgramRecord:
    """One registered compiled program, with its declared facts.

    `fn` is either a `jax.jit`-wrapped callable (its own donation
    declaration is read back from `lowered.args_info`) or a plain
    callable jitted here with `donate_argnums`. `fn=None` records carry
    only registration metadata (the serving bucket fill records).
    `compile=False` restricts the lint to trace/lower-level rules —
    the flagship ResNet50 lowers in ~2s on CPU but XLA-compiles in
    minutes, and the dtype/donation rules don't need the compile."""

    name: str
    fn: Optional[Callable] = None
    example_args: Tuple = ()
    example_kwargs: Dict[str, Any] = field(default_factory=dict)
    donate_argnums: Tuple[int, ...] = ()
    static_argnums: Tuple[int, ...] = ()
    precision_policy: Optional[str] = None    # "bf16" | "f16" | "f32"
    consumed_outputs: Optional[Tuple[int, ...]] = None  # None = all
    source: str = "deeplearning4j_tpu/analysis/programs.py"
    compile: bool = True
    # serving bucket metadata (prog-excess-padding)
    bucket_capacity: Optional[int] = None
    bucket_rows_per_dispatch: Optional[float] = None
    # mesh-sharded registration fact (prog-unsharded-optimizer-state):
    # top-level example_args indices whose leaves the program DECLARES
    # sharded (the ZeRO-1 optimizer state). The lint verifies the
    # lowered module actually carries non-replicated mhlo.sharding
    # annotations AND donation/aliasing on those arguments — a silent
    # fallback to replicated state is exactly the O(n) memory
    # regression the rule exists to catch.
    sharded_argnums: Tuple[int, ...] = ()


# ----------------------------------------------------------- jaxpr walk
def _iter_eqns(jaxpr):
    """Yield every eqn of `jaxpr` and of every sub-jaxpr reachable
    through eqn params (pjit/scan/while/cond/remat/custom_vjp...)."""
    stack = [jaxpr]
    seen = set()
    while stack:
        jx = stack.pop()
        if id(jx) in seen:
            continue
        seen.add(id(jx))
        for eqn in jx.eqns:
            yield eqn
            for v in eqn.params.values():
                vs = v if isinstance(v, (list, tuple)) else [v]
                for s in vs:
                    inner = getattr(s, "jaxpr", None)
                    if inner is not None and hasattr(inner, "eqns"):
                        stack.append(inner)      # ClosedJaxpr
                    elif hasattr(s, "eqns"):
                        stack.append(s)          # raw Jaxpr


def _matmul_ops(closed_jaxpr) -> List[Tuple[str, str, str]]:
    """(primitive, lhs_dtype, rhs_dtype) for every dot/conv eqn."""
    out = []
    for eqn in _iter_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name in MATMUL_PRIMS and len(eqn.invars) >= 2:
            out.append((eqn.primitive.name,
                        str(eqn.invars[0].aval.dtype),
                        str(eqn.invars[1].aval.dtype)))
    return out


def _host_transfer_prims(closed_jaxpr) -> List[str]:
    out = []
    for eqn in _iter_eqns(closed_jaxpr.jaxpr):
        name = eqn.primitive.name
        if name in HOST_TRANSFER_PRIMS or any(
                m in name for m in HOST_TRANSFER_MARKERS):
            out.append(name)
    return out


# ------------------------------------------------------- HLO text maths
def _tensor_bytes(type_str: str) -> int:
    """Bytes of a StableHLO `4x8xf32`-style tensor type string."""
    parts = type_str.strip().split("x")
    if not parts:
        return 0
    dt = parts[-1]
    n = 1
    for d in parts[:-1]:
        if d.isdigit():
            n *= int(d)
    return n * _DTYPE_BYTES.get(dt, 4)


def _hlo_shape_bytes(dtype: str, dims: str) -> int:
    n = 1
    for d in dims.split(","):
        d = d.strip()
        if d.isdigit():
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


_MAIN_SIG_RE = re.compile(
    r"func\.func\s+(?:public\s+)?@main\((.*?)\)\s*->", re.S)
_ARG_RE = re.compile(r"%arg(\d+): tensor<([^>]*)>\s*(\{[^}]*\})?")
_STABLE_TRANSPOSE_RE = re.compile(
    r"stablehlo\.transpose.*?->\s*tensor<([^>]*)>")
_HLO_TRANSPOSE_RE = re.compile(
    r"= (\w+)\[([^\]]*)\][^ ]* (?:transpose|copy)\(")
_RESULT_RE = re.compile(r"->\s*\((.*?)\)\s*\{", re.S)


def _main_signature(lowered_text: str) -> List[Tuple[int, str, bool]]:
    """[(arg_index, tensor_type, has_alias)] of the lowered @main.
    Donation shows as `tf.aliasing_output` on single-device lowerings
    and as `jax.buffer_donor` on SPMD-partitioned ones (aliases only
    resolve at compile there) — both count as the module carrying the
    donation declaration."""
    m = _MAIN_SIG_RE.search(lowered_text)
    if m is None:
        return []
    return [(int(a), t,
             bool(attr and ("aliasing_output" in attr
                            or "buffer_donor" in attr)))
            for a, t, attr in _ARG_RE.findall(m.group(1))]


def _donated_leaf_avals(lowered) -> List[Any]:
    """ShapedArray avals of every leaf the jit site declared donated,
    read back from `lowered.args_info` — the jit site's own
    declaration, not a re-guess from the record."""
    import jax

    leaves = jax.tree_util.tree_leaves(
        lowered.args_info,
        is_leaf=lambda a: hasattr(a, "donated"))
    return [getattr(l, "aval", None) or getattr(l, "shape", None)
            for l in leaves if getattr(l, "donated", False)]


def _aval_bytes(aval) -> int:
    try:
        import numpy as np

        size = 1
        for d in aval.shape:
            size *= int(d)
        return size * np.dtype(aval.dtype).itemsize
    except Exception:   # noqa: BLE001 - unknown aval shape: assume big
        return 1 << 30


# --------------------------------------------------------------- checks
_DONATION_WARNING = "donated buffers were not usable"


def _lint_one(rec: ProgramRecord, th: Thresholds) -> List[Finding]:
    import jax

    findings: List[Finding] = []

    def finding(rule: str, message: str) -> None:
        findings.append(Finding(rule, rec.source, 1, message,
                                symbol=rec.name))

    # ---- prog-excess-padding (metadata-only records) -----------------
    if rec.bucket_capacity:
        rows = rec.bucket_rows_per_dispatch or 0.0
        fill = rows / float(rec.bucket_capacity)
        if fill < th.min_bucket_fill:
            finding(
                "prog-excess-padding",
                f"bucket capacity {rec.bucket_capacity} dispatches "
                f"{rows:g} rows on average (fill {fill:.2f} < "
                f"{th.min_bucket_fill:.2f}) — the MXU runs mostly "
                f"padding")
    if rec.fn is None:
        return findings

    jitted = rec.fn
    if not hasattr(jitted, "lower"):
        jitted = jax.jit(jitted, donate_argnums=rec.donate_argnums,
                         static_argnums=rec.static_argnums)

    # ONE trace serves every rule: jaxpr + out tree from the Traced,
    # the lowered module (donation attrs) from it, the compile only
    # when the record allows it
    with warnings.catch_warnings(record=True) as wrec:
        warnings.simplefilter("always")
        traced = jitted.trace(*rec.example_args, **rec.example_kwargs)
        lowered = traced.lower()
    closed = traced.jaxpr
    out_shape = traced.out_info
    lowered_text = lowered.as_text()

    # ---- prog-unhonored-donation -------------------------------------
    # jax reports unmatched donations at lowering; the lowered module's
    # aliasing attributes are the accepted set. Both are checked: a
    # warning names the dropped buffers, a donation declaration whose
    # accepted set is empty is the catastrophic (platform/backend) case.
    donated = [a for a in _donated_leaf_avals(lowered)
               if a is not None and _aval_bytes(a) >= th.min_donated_bytes]
    dropped = [str(w.message) for w in wrec
               if _DONATION_WARNING in str(w.message)]
    sig = _main_signature(lowered_text)
    aliased = sum(1 for _, _, has in sig if has)
    if dropped:
        detail = dropped[0].splitlines()[0]
        finding(
            "prog-unhonored-donation",
            f"donated argument(s) absent from the executable's "
            f"input-output alias map ({detail}) — the caller loses the "
            f"buffer AND pays the copy")
    elif donated and aliased == 0:
        finding(
            "prog-unhonored-donation",
            f"{len(donated)} donated buffer(s) declared but the "
            f"lowered module carries no aliasing attribute at all — "
            f"donation is silently ignored on this path")

    # ---- prog-unsharded-optimizer-state ------------------------------
    if rec.sharded_argnums:
        _check_sharded_args(rec, lowered_text, finding)

    # ---- prog-fp32-matmul-under-policy -------------------------------
    if rec.precision_policy in MIXED_POLICIES:
        ops = _matmul_ops(closed)
        bad = [o for o in ops if "float32" in (o[1], o[2])
               or "float64" in (o[1], o[2])]
        if bad:
            prim, lhs, rhs = bad[0]
            finding(
                "prog-fp32-matmul-under-policy",
                f"{len(bad)} of {len(ops)} dot/conv op(s) compute in "
                f"f32 under the declared {rec.precision_policy} "
                f"policy (first: {prim} {lhs} x {rhs})")

    # ---- prog-hidden-host-transfer -----------------------------------
    host = _host_transfer_prims(closed)
    if not host and "custom_call" in lowered_text:
        host = [m.group(0).split("@")[-1] for m in re.finditer(
            r"stablehlo\.custom_call\s*@\S*callback\S*", lowered_text)]
    if host:
        finding(
            "prog-hidden-host-transfer",
            f"host-transfer edge(s) inside the program: "
            f"{', '.join(sorted(set(host))[:4])} — every call blocks "
            f"the device on the host")

    # ---- prog-dead-output --------------------------------------------
    if rec.consumed_outputs is not None:
        _dead_outputs(rec, closed, out_shape, finding)

    # ---- prog-transpose-churn ----------------------------------------
    if rec.compile:
        compiled = lowered.compile()
        txt = compiled.as_text()
        ops = _HLO_TRANSPOSE_RE.findall(txt)
        churn = sum(_hlo_shape_bytes(dt, dims) for dt, dims in ops)
        total = _compiled_bytes_accessed(compiled)
        if total is None:
            total = _signature_bytes(lowered_text)
        if (len(ops) >= th.transpose_min_ops and total
                and churn / total >= th.transpose_bytes_frac):
            finding(
                "prog-transpose-churn",
                f"{len(ops)} transpose/copy op(s) move "
                f"{churn} bytes = {churn / total:.0%} of program "
                f"traffic (threshold {th.transpose_bytes_frac:.0%}) — "
                f"layout thrash")
    else:
        # lower-only records: model-authored transposes in StableHLO
        trs = _STABLE_TRANSPOSE_RE.findall(lowered_text)
        churn = sum(_tensor_bytes(t) for t in trs)
        total = _signature_bytes(lowered_text)
        if (len(trs) >= th.transpose_min_ops and total
                and churn / total >= th.transpose_bytes_frac):
            finding(
                "prog-transpose-churn",
                f"{len(trs)} authored transpose(s) move {churn} bytes "
                f"= {churn / total:.0%} of program I/O (threshold "
                f"{th.transpose_bytes_frac:.0%}) — layout thrash")
    return findings


def _arg_segments(lowered_text: str) -> Dict[int, str]:
    """{arg_index: raw attribute text} of the lowered @main signature.
    Attribute dicts may nest braces inside quoted mhlo.sharding values
    (`"{devices=[8]<=[8]}"`), so the signature is split on `%arg`
    boundaries instead of brace-matched."""
    m = _MAIN_SIG_RE.search(lowered_text)
    if m is None:
        return {}
    out: Dict[int, str] = {}
    parts = m.group(1).split("%arg")
    for part in parts[1:]:
        idx_end = 0
        while idx_end < len(part) and part[idx_end].isdigit():
            idx_end += 1
        if idx_end == 0:
            continue
        out[int(part[:idx_end])] = part
    return out


def _check_sharded_args(rec: ProgramRecord, lowered_text: str,
                        finding) -> None:
    """prog-unsharded-optimizer-state: every example leaf of a
    declared `sharded_argnums` argument that IS sharded at the call
    site must appear in the lowered @main with a non-replicated
    mhlo.sharding annotation AND donation/aliasing; a declaration with
    no sharded leaf at all is the catastrophic silent-replication
    case."""
    import jax

    segs = _arg_segments(lowered_text)
    offsets = []
    pos = 0
    for a in rec.example_args:
        n = len(jax.tree_util.tree_leaves(a))
        offsets.append((pos, pos + n))
        pos += n

    def leaf_sharded(leaf) -> bool:
        sh = getattr(leaf, "sharding", None)
        return sh is not None and not sh.is_fully_replicated

    for argnum in rec.sharded_argnums:
        if argnum >= len(offsets):
            continue
        lo, hi = offsets[argnum]
        leaves = jax.tree_util.tree_leaves(rec.example_args[argnum])
        expected = [lo + i for i, leaf in enumerate(leaves)
                    if leaf_sharded(leaf)]
        if not expected:
            finding(
                "prog-unsharded-optimizer-state",
                f"argument {argnum} is declared mesh-sharded "
                f"optimizer state but NO leaf of it is sharded at the "
                f"call site — the state is silently replicated (n x "
                f"the memory the registration promises to shard)")
            continue
        unannotated = []
        unaliased = []
        for i in expected:
            seg = segs.get(i, "")
            if "mhlo.sharding" not in seg or "devices=" not in seg:
                unannotated.append(i)
            elif "buffer_donor" not in seg \
                    and "aliasing_output" not in seg:
                unaliased.append(i)
        if unannotated:
            finding(
                "prog-unsharded-optimizer-state",
                f"{len(unannotated)} of {len(expected)} sharded "
                f"optimizer-state leaf/leaves of argument {argnum} "
                f"carry no device sharding annotation in the lowered "
                f"module — XLA receives them replicated")
        elif unaliased:
            finding(
                "prog-unsharded-optimizer-state",
                f"{len(unaliased)} of {len(expected)} sharded "
                f"optimizer-state leaf/leaves of argument {argnum} "
                f"are sharded but not donated/aliased — the sharded "
                f"update still pays a full state copy per step")


def _dead_outputs(rec: ProgramRecord, closed, out_shape,
                  finding) -> None:
    """Outputs the registration declares unconsumed, when their leaves
    are genuinely computed (not input pass-throughs or literals)."""
    import jax

    if not isinstance(out_shape, (tuple, list)):
        return
    invars = set(map(id, closed.jaxpr.invars))
    offsets = []
    pos = 0
    for child in out_shape:
        n = len(jax.tree_util.tree_leaves(child))
        offsets.append((pos, pos + n))
        pos += n
    consumed = set(rec.consumed_outputs)
    for i, (lo, hi) in enumerate(offsets):
        if i in consumed:
            continue
        leaves = closed.jaxpr.outvars[lo:hi]
        computed = [v for v in leaves
                    if type(v).__name__ != "Literal"
                    and id(v) not in invars]
        if computed:
            finding(
                "prog-dead-output",
                f"output {i} ({hi - lo} leaf/leaves) is computed but "
                f"no caller consumes it — wasted flops and transfer")


def _compiled_bytes_accessed(compiled) -> Optional[float]:
    try:
        ca = compiled.cost_analysis()
    except Exception:   # noqa: BLE001 - cost analysis is best-effort
        return None
    entries = ca if isinstance(ca, (list, tuple)) else [ca]
    total = 0.0
    for e in entries:
        if isinstance(e, dict):
            total += float(e.get("bytes accessed", 0.0) or 0.0)
    return total or None


def _signature_bytes(lowered_text: str) -> int:
    """Sum of @main argument + result tensor bytes — the lower-only
    fallback denominator for churn fractions."""
    total = sum(_tensor_bytes(t) for _, t, _ in
                _main_signature(lowered_text))
    m = _RESULT_RE.search(lowered_text)
    if m:
        total += sum(_tensor_bytes(t) for t in
                     re.findall(r"tensor<([^>]*)>", m.group(1)))
    return total


# ------------------------------------------------------------------ run
def run(records: Sequence[ProgramRecord],
        thresholds: Optional[Thresholds] = None) -> List[Finding]:
    """Lint every record; findings are fingerprintable (file = the
    program's owning source, symbol = the program name, line-free
    message) so the baseline/pragma machinery applies unchanged."""
    th = thresholds or Thresholds()
    findings: List[Finding] = []
    for rec in records:
        findings.extend(_lint_one(rec, th))
    findings.sort(key=lambda f: (f.file, f.symbol, f.rule))
    return findings
