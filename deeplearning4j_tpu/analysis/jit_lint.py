"""Pass 1 — JIT / recompile hygiene.

Walks every function reachable from the step/serving hot paths (the
`fit`/`output`/`predict` entry points, HTTP handlers, and every
`threading.Thread` target — the batcher/completion/watchdog/flush
thread bodies) and flags the hazards that erase compiled-path wins:

  jit-host-sync            blocking device→host sync on a hot path
  jit-missing-donate       step-shaped jax.jit without buffer donation
  jit-traced-python-scalar shape-derived value fed to a traced arg
  jit-use-after-donation   donated buffer read after the donating call

Reachability is a real call graph where the AST can prove one and a
name-based over-approximation where it cannot (ROADMAP carried-forward
gap, closed by the engine's stable entry points):

  - roots: the `fit`/`output`/`predict`/HTTP-handler names, every
    `threading.Thread` target, and the engine's StepProgram/StepHarness
    entry points by exact qualname (`ROOT_QUALNAMES`) — the compiled
    step path hangs off those whatever the surrounding loop is named;
  - jit sites include every spelling in the tree: `jax.jit(f, ...)`,
    `@jax.jit`, `@partial(jax.jit, ...)` (plain or
    functools-qualified), the chained `functools.partial(jax.jit,
    ...)(f)` call, and module-level aliases
    `jit = functools.partial(jax.jit, ...)` whose call/decorator
    sites inherit the partial's donate/static kwargs;
  - `self.m()` edges resolve through a class-hierarchy map (the class,
    its ancestors, and its descendants by base-name linking — virtual
    dispatch included) to the actual method bodies;
  - everything else falls back to the old rule: an edge `f -> g`
    exists when `f`'s body calls *any* function named `g`. False
    reachability costs a pragma; a missed hot function costs a
    recompile nobody traced — so unresolvable calls stay
    over-approximate, never dropped.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from deeplearning4j_tpu.analysis.findings import (
    Finding,
    pragma_allows,
)
from deeplearning4j_tpu.analysis.source import (
    SourceFile,
    call_name,
    dotted,
)

# entry points of the step/serving hot paths (thread targets are added
# dynamically — every Thread body is a hot path in this codebase)
ROOT_NAMES = {"fit", "output", "predict", "do_POST", "do_GET"}

# the engine's stable compiled-step entry points, rooted by exact
# qualname: every fit loop now funnels through these, so the walk no
# longer depends on what the surrounding loop method happens to be
# called (ROADMAP: "real call-graph edges once a StepProgram
# abstraction gives it stable entry points")
ROOT_QUALNAMES = {
    "deeplearning4j_tpu/engine/step_program.py::StepProgram.run",
    "deeplearning4j_tpu/engine/step_program.py::StepProgram.run_batch",
    "deeplearning4j_tpu/engine/step_program.py::StepProgram.run_group",
    "deeplearning4j_tpu/engine/harness.py::StepHarness.guarded",
    "deeplearning4j_tpu/engine/harness.py::StepHarness.step_scope",
    "deeplearning4j_tpu/engine/harness.py::StepHarness.session",
    "deeplearning4j_tpu/engine/harness.py::StepHarness.check_preemption",
}

STEP_SHAPED = re.compile(r"step|update|slab")

# files whose host syncs are the *instrument* (the sanctioned sites the
# tentpole names: the StepPhaseProfiler's deliberate sampled sync)
SANCTIONED_SYNC_FILES = ("observability/perf.py",)


@dataclass
class JitSite:
    file: SourceFile
    line: int
    wrapped_name: str
    bound_to: Optional[str]
    donate: bool
    static: bool
    donate_argnums: Optional[Tuple[int, ...]] = None


@dataclass
class _FuncInfo:
    sf: SourceFile
    node: ast.FunctionDef
    qualname: str
    calls: Set[str] = field(default_factory=set)
    self_calls: Set[str] = field(default_factory=set)
    owner_class: Optional[str] = None
    thread_targets: Set[str] = field(default_factory=set)


def _jit_kwargs(call: ast.Call) -> Tuple[bool, bool, Optional[Tuple[int, ...]]]:
    donate = static = False
    nums: Optional[Tuple[int, ...]] = None
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            donate = True
            try:
                v = ast.literal_eval(kw.value)
                if isinstance(v, int):
                    nums = (v,)
                elif isinstance(v, (tuple, list)) and all(
                        isinstance(x, int) for x in v):
                    nums = tuple(v)
            except (ValueError, SyntaxError):
                nums = None
        if kw.arg in ("static_argnums", "static_argnames"):
            static = True
    return donate, static, nums


def _wrapped_name(expr) -> str:
    """Name of the function a jax.jit call wraps, through one level of
    combinator (jax.shard_map(worker, ...), value_and_grad(f))."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Lambda):
        return "<lambda>"
    if isinstance(expr, ast.Call) and expr.args:
        return _wrapped_name(expr.args[0])
    return ""


def _is_jax_jit(func) -> bool:
    d = dotted(func)
    return d == "jax.jit" or d == "jit" or d.endswith(".jit")


def _partial_jit_aliases(sf: SourceFile) -> Dict[str, ast.Call]:
    """Module-level `jit = functools.partial(jax.jit, ...)` aliases:
    name -> the partial() Call carrying the jit kwargs. Call sites of
    the alias are jit sites with those kwargs (a previously-missed
    form — the bench's flagship program is built this way)."""
    aliases: Dict[str, ast.Call] = {}
    for node in sf.tree.body:
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        c = node.value
        if call_name(c) == "partial" and c.args \
                and _is_jax_jit(c.args[0]):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    aliases[t.id] = c
    return aliases


def collect_jit_sites(sources: List[SourceFile]) -> List[JitSite]:
    sites: List[JitSite] = []
    for sf in sources:
        parents: Dict[int, ast.AST] = {}
        for node in ast.walk(sf.tree):
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node
        aliases = _partial_jit_aliases(sf)
        for node in ast.walk(sf.tree):
            # call form: jax.jit(X, ...) — possibly partial(jax.jit, ...)
            # (plain or functools-qualified), or a module-level
            # partial-alias call site `step = jit(step_fn)`
            if isinstance(node, ast.Call):
                jit_call = None
                alias_call = None
                wrapped = ""
                if _is_jax_jit(node.func):
                    jit_call = node
                    wrapped = _wrapped_name(node.args[0]) \
                        if node.args else ""
                elif (call_name(node) == "partial" and node.args
                      and _is_jax_jit(node.args[0])):
                    jit_call = node
                    wrapped = ""          # decorator form fills it in
                elif (isinstance(node.func, ast.Name)
                      and node.func.id in aliases):
                    jit_call = node
                    alias_call = aliases[node.func.id]
                    wrapped = _wrapped_name(node.args[0]) \
                        if node.args else ""
                elif (isinstance(node.func, ast.Call)
                      and call_name(node.func) == "partial"
                      and node.func.args
                      and _is_jax_jit(node.func.args[0])):
                    # chained form: functools.partial(jax.jit, ...)(f)
                    jit_call = node
                    alias_call = node.func
                    wrapped = _wrapped_name(node.args[0]) \
                        if node.args else ""
                if jit_call is None:
                    continue
                donate, static, nums = _jit_kwargs(jit_call)
                if alias_call is not None:
                    # kwargs split between the partial and the call site
                    a_donate, a_static, a_nums = _jit_kwargs(alias_call)
                    donate = donate or a_donate
                    static = static or a_static
                    nums = nums if nums is not None else a_nums
                # decorator? the parent chain reaches a FunctionDef
                # whose decorator_list contains us
                parent = parents.get(id(node))
                bound_to: Optional[str] = None
                if isinstance(parent, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)) \
                        and node in parent.decorator_list:
                    wrapped = parent.name
                    bound_to = parent.name
                elif isinstance(parent, ast.Assign) and wrapped:
                    t = parent.targets[0]
                    if isinstance(t, ast.Name):
                        bound_to = t.id
                    elif isinstance(t, ast.Attribute):
                        bound_to = t.attr
                if not wrapped:
                    continue
                sites.append(JitSite(sf, node.lineno, wrapped, bound_to,
                                     donate, static, nums))
            # bare @jax.jit decorator (an Attribute, not a Call) — or a
            # bare @<alias> decorator carrying the partial's kwargs
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Name) and dec.id in aliases:
                        donate, static, nums = _jit_kwargs(
                            aliases[dec.id])
                        sites.append(JitSite(sf, node.lineno, node.name,
                                             node.name, donate, static,
                                             nums))
                    elif not isinstance(dec, ast.Call) \
                            and _is_jax_jit(dec):
                        sites.append(JitSite(sf, node.lineno, node.name,
                                             node.name, False, False))
    return sites


# ------------------------------------------------------- reachability
def _is_self_call(node: ast.Call) -> bool:
    return (isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "self")


class _ClassGraph:
    """Class-hierarchy map for real `self.m()` edge resolution.

    Classes link by base NAME across the whole package (no imports are
    executed), so `self.m()` resolves to the method bodies of the
    class, its ancestors, and its descendants — virtual dispatch over
    overrides included. Name collisions merge conservatively (both
    hierarchies are related)."""

    def __init__(self, sources: List[SourceFile]):
        # class name -> [{bases, methods{name: node-qualname}}]
        self.entries: Dict[str, List[dict]] = {}
        self.derived: Dict[str, Set[str]] = {}
        for sf in sources:
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                bases = [dotted(b).split(".")[-1] for b in node.bases]
                methods = {
                    ch.name: f"{sf.rel}::{sf.qualname_of(ch)}"
                    for ch in node.body
                    if isinstance(ch, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))}
                self.entries.setdefault(node.name, []).append(
                    {"bases": [b for b in bases if b],
                     "methods": methods})
                for b in bases:
                    if b:
                        self.derived.setdefault(b, set()).add(node.name)

    def related(self, cls: str) -> Set[str]:
        """The class plus ancestors and descendants by name-linking."""
        out: Set[str] = set()
        frontier = [cls]
        while frontier:      # ancestors
            c = frontier.pop()
            if c in out:
                continue
            out.add(c)
            for entry in self.entries.get(c, ()):
                frontier.extend(entry["bases"])
        frontier = [cls]
        down: Set[str] = set()
        while frontier:      # descendants
            c = frontier.pop()
            if c in down:
                continue
            down.add(c)
            frontier.extend(self.derived.get(c, ()))
        return out | down

    def resolve(self, cls: str, method: str) -> List[str]:
        """Qualnames of every `method` body `self.method()` can reach
        from `cls` (empty when the hierarchy defines none — the caller
        falls back to name matching)."""
        return [entry["methods"][method]
                for c in self.related(cls)
                for entry in self.entries.get(c, ())
                if method in entry["methods"]]


def build_reachable(sources: List[SourceFile]) -> Set[str]:
    """Set of function qualnames reachable from the hot-path roots."""
    funcs: List[_FuncInfo] = []
    by_name: Dict[str, List[_FuncInfo]] = {}
    by_qual: Dict[str, _FuncInfo] = {}
    classes = _ClassGraph(sources)
    for sf in sources:
        # AST parents of each function: methods are direct ClassDef
        # children (nested `outer.inner` functions are NOT methods)
        method_owner: Dict[int, str] = {}
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                for ch in node.body:
                    if isinstance(ch, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                        method_owner[id(ch)] = node.name
        for node in sf.functions():
            fi = _FuncInfo(sf, node, f"{sf.rel}::{sf.qualname_of(node)}",
                           owner_class=method_owner.get(id(node)))
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    n = call_name(sub)
                    if n:
                        if _is_self_call(sub):
                            fi.self_calls.add(n)
                        else:
                            fi.calls.add(n)
                    if n == "Thread":
                        for kw in sub.keywords:
                            if kw.arg == "target":
                                tn = dotted(kw.value).split(".")[-1]
                                if tn:
                                    fi.thread_targets.add(tn)
            funcs.append(fi)
            by_name.setdefault(node.name, []).append(fi)
            by_qual[fi.qualname] = fi

    thread_roots: Set[str] = set()
    for fi in funcs:
        thread_roots |= fi.thread_targets
    roots = [fi for fi in funcs
             if fi.node.name in ROOT_NAMES
             or fi.node.name in thread_roots
             or fi.qualname in ROOT_QUALNAMES]

    seen: Set[str] = set()
    frontier = list(roots)
    while frontier:
        fi = frontier.pop()
        if fi.qualname in seen:
            continue
        seen.add(fi.qualname)
        # real edges: self.m() through the class hierarchy when it
        # resolves; name-based fallback when it does not
        for called in fi.self_calls:
            targets = (classes.resolve(fi.owner_class, called)
                       if fi.owner_class else [])
            if targets:
                for q in targets:
                    callee = by_qual.get(q)
                    if callee is not None and callee.qualname not in seen:
                        frontier.append(callee)
                continue
            for callee in by_name.get(called, ()):
                if callee.qualname not in seen:
                    frontier.append(callee)
        for called in fi.calls | fi.thread_targets:
            for callee in by_name.get(called, ()):
                if callee.qualname not in seen:
                    frontier.append(callee)
    return seen


# ------------------------------------------------------------- checks
def _host_sync_marker(node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Attribute):
        if f.attr == "item" and not node.args:
            return ".item()"
        if f.attr == "tolist" and not node.args:
            return ".tolist()"
        if f.attr == "block_until_ready":
            return "block_until_ready"
        if f.attr == "device_get":
            return "jax.device_get"
    if isinstance(f, ast.Name) and f.id == "float" and len(node.args) == 1:
        a = node.args[0]
        if isinstance(a, ast.Call) and isinstance(a.func, ast.Attribute) \
                and a.func.attr == "score":
            return "float(x.score())"
    if isinstance(f, ast.Name) and f.id == "block_until_ready":
        return "block_until_ready"
    return None


def run(sources: List[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    reachable = build_reachable(sources)
    sites = collect_jit_sites(sources)

    # --- jit-missing-donate -------------------------------------------
    for s in sites:
        if not s.donate and STEP_SHAPED.search(s.wrapped_name or ""):
            line = s.line
            if pragma_allows(s.file.allow, line, "jit-missing-donate"):
                continue
            findings.append(Finding(
                "jit-missing-donate", s.file.rel, line,
                f"jax.jit of step-shaped '{s.wrapped_name}' without "
                f"donate_argnums — updated buffers copy instead of "
                f"aliasing",
                symbol=s.wrapped_name))

    # per-module jitted identifiers
    jitted_by_file: Dict[str, Dict[str, JitSite]] = {}
    for s in sites:
        if s.bound_to:
            jitted_by_file.setdefault(s.file.rel, {})[s.bound_to] = s

    for sf in sources:
        jitted = jitted_by_file.get(sf.rel, {})
        in_sanctioned = any(sf.rel.endswith(x)
                            for x in SANCTIONED_SYNC_FILES)
        for fnode in sf.functions():
            qual = f"{sf.rel}::{sf.qualname_of(fnode)}"
            hot = qual in reachable

            # --- jit-host-sync ----------------------------------------
            if hot and not in_sanctioned:
                for sub in ast.walk(fnode):
                    if not isinstance(sub, ast.Call):
                        continue
                    marker = _host_sync_marker(sub)
                    if marker is None:
                        continue
                    if pragma_allows(sf.allow, sub.lineno,
                                     "jit-host-sync"):
                        continue
                    findings.append(Finding(
                        "jit-host-sync", sf.rel, sub.lineno,
                        f"{marker} forces a device->host sync on a "
                        f"hot path (reachable from "
                        f"{'/'.join(sorted(ROOT_NAMES))} or a thread "
                        f"body)",
                        symbol=sf.qualname_of(fnode)))

            # --- jit-traced-python-scalar -----------------------------
            for sub in ast.walk(fnode):
                if not isinstance(sub, ast.Call):
                    continue
                cn = call_name(sub)
                site = jitted.get(cn)
                if site is None or site.static:
                    continue
                for arg in sub.args:
                    label = _scalar_shaped(arg)
                    if label is None:
                        continue
                    if pragma_allows(sf.allow, sub.lineno,
                                     "jit-traced-python-scalar"):
                        continue
                    findings.append(Finding(
                        "jit-traced-python-scalar", sf.rel, sub.lineno,
                        f"{label} passed as a traced argument to "
                        f"jitted '{cn}' — each new value retraces "
                        f"and recompiles",
                        symbol=sf.qualname_of(fnode)))

            # --- jit-use-after-donation -------------------------------
            findings.extend(_use_after_donation(sf, fnode, jitted))
    return findings


def _scalar_shaped(arg) -> Optional[str]:
    if isinstance(arg, ast.Subscript) \
            and isinstance(arg.value, ast.Attribute) \
            and arg.value.attr == "shape":
        return f"{dotted(arg.value)}[...]"
    if isinstance(arg, ast.Attribute) and arg.attr in ("ndim", "size"):
        return dotted(arg)
    if isinstance(arg, ast.Call) and isinstance(arg.func, ast.Name) \
            and arg.func.id == "len":
        return "len(...)"
    return None


def _use_after_donation(sf: SourceFile, fnode,
                        jitted: Dict[str, "JitSite"]) -> List[Finding]:
    donating = {k: s for k, s in jitted.items() if s.donate}
    if not donating:
        return []
    loads: List[Tuple[int, str]] = []
    stores: List[Tuple[int, str]] = []
    calls: List[Tuple[int, str, ast.Call, Set[str]]] = []
    for sub in ast.walk(fnode):
        if isinstance(sub, ast.Name):
            if isinstance(sub.ctx, ast.Load):
                loads.append((sub.lineno, sub.id))
            else:
                stores.append((sub.lineno, sub.id))
        if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call):
            cn = call_name(sub.value)
            if cn in donating:
                targets: Set[str] = set()
                for t in sub.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            targets.add(n.id)
                calls.append((sub.lineno, cn, sub.value, targets))

    findings: List[Finding] = []
    for call_line, cn, call, rebound in calls:
        site = donating[cn]
        positions = site.donate_argnums
        args = call.args
        donated_names = []
        for i, a in enumerate(args):
            if positions is not None and i not in positions:
                continue
            if isinstance(a, ast.Name):
                donated_names.append(a.id)
        for name in donated_names:
            if name in rebound:
                continue
            later_loads = [ln for ln, nm in loads
                           if nm == name and ln > call_line]
            for ln in sorted(later_loads):
                restored = any(sl for sl, nm in stores
                               if nm == name and call_line < sl <= ln)
                if restored:
                    break
                if pragma_allows(sf.allow, ln, "jit-use-after-donation"):
                    break
                findings.append(Finding(
                    "jit-use-after-donation", sf.rel, ln,
                    f"'{name}' was donated to jitted '{cn}' and read "
                    f"again without being rebound — the buffer is "
                    f"invalid after donation",
                    symbol=sf.qualname_of(fnode)))
                break
    return findings
