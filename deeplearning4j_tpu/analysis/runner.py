"""Orchestrates the static passes + the program pass + baseline + CLI.

Used two ways:

  - `tools/analyze.py` (zero-dependency CLI; exit 0 = clean vs
    baseline, 1 = new findings, 2 = usage error). The default run is
    the three AST passes — parsed, never imported, no jax. The
    `--programs` mode adds pass 4 (analysis/program_lint): it imports
    jax (pinned to JAX_PLATFORMS=cpu), builds the representative
    program set (analysis/programs), and lints jaxpr/lowered/compiled
    HLO against each program's declared facts.
  - `tests/test_static_analysis.py` runs `analyze()` inside tier-1 so
    a new violation fails CI with the same report a developer sees
    locally.
"""

from __future__ import annotations

import argparse
import subprocess
import sys
from dataclasses import dataclass, field
from pathlib import Path
from typing import List, Optional, Sequence, Set

from deeplearning4j_tpu.analysis import (
    concurrency_lint,
    conformance,
    jit_lint,
)
from deeplearning4j_tpu.analysis.findings import (
    RULES,
    Baseline,
    Finding,
)
from deeplearning4j_tpu.analysis.source import load_sources

PASSES = ("jit", "concurrency", "conformance")
PROGRAM_PASS = "programs"


@dataclass
class AnalysisResult:
    findings: List[Finding] = field(default_factory=list)
    new: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    stale: List[dict] = field(default_factory=list)
    files_scanned: int = 0
    programs_checked: int = 0
    catalog: Optional[object] = None

    @property
    def clean(self) -> bool:
        return not self.new


def analyze(pkg_dir, root=None, tests_dir=None,
            baseline: Optional[Baseline] = None,
            passes: Sequence[str] = PASSES,
            only: Optional[Set[str]] = None,
            program_records=None) -> AnalysisResult:
    """Run the selected passes over `pkg_dir`.

    `only` (repo-relative paths) limits which files *report* findings
    (--diff mode); the conformance pass still reads the whole package —
    registry equality is a global property — but its findings are
    filtered to the changed files. The "programs" pass lints
    `program_records` (default: the representative set from
    analysis/programs — imports jax)."""
    pkg_dir = Path(pkg_dir)
    root = Path(root) if root is not None else pkg_dir.parent
    ast_passes = [p for p in passes if p != PROGRAM_PASS]
    sources = load_sources(pkg_dir, root) if ast_passes else []
    narrowed = sources if only is None \
        else [sf for sf in sources if sf.rel in only]

    findings: List[Finding] = []
    catalog = None
    programs_checked = 0
    if "jit" in passes:
        all_jit = jit_lint.run(sources)
        findings += [f for f in all_jit
                     if only is None or f.file in only]
    if "concurrency" in passes:
        con, catalog = concurrency_lint.run_with_catalog(narrowed)
        findings += con
    if "conformance" in passes:
        conf = conformance.run(sources, tests_dir=tests_dir)
        findings += [f for f in conf
                     if only is None or f.file in only]
    if PROGRAM_PASS in passes:
        from deeplearning4j_tpu.analysis import program_lint
        records = program_records
        if records is None:
            from deeplearning4j_tpu.analysis import programs
            records = programs.build_default_records()
        programs_checked = len(records)
        prog = program_lint.run(records)
        findings += [f for f in prog
                     if only is None or f.file in only]

    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    res = AnalysisResult(findings=findings,
                         files_scanned=len(narrowed),
                         programs_checked=programs_checked,
                         catalog=catalog)
    if baseline is None:
        res.new = list(findings)
    else:
        res.new, res.suppressed, res.stale = baseline.apply(findings)
    return res


# ----------------------------------------------------------------- CLI
def _git_changed_files(root: Path, ref: str) -> Set[str]:
    files: Set[str] = set()
    for cmd in (["git", "diff", "--name-only", ref, "--", "*.py"],
                ["git", "ls-files", "--others", "--exclude-standard",
                 "--", "*.py"]):
        try:
            out = subprocess.run(cmd, cwd=root, capture_output=True,
                                 text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            continue
        if out.returncode == 0:
            files |= {ln.strip() for ln in out.stdout.splitlines()
                      if ln.strip()}
    return files


def render_catalog(catalog) -> str:
    lines = ["thread/lock catalog:"]
    for t in catalog.threads:
        nm = t.name_literal or ("<dynamic>" if t.named else "<unnamed>")
        lines.append(
            f"  thread {t.file}:{t.line} name={nm} "
            f"daemon={'y' if t.daemon else 'N'} "
            f"bound={t.bound_to or '-'} "
            f"joined={'y' if t.joined else 'N'}")
    for lk in catalog.locks:
        lines.append(f"  {lk.kind.lower():9s} {lk.file}:{lk.line} "
                     f"bound={lk.bound_to or '-'}")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="dl4j-analyze",
        description="static invariant checker for deeplearning4j_tpu "
                    "(JIT hygiene, concurrency discipline, registry "
                    "conformance)")
    ap.add_argument("paths", nargs="*",
                    help="restrict to these files (repo-relative)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: auto from this file)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON (default: "
                         "tools/analyze_baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write all current findings to the baseline "
                         "file and exit 0")
    ap.add_argument("--diff", nargs="?", const="HEAD", default=None,
                    metavar="REF",
                    help="check only files changed vs REF "
                         "(default HEAD) — fast local iteration")
    ap.add_argument("--rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--catalog", action="store_true",
                    help="print the thread/lock catalog")
    ap.add_argument("--passes", default=",".join(PASSES),
                    help=f"comma list of passes (default: all of "
                         f"{','.join(PASSES)})")
    ap.add_argument("--programs", action="store_true",
                    help="run pass 4 (compiled-program lint) over the "
                         "representative program set instead of the "
                         "AST passes — imports jax, pinned to "
                         "JAX_PLATFORMS=cpu")
    args = ap.parse_args(argv)

    if args.rules:
        for r in RULES.values():
            print(f"{r.id:28s} [{r.pass_name}] {r.description}")
        by_kind = {"static": 0, "program": 0, "runtime": 0}
        for r in RULES.values():
            kind = r.pass_name if r.pass_name in by_kind else "static"
            by_kind[kind] += 1
        print(f"{len(RULES)} rules ({by_kind['static']} static, "
              f"{by_kind['program']} program, "
              f"{by_kind['runtime']} runtime sanitizer)")
        return 0

    root = Path(args.root) if args.root else \
        Path(__file__).resolve().parents[2]
    pkg_dir = root / "deeplearning4j_tpu"
    tests_dir = root / "tests"
    if not pkg_dir.is_dir():
        print(f"error: package dir not found under {root}",
              file=sys.stderr)
        return 2
    baseline_path = Path(args.baseline) if args.baseline \
        else root / "tools" / "analyze_baseline.json"

    only: Optional[Set[str]] = None
    if args.paths:
        only = set()
        for p in args.paths:
            rp = Path(p)
            try:
                only.add(rp.resolve().relative_to(
                    root.resolve()).as_posix())
            except ValueError:
                only.add(rp.as_posix())
    if args.diff is not None:
        changed = {f for f in _git_changed_files(root, args.diff)
                   if f.startswith("deeplearning4j_tpu/")}
        only = changed if only is None else (only & changed)
        if not only:
            print("dl4j-analyze: no changed package files vs "
                  f"{args.diff}; nothing to check")
            return 0

    if args.programs:
        import os
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        passes = (PROGRAM_PASS,)
    else:
        passes = tuple(p.strip() for p in args.passes.split(",")
                       if p.strip())
        for p in passes:
            if p not in PASSES:
                print(f"error: unknown pass '{p}'", file=sys.stderr)
                return 2

    baseline = None
    if not args.no_baseline and not args.write_baseline \
            and baseline_path.exists():
        baseline = Baseline.load(baseline_path)

    res = analyze(pkg_dir, root=root, tests_dir=tests_dir,
                  baseline=baseline, passes=passes, only=only)

    if args.write_baseline:
        Baseline.from_findings(res.findings).save(baseline_path)
        print(f"dl4j-analyze: wrote {len(res.findings)} suppressions "
              f"to {baseline_path}")
        return 0

    if args.catalog and res.catalog is not None:
        print(render_catalog(res.catalog))

    for f in res.new:
        print(f.render())
    for e in res.stale:
        print(f"stale baseline entry (violation fixed — remove it): "
              f"{e['rule']} {e['file']} [{e.get('symbol', '')}]")
    by_rule = {}
    for f in res.findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    scanned = (f"{res.programs_checked} programs"
               if PROGRAM_PASS in passes
               else f"{res.files_scanned} files")
    print(f"dl4j-analyze: {len(res.new)} new finding(s), "
          f"{len(res.suppressed)} baselined, {len(res.stale)} stale "
          f"baseline entr(ies); {scanned}, "
          f"{len(RULES)} rules"
          + (f"; by rule: " +
             ", ".join(f"{k}={v}" for k, v in sorted(by_rule.items()))
             if by_rule else ""))
    return 1 if res.new else 0
