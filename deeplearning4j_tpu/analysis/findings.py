"""Rule catalog, findings, and the checked-in baseline.

A `Finding` is one violation of one `Rule` at one source location.
Findings are matched against the baseline (`tools/analyze_baseline.json`)
by *fingerprint* — rule + file + enclosing symbol + message, no line
number — so pre-existing violations stay suppressed across unrelated
edits while NEW violations (or an old one moving to a new function)
fail tier-1. Baseline entries that no longer fire are reported as
stale so the burn-down list shrinks explicitly, never silently.

Inline sanctioning: a source line (or the line directly above it) may
carry

    # analyze: allow=<rule-id>[,<rule-id>] — <reason>

which suppresses those rules for that statement. Pragmas are for sites
that are *correct by design* (the StepPhaseProfiler's deliberate device
sync, the dashboard's host-side rendering); the baseline is for debt.
"""

from __future__ import annotations

import hashlib
import json
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

PRAGMA_RE = re.compile(r"#\s*analyze:\s*allow=([a-z0-9,\-]+)")


@dataclass(frozen=True)
class Rule:
    id: str
    pass_name: str          # "jit" | "concurrency" | "conformance"
                            # | "program" | "runtime"
    description: str


_RULE_LIST = [
    # ---- pass 1: JIT / recompile hygiene ----
    Rule("jit-host-sync", "jit",
         "host-sync call (.item()/.tolist()/block_until_ready/"
         "jax.device_get/float(x.score())) in a function reachable from "
         "the step/serving hot paths, outside the sanctioned sites"),
    Rule("jit-missing-donate", "jit",
         "jax.jit call site on a step-shaped function (name matches "
         "step/update/slab) without donate_argnums/donate_argnames — "
         "the updated buffers copy instead of aliasing"),
    Rule("jit-traced-python-scalar", "jit",
         "shape-derived or Python-scalar expression (x.shape[i], len(), "
         ".ndim) passed as a traced argument to a jitted callable — "
         "every new value retraces and recompiles the program"),
    Rule("jit-use-after-donation", "jit",
         "argument donated to a jitted call is read again afterwards "
         "without being rebound — donated buffers are invalidated"),
    # ---- pass 2: concurrency ----
    Rule("thr-unnamed-thread", "concurrency",
         "threading.Thread(...) without name= — anonymous threads make "
         "hang forensics (faulthandler dumps, watchdog reports) useless"),
    Rule("thr-non-daemon-thread", "concurrency",
         "threading.Thread(...) that is not daemon=True — a non-daemon "
         "background thread turns any crash into a hang at exit"),
    Rule("thr-orphan-thread", "concurrency",
         "thread started with no join-or-ledger shutdown path (not "
         "bound, or bound but never joined/tracked) — shutdown cannot "
         "prove the thread is gone"),
    Rule("thr-blocking-under-lock", "concurrency",
         "blocking call (sleep/open/join/socket) or metric/fault "
         "emission while holding a registry lock — serializes the hot "
         "path and invites lock-order inversions"),
    # ---- pass 3: registry conformance ----
    Rule("reg-unregistered-fault-point", "conformance",
         'fire("...") literal not listed in faults.REGISTERED_POINTS'),
    Rule("reg-unfired-fault-point", "conformance",
         "REGISTERED_POINTS entry with no fire(...) site in the package"),
    Rule("reg-unregistered-metric", "conformance",
         "emitted or referenced dl4j_* metric literal not listed in "
         "metrics.REGISTERED_METRICS (nor a registered-name prefix)"),
    Rule("reg-unemitted-metric", "conformance",
         "REGISTERED_METRICS entry (non-derived) with no emission site"),
    Rule("reg-swallowed-exception", "conformance",
         "bare `except Exception: pass` (or continue) without the "
         "guarded-telemetry annotation — silent failure swallowing"),
    Rule("reg-untested-registry-name", "conformance",
         "registered fault point or metric name not named by any test"),
    Rule("reg-unregistered-program-rule", "conformance",
         'Rule("prog-...") in the catalog not listed in the pinned '
         "REGISTERED_PROGRAM_RULES registry (analysis/program_lint.py)"),
    Rule("reg-unimplemented-program-rule", "conformance",
         "REGISTERED_PROGRAM_RULES entry with no Rule(...) catalog "
         "definition — a pinned program rule nothing implements"),
    # ---- pass 4: compiled-program lint (jaxpr / lowered / compiled HLO) ----
    Rule("prog-fp32-matmul-under-policy", "program",
         "dot_general/conv op computing in f32 inside a program whose "
         "declared precision_policy is bf16/f16 — the matmul units run "
         "at half throughput and the policy is silently violated"),
    Rule("prog-unhonored-donation", "program",
         "argument marked in donate_argnums but absent from the "
         "executable's input-output alias map — the caller loses the "
         "buffer AND pays the copy (silent 2x memory)"),
    Rule("prog-transpose-churn", "program",
         "transpose/copy op bytes above threshold in the compiled "
         "program — NHWC<->NCHW (or batch<->time major) layout thrash "
         "burning memory bandwidth the roofline charges to the model"),
    Rule("prog-hidden-host-transfer", "program",
         "outfeed/infeed/host-callback edge inside a hot compiled "
         "program — every call blocks the device on the host"),
    Rule("prog-dead-output", "program",
         "computed program output no caller consumes — the program "
         "pays flops and a device->host edge for a value that is "
         "dropped on the floor"),
    Rule("prog-excess-padding", "program",
         "serving pow2 bucket fill ratio below threshold — most of "
         "every dispatched batch is padding, so the MXU runs mostly "
         "dead rows"),
    Rule("prog-unsharded-optimizer-state", "program",
         "mesh-registered (ZeRO-1) program whose lowered module does "
         "not shard its declared optimizer-state argument (missing "
         "device sharding annotations or donation/aliasing) — the "
         "state is silently replicated, n x the promised memory"),
    # ---- runtime sanitizers (DL4J_TPU_SANITIZE=locks) ----
    Rule("san-lock-order-cycle", "runtime",
         "cyclic lock-acquisition order observed across threads — a "
         "potential deadlock (A held while taking B, elsewhere B held "
         "while taking A)"),
    Rule("san-long-held-lock", "runtime",
         "lock held longer than the sanitizer threshold — a blocking "
         "operation is living inside a critical section"),
]

RULES: Dict[str, Rule] = {r.id: r for r in _RULE_LIST}


@dataclass
class Finding:
    rule: str
    file: str               # repo-relative posix path
    line: int
    message: str            # MUST NOT embed line numbers (fingerprint)
    symbol: str = ""        # enclosing function qualname, "" at module level

    def fingerprint(self) -> str:
        raw = f"{self.rule}|{self.file}|{self.symbol}|{self.message}"
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.file}:{self.line}: {self.rule}{sym} {self.message}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "file": self.file, "line": self.line,
                "symbol": self.symbol, "message": self.message,
                "fingerprint": self.fingerprint()}


# ------------------------------------------------------------- baseline
@dataclass
class Baseline:
    entries: List[dict] = field(default_factory=list)

    @classmethod
    def load(cls, path) -> "Baseline":
        with open(path) as f:
            data = json.load(f)
        return cls(entries=list(data.get("suppressions", [])))

    def save(self, path) -> None:
        with open(path, "w") as f:
            json.dump({"version": 1,
                       "note": "pre-existing dl4j-analyze findings, "
                               "suppressed pending burn-down; new "
                               "violations fail tier-1",
                       "suppressions": self.entries}, f, indent=2,
                      sort_keys=False)
            f.write("\n")

    @classmethod
    def from_findings(cls, findings: Iterable[Finding]) -> "Baseline":
        return cls(entries=[f.to_dict() for f in findings])

    def apply(self, findings: List[Finding]
              ) -> Tuple[List[Finding], List[Finding], List[dict]]:
        """Split findings into (new, suppressed) and report stale
        baseline entries. Multiplicity-aware: two identical findings
        need two baseline entries."""
        budget: Dict[str, int] = {}
        for e in self.entries:
            budget[e["fingerprint"]] = budget.get(e["fingerprint"], 0) + 1
        new, suppressed = [], []
        for f in findings:
            fp = f.fingerprint()
            if budget.get(fp, 0) > 0:
                budget[fp] -= 1
                suppressed.append(f)
            else:
                new.append(f)
        stale = []
        for e in self.entries:
            if budget.get(e["fingerprint"], 0) > 0:
                budget[e["fingerprint"]] -= 1
                stale.append(e)
        return new, suppressed, stale


def parse_pragmas(text: str) -> Dict[int, set]:
    """Map 1-based line number -> set of allowed rule ids."""
    allow: Dict[int, set] = {}
    for i, line in enumerate(text.splitlines(), start=1):
        m = PRAGMA_RE.search(line)
        if m:
            allow[i] = {r.strip() for r in m.group(1).split(",")
                        if r.strip()}
    return allow


def pragma_allows(allow: Dict[int, set], line: int, rule: str) -> bool:
    """A pragma on the flagged line, or on the line directly above it,
    sanctions the site."""
    return (rule in allow.get(line, ()) or
            rule in allow.get(line - 1, ()))
