"""Keras HDF5 model importer.

Parity: deeplearning4j-modelimport
(nn/modelimport/keras/KerasModelImport.java:48-119 — entry points;
KerasModel.java / KerasSequentialModel.java — config+weights mapping;
KerasLayer.java — the supported layer-type table; Hdf5Archive.java — the
HDF5 reader, replaced here by h5py per SURVEY §2.3).

Reads whole-model HDF5 files (`model.save("m.h5")`): `model_config` JSON
attr + `model_weights/` groups (+ optional `training_config` for the
loss). Supports both the legacy Keras-2-style and current Keras-3 weight
path layouts by following each layer group's `weight_names` attr and
falling back to a dataset walk.

Layer mappings (reference table: KerasLayer.java):
  InputLayer, Dense, Conv2D, Conv1D, MaxPooling2D, AveragePooling2D,
  GlobalMaxPooling2D, GlobalAveragePooling2D, Flatten (auto CnnToFF
  preprocessor), Dropout, Activation, BatchNormalization, Embedding,
  LSTM, ZeroPadding2D, Add/Concatenate/... merge layers (functional
  graphs), Loss (from training_config); LRN via the built-in custom
  mapping (the KerasLRN role), and arbitrary custom layer classes via
  `register_custom_layer` (the KerasLayer.registerCustomLayer role,
  KerasLayer.java:261).

Dim ordering: this framework is natively NHWC == TensorFlow
channels_last, so Conv kernels (kh, kw, in, out) and Dense kernels
(in, out) copy without transposition (the reference needed
TensorFlowCnnToFeedForwardPreProcessor for this; here it is the identity
case). channels_first models are rejected with a clear error.
LSTM gate order is remapped keras [i, f, g, o] -> ours [i, f, o, g].
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf import InputType
from deeplearning4j_tpu.nn.conf.graph_conf import GraphBuilder
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.layers import (
    LSTM,
    ActivationLayer,
    BatchNormalization,
    Convolution1DLayer,
    ConvolutionLayer,
    DenseLayer,
    DropoutLayer,
    EmbeddingLayer,
    GlobalPoolingLayer,
    LocalResponseNormalization,
    OutputLayer,
    SubsamplingLayer,
    ZeroPaddingLayer,
)
from deeplearning4j_tpu.nn.conf.graph_vertices import (
    ElementWiseVertex,
    LastTimeStepVertex,
    MergeVertex,
    PreprocessorVertex,
)
from deeplearning4j_tpu.nn.conf.preprocessors import (
    CnnToFeedForwardPreProcessor,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


class KerasImportError(ValueError):
    """Unsupported or malformed Keras model (ref:
    InvalidKerasConfigurationException / UnsupportedKerasConfigurationException)."""


_ACTIVATIONS = {
    "linear": "identity",
    "relu": "relu",
    "relu6": "relu6",
    "elu": "elu",
    "selu": "selu",
    "tanh": "tanh",
    "sigmoid": "sigmoid",
    "hard_sigmoid": "hardsigmoid",
    "softmax": "softmax",
    "softplus": "softplus",
    "softsign": "softsign",
    "swish": "swish",
    "silu": "swish",
    "gelu": "gelu",
    "leaky_relu": "leakyrelu",
    "mish": "mish",
}

_LOSSES = {
    "categorical_crossentropy": "mcxent",
    "sparse_categorical_crossentropy": "mcxent",
    "binary_crossentropy": "xent",
    "mean_squared_error": "mse",
    "mse": "mse",
    "mean_absolute_error": "mae",
    "mae": "mae",
    "mean_absolute_percentage_error": "mape",
    "mean_squared_logarithmic_error": "msle",
    "hinge": "hinge",
    "squared_hinge": "squared_hinge",
    "poisson": "poisson",
    "kullback_leibler_divergence": "kl_divergence",
    "kl_divergence": "kl_divergence",
    "cosine_proximity": "cosine_proximity",
}


def _map_activation(name) -> str:
    if name is None:
        return "identity"
    if isinstance(name, dict):   # serialized Activation object
        name = name.get("class_name", "linear")
    key = str(name).lower()
    if key not in _ACTIVATIONS:
        raise KerasImportError(
            f"Unsupported Keras activation '{name}'. "
            f"Supported: {sorted(_ACTIVATIONS)}")
    return _ACTIVATIONS[key]


def _map_loss(name: Optional[str]) -> Optional[str]:
    if name is None:
        return None
    if isinstance(name, dict):
        name = (name.get("config") or {}).get("name") or name.get(
            "class_name", "")
    key = str(name).lower()
    return _LOSSES.get(key)


def _check_channels_last(cfg: dict, cls: str):
    df = cfg.get("data_format", "channels_last")
    if df not in (None, "channels_last"):
        raise KerasImportError(
            f"{cls}: data_format='{df}' (Theano/channels_first ordering) "
            "is not supported; re-save the model with channels_last")


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (list, tuple)):
        return int(v[0]), int(v[1] if len(v) > 1 else v[0])
    return int(v), int(v)


def _input_type_from_shape(shape) -> InputType:
    """batch_shape/batch_input_shape (leading None) -> InputType."""
    dims = [d for d in shape[1:]]
    if len(dims) == 3:
        h, w, c = dims
        return InputType.convolutional(int(h), int(w), int(c))
    if len(dims) == 2:
        t, f = dims
        return InputType.recurrent(int(f), None if t is None else int(t))
    if len(dims) == 1:
        return InputType.feed_forward(int(dims[0]))
    raise KerasImportError(f"Unsupported Keras input shape {shape}")


# --------------------------------------------------------------------- HDF5

def _read_archive(path: str):
    import h5py

    with h5py.File(path, "r") as f:
        raw = f.attrs.get("model_config")
        if raw is None:
            raise KerasImportError(
                f"{path}: no model_config attr — not a whole-model Keras "
                "HDF5 file (weights-only files need the architecture too)")
        if isinstance(raw, bytes):
            raw = raw.decode("utf-8")
        model_config = json.loads(raw)
        tc = f.attrs.get("training_config")
        training_config = None
        if tc is not None:
            training_config = json.loads(
                tc.decode("utf-8") if isinstance(tc, bytes) else tc)

        weights: Dict[str, Dict[str, np.ndarray]] = {}
        mw = f.get("model_weights", f)   # some files are rooted at /
        for lname in mw:
            grp = mw[lname]
            if not hasattr(grp, "attrs"):
                continue
            found: Dict[str, np.ndarray] = {}
            wnames = grp.attrs.get("weight_names")
            if wnames is not None and len(wnames):
                for wn in wnames:
                    wn = wn.decode() if isinstance(wn, bytes) else str(wn)
                    ds = grp.get(wn) or f.get(wn) or mw.get(wn)
                    if ds is not None:
                        leaf = wn.split("/")[-1].split(":")[0]
                        found[leaf] = np.asarray(ds)
            else:
                def walk(g):
                    import h5py as _h
                    for k in g:
                        it = g[k]
                        if isinstance(it, _h.Dataset):
                            found[k.split(":")[0]] = np.asarray(it)
                        else:
                            walk(it)
                walk(grp)
            if found:
                weights[lname] = found
    return model_config, weights, training_config


# ----------------------------------------------------------- layer mapping

# Custom-layer registration (the KerasLayer.registerCustomLayer role —
# KerasLayer.java:261 throws on unknown types unless a custom mapping
# was registered; the reference ships KerasLRN/KerasPoolHelper as
# built-in customs for Caffe-converted models).
_CUSTOM_LAYERS: Dict[str, Tuple[Any, Any]] = {}


def register_custom_layer(class_name: str, mapper,
                          weight_mapper=None) -> None:
    """Register an import mapping for a custom Keras layer class.

    mapper(cfg, is_output=..., loss=...) must return a framework layer
    (or 'flatten' / None skip markers, like _map_layer). Optional
    weight_mapper(layer, weights_dict) -> (params, state) overrides the
    built-in weight copy for layers the mapper returns."""
    _CUSTOM_LAYERS[class_name] = (mapper, weight_mapper)


def unregister_custom_layer(class_name: str) -> None:
    _CUSTOM_LAYERS.pop(class_name, None)


def _map_lrn(cfg: dict, *, is_output: bool, loss: Optional[str]):
    """Built-in custom mapping for LRN layers from Caffe-converted
    models (the KerasLRN role). Accepts both Caffe-ish (k/n/alpha/beta)
    and tf.nn.local_response_normalization (bias/depth_radius) naming."""
    if "n" in cfg:
        n = int(cfg["n"])            # full window (Caffe naming)
    elif "depth_radius" in cfg:
        n = 2 * int(cfg["depth_radius"]) + 1   # radius -> window
    else:
        n = 5
    return LocalResponseNormalization(
        k=float(cfg.get("k", cfg.get("bias", 2.0))),
        n=n,
        alpha=float(cfg.get("alpha", 1e-4)),
        beta=float(cfg.get("beta", 0.75)))


register_custom_layer("LRN", _map_lrn)
register_custom_layer("LocalResponseNormalization", _map_lrn)


def _map_layer(cls: str, cfg: dict, *, is_output: bool, loss: Optional[str]):
    """Return a framework layer, 'flatten' (skip marker), or None (skip).

    Ref: the per-type Keras*.java mapping classes
    (KerasDense.java, KerasConvolution.java, KerasLstm.java, ...)."""
    if cls == "Dense":
        act = _map_activation(cfg.get("activation"))
        if is_output:
            return OutputLayer(n_out=int(cfg["units"]), activation=act,
                               loss=loss or "mcxent")
        return DenseLayer(n_out=int(cfg["units"]), activation=act)
    if cls in ("Conv2D", "Convolution2D"):
        _check_channels_last(cfg, cls)
        kh, kw = _pair(cfg.get("kernel_size", 3))
        sh, sw = _pair(cfg.get("strides", 1))
        same = cfg.get("padding", "valid") == "same"
        dh, dw = _pair(cfg.get("dilation_rate", 1))
        return ConvolutionLayer(
            n_out=int(cfg["filters"]), kernel_size=(kh, kw),
            stride=(sh, sw), dilation=(dh, dw),
            convolution_mode="same" if same else "truncate",
            padding=(0, 0),
            activation=_map_activation(cfg.get("activation")))
    if cls in ("Conv1D", "Convolution1D"):
        _check_channels_last(cfg, cls)
        pad = cfg.get("padding", "valid")
        if pad == "causal":
            raise KerasImportError(
                "Conv1D padding='causal' is not supported (no "
                "reference counterpart; pre-pad with ZeroPadding1D)")
        d = cfg.get("dilation_rate", 1)
        d = d[0] if isinstance(d, (list, tuple)) else d
        if int(d) != 1 or int(cfg.get("groups", 1)) != 1:
            raise KerasImportError(
                "Conv1D with dilation_rate/groups != 1 has no "
                "Convolution1DLayer counterpart")
        k = cfg.get("kernel_size", 3)
        k = int(k[0]) if isinstance(k, (list, tuple)) else int(k)
        s = cfg.get("strides", 1)
        s = int(s[0]) if isinstance(s, (list, tuple)) else int(s)
        return Convolution1DLayer(
            n_out=int(cfg["filters"]), kernel_size=k, stride=s,
            convolution_mode="same" if pad == "same" else "truncate",
            padding=0,
            activation=_map_activation(cfg.get("activation")))
    if cls in ("MaxPooling2D", "AveragePooling2D"):
        _check_channels_last(cfg, cls)
        kh, kw = _pair(cfg.get("pool_size", 2))
        strides = cfg.get("strides") or (kh, kw)
        sh, sw = _pair(strides)
        same = cfg.get("padding", "valid") == "same"
        return SubsamplingLayer(
            pooling_type="max" if cls.startswith("Max") else "avg",
            kernel_size=(kh, kw), stride=(sh, sw),
            convolution_mode="same" if same else "truncate")
    if cls in ("GlobalMaxPooling2D", "GlobalAveragePooling2D",
               "GlobalMaxPooling1D", "GlobalAveragePooling1D"):
        return GlobalPoolingLayer(
            pooling_type="max" if "Max" in cls else "avg")
    if cls == "Flatten":
        return "flatten"
    if cls == "Dropout":
        return DropoutLayer(dropout=float(cfg.get("rate", 0.5)))
    if cls == "Activation":
        return ActivationLayer(
            activation=_map_activation(cfg.get("activation")))
    if cls == "BatchNormalization":
        axis = cfg.get("axis", -1)
        if isinstance(axis, (list, tuple)) and len(axis) == 1:
            axis = axis[0]
        if axis not in (-1, 3):
            # this framework normalizes the trailing (channel) axis; a
            # non-last axis is the channels_first BN layout
            raise KerasImportError(
                f"BatchNormalization axis={axis} is not the trailing "
                "axis (channels_first layout?); only channels_last "
                "models are supported")
        return BatchNormalization(
            eps=float(cfg.get("epsilon", 1e-3)),
            decay=float(cfg.get("momentum", 0.99)))
    if cls == "Embedding":
        return EmbeddingLayer(n_in=int(cfg["input_dim"]),
                              n_out=int(cfg["output_dim"]))
    if cls == "LSTM":
        return LSTM(n_out=int(cfg["units"]),
                    activation=_map_activation(cfg.get("activation", "tanh")),
                    gate_activation=_map_activation(
                        cfg.get("recurrent_activation", "sigmoid")))
    if cls == "ZeroPadding2D":
        _check_channels_last(cfg, cls)
        p = cfg.get("padding", 1)
        if isinstance(p, (list, tuple)) and len(p) == 2 \
                and isinstance(p[0], (list, tuple)):
            (t, b), (l, r) = p
            return ZeroPaddingLayer(padding=(int(t), int(b), int(l), int(r)))
        ph, pw = _pair(p)
        return ZeroPaddingLayer(padding=(ph, pw))
    if cls == "InputLayer":
        return None
    # keras-3 registered custom classes serialize as "package>Name";
    # match both the qualified and the bare class name
    bare = cls.rsplit(">", 1)[-1]
    if cls in _CUSTOM_LAYERS or bare in _CUSTOM_LAYERS:
        mapper, wmap = _CUSTOM_LAYERS.get(cls) or _CUSTOM_LAYERS[bare]
        layer = mapper(cfg, is_output=is_output, loss=loss)
        if wmap is not None and layer is not None \
                and not isinstance(layer, str):
            layer._keras_weight_mapper = wmap
        return layer
    raise KerasImportError(
        f"Unsupported Keras layer type '{cls}' "
        "(ref KerasLayer.java:261 supported-type table; register a "
        "mapping with modelimport.keras.register_custom_layer)")


_MERGE_CLASSES = {"Add": "add", "Subtract": "subtract",
                  "Multiply": "product", "Average": "average",
                  "Maximum": "max"}


# -------------------------------------------------------------- weight copy

def _reorder_lstm(k: np.ndarray, H: int) -> np.ndarray:
    """keras gate blocks [i, f, g, o] -> ours [i, f, o, g] (last axis)."""
    i, f, g, o = (k[..., 0:H], k[..., H:2 * H],
                  k[..., 2 * H:3 * H], k[..., 3 * H:4 * H])
    return np.concatenate([i, f, o, g], axis=-1)


def _params_from_keras(layer, w: Dict[str, np.ndarray]):
    """Map a keras layer's weight dict onto (params, state) for `layer`."""
    dt = jnp.float32
    wmap = getattr(layer, "_keras_weight_mapper", None)
    if wmap is not None:
        return wmap(layer, w)
    if isinstance(layer, Convolution1DLayer):
        # keras Conv1D kernel [k, Cin, Cout] == ours, no transposition
        return ({"W": jnp.asarray(w["kernel"], dt),
                 "b": jnp.asarray(
                     w.get("bias", np.zeros(w["kernel"].shape[-1])), dt)},
                None)
    if isinstance(layer, (DenseLayer, OutputLayer)):
        return ({"W": jnp.asarray(w["kernel"], dt),
                 "b": jnp.asarray(w.get("bias",
                                        np.zeros(w["kernel"].shape[1])), dt)},
                None)
    if isinstance(layer, ConvolutionLayer):
        return ({"W": jnp.asarray(w["kernel"], dt),
                 "b": jnp.asarray(
                     w.get("bias", np.zeros(w["kernel"].shape[-1])), dt)},
                None)
    if isinstance(layer, BatchNormalization):
        c = w["gamma"].shape[0] if "gamma" in w else \
            w["moving_mean"].shape[0]
        params = {"gamma": jnp.asarray(w.get("gamma", np.ones(c)), dt),
                  "beta": jnp.asarray(w.get("beta", np.zeros(c)), dt)}
        state = {"mean": jnp.asarray(w["moving_mean"], dt),
                 "var": jnp.asarray(w["moving_variance"], dt)}
        return params, state
    if isinstance(layer, EmbeddingLayer):
        emb = w["embeddings"]
        return ({"W": jnp.asarray(emb, dt),
                 "b": jnp.zeros((emb.shape[1],), dt)}, None)
    if isinstance(layer, LSTM):
        H = layer.n_out
        return ({"W": jnp.asarray(_reorder_lstm(w["kernel"], H), dt),
                 "RW": jnp.asarray(
                     _reorder_lstm(w["recurrent_kernel"], H), dt),
                 "b": jnp.asarray(
                     _reorder_lstm(w.get("bias", np.zeros(4 * H)), H), dt)},
                None)
    return None, None


# ------------------------------------------------------------- entry points

class KerasModelImport:
    """Entry points mirroring KerasModelImport.java:48-119."""

    @staticmethod
    def import_keras_sequential_model_and_weights(
            path: str, enforce_training_config: bool = False
    ) -> MultiLayerNetwork:
        model_config, weights, training_config = _read_archive(path)
        if model_config.get("class_name") != "Sequential":
            raise KerasImportError(
                f"{path} is not a Sequential model; use "
                "import_keras_model_and_weights")
        return _build_sequential(model_config, weights, training_config,
                                 enforce_training_config)

    @staticmethod
    def import_keras_model_and_weights(
            path: str, enforce_training_config: bool = False):
        """Sequential -> MultiLayerNetwork; Functional -> ComputationGraph."""
        model_config, weights, training_config = _read_archive(path)
        if model_config.get("class_name") == "Sequential":
            return _build_sequential(model_config, weights, training_config,
                                     enforce_training_config)
        return _build_functional(model_config, weights, training_config,
                                 enforce_training_config)

    @staticmethod
    def import_keras_model_configuration(path: str):
        """Configuration only, no weights (ref :119 overloads)."""
        model_config, _, training_config = _read_archive(path)
        if model_config.get("class_name") == "Sequential":
            net = _build_sequential(model_config, {}, training_config, False)
            return net.conf
        net = _build_functional(model_config, {}, training_config, False)
        return net.conf


def _loss_from_training_config(training_config, enforce: bool):
    loss = _map_loss(training_config.get("loss")) if training_config else None
    if loss is None and enforce:
        raise KerasImportError(
            "no (supported) loss in training_config but "
            "enforce_training_config=True")
    return loss


def _build_sequential(model_config, weights, training_config, enforce):
    cfg = model_config.get("config")
    layer_list = cfg["layers"] if isinstance(cfg, dict) else cfg
    loss = _loss_from_training_config(training_config, enforce)

    input_type = None
    mapped: List[Tuple[Optional[str], Any]] = []   # (keras name, layer)
    n_real = sum(1 for lc in layer_list
                 if lc["class_name"] not in
                 ("InputLayer", "Flatten", "Dropout", "Activation"))
    seen_real = 0
    for lc in layer_list:
        cls = lc["class_name"]
        c = lc.get("config", {})
        if cls == "InputLayer":
            shape = c.get("batch_shape") or c.get("batch_input_shape")
            input_type = _input_type_from_shape(shape)
            continue
        if input_type is None and (
                c.get("batch_input_shape") or c.get("batch_shape")):
            input_type = _input_type_from_shape(
                c.get("batch_input_shape") or c.get("batch_shape"))
        if cls == "LSTM" and not c.get("return_sequences", False):
            raise KerasImportError(
                "LSTM with return_sequences=False has no MultiLayerNetwork "
                "equivalent (needs last-time-step selection); import via "
                "import_keras_model_and_weights on a functional model — "
                "the importer maps it to a LastTimeStep vertex")
        is_out = False
        if cls not in ("Flatten", "Dropout", "Activation"):
            seen_real += 1
            is_out = seen_real == n_real and cls == "Dense"
        layer = _map_layer(cls, c, is_output=is_out, loss=loss)
        if layer == "flatten" or layer is None:
            continue   # CnnToFF preprocessor is auto-inserted
        mapped.append((c.get("name"), layer))

    if input_type is None:
        raise KerasImportError("could not determine the model input shape")

    lb = (NeuralNetConfiguration.Builder().updater("sgd")
          .learning_rate(1e-3).list())
    for _, layer in mapped:
        lb = lb.layer(layer)
    conf = lb.set_input_type(input_type).build()
    net = MultiLayerNetwork(conf).init()
    _copy_weights_mln(net, mapped, weights)
    return net


def _copy_weights_mln(net, mapped, weights):
    for i, (kname, layer) in enumerate(mapped):
        w = weights.get(kname)
        if not w:
            continue
        params, state = _params_from_keras(layer, w)
        if params is not None:
            _check_shapes(kname, net.params[i], params)
            net.params[i] = params
        if state is not None:
            _check_shapes(kname, net.states[i], state)
            net.states[i] = state


def _check_shapes(name, have, want):
    import jax

    h = jax.tree_util.tree_map(lambda a: a.shape, have)
    w = jax.tree_util.tree_map(lambda a: a.shape, want)
    if h != w:
        raise KerasImportError(
            f"weight shape mismatch for layer '{name}': model expects {h}, "
            f"HDF5 provides {w}")


# ----------------------------------------------------------- functional API

def _inbound_shapes(node) -> List[Optional[list]]:
    """Collect tensor shapes attached to keras-3 inbound nodes (absent in
    keras-2 configs)."""
    out: List[Optional[list]] = []

    def rec(v):
        if isinstance(v, dict):
            cfgd = v.get("config") if isinstance(v.get("config"), dict) \
                else None
            if cfgd and "keras_history" in cfgd:
                out.append(cfgd.get("shape"))
                return
            for vv in v.values():
                rec(vv)
        elif isinstance(v, (list, tuple)):
            for vv in v:
                rec(vv)

    rec(node)
    return out


def _inbound_names(node) -> List[str]:
    """Parse inbound layer names from Keras 2 ([[name,0,0,{}],...]) or
    Keras 3 ({'args': [... keras_history ...]}) node formats."""
    out: List[str] = []

    def rec(v):
        if isinstance(v, dict):
            if "keras_history" in v:
                out.append(v["keras_history"][0])
                return
            kh = (v.get("config") or {}).get("keras_history")
            if kh:
                out.append(kh[0])
                return
            for vv in v.values():
                rec(vv)
        elif isinstance(v, (list, tuple)):
            if (len(v) >= 3 and isinstance(v[0], str)
                    and isinstance(v[1], int)):
                out.append(v[0])
                return
            for vv in v:
                rec(vv)

    rec(node)
    return out


def _build_functional(model_config, weights, training_config, enforce):
    cfg = model_config["config"]
    layer_list = cfg["layers"]
    loss = _loss_from_training_config(training_config, enforce)
    # normalize: output_layers is [name,0,0] / [[name,0,0],...] / keras-3
    # dicts — _inbound_names parses all three
    out_names: List[str] = []
    for n in _inbound_names(cfg.get("output_layers", [])):
        if n not in out_names:
            out_names.append(n)

    gb = GraphBuilder(NeuralNetConfiguration.Builder()
                      .updater("sgd").learning_rate(1e-3))
    input_names: List[str] = []
    input_types: List[InputType] = []
    mapped: Dict[str, Any] = {}
    for lc in layer_list:
        cls = lc["class_name"]
        c = lc.get("config", {})
        name = c.get("name") or lc.get("name")
        inbound = _inbound_names(lc.get("inbound_nodes", []))
        # dedupe preserving order
        seen = set()
        inbound = [n for n in inbound
                   if not (n in seen or seen.add(n))]
        if cls == "InputLayer":
            shape = c.get("batch_shape") or c.get("batch_input_shape")
            input_names.append(name)
            input_types.append(_input_type_from_shape(shape))
            continue
        def resolve(names):
            return [mapped[n][1] if isinstance(mapped.get(n), tuple)
                    and mapped[n][0] == "alias" else n for n in names]

        if cls in _MERGE_CLASSES:
            gb.add_vertex(name, ElementWiseVertex(op=_MERGE_CLASSES[cls]),
                          *resolve(inbound))
            continue
        if cls == "Concatenate":
            gb.add_vertex(name, MergeVertex(), *resolve(inbound))
            continue
        is_out = name in out_names and cls == "Dense"
        layer = _map_layer(cls, c, is_output=is_out, loss=loss)
        if layer == "flatten":
            # with a known 4D input shape, Flatten is a real reshape node
            # (a merge downstream must see the flattened vector); with an
            # already-flat input it is transparent
            shape4 = next((sh for sh in _inbound_shapes(
                lc.get("inbound_nodes", [])) if sh and len(sh) == 4), None)
            if shape4 is not None:
                h, w, ch = (int(d) for d in shape4[1:])
                gb.add_vertex(name, PreprocessorVertex(
                    preprocessor=CnnToFeedForwardPreProcessor(
                        height=h, width=w, channels=ch)),
                    *resolve(inbound))
            else:
                mapped[name] = ("alias", resolve(inbound)[0])
            continue
        if layer is None:
            mapped[name] = ("alias", resolve(inbound)[0])
            continue
        if cls == "LSTM" and not c.get("return_sequences", False):
            # keras folds last-step selection into the layer; here it is
            # an explicit LastTimeStep vertex named after the keras layer
            seq_name = name + "__seq"
            gb.add_layer(seq_name, layer, *resolve(inbound))
            gb.add_vertex(name, LastTimeStepVertex(), seq_name)
            mapped[name] = ("layer", layer, seq_name)
            continue
        gb.add_layer(name, layer, *resolve(inbound))
        mapped[name] = ("layer", layer, name)

    # resolve aliases in output names
    outs = [mapped[n][1] if isinstance(mapped.get(n), tuple)
            and mapped[n][0] == "alias" else n for n in out_names]
    gb.add_inputs(*input_names)
    gb.set_outputs(*outs)
    gb.set_input_types(**dict(zip(input_names, input_types)))
    conf = gb.build()
    net = ComputationGraph(conf).init()
    for name, entry in mapped.items():
        if entry[0] != "layer":
            continue
        node_name = entry[2]
        w = weights.get(name)
        if not w:
            continue
        params, state = _params_from_keras(entry[1], w)
        if params is not None:
            _check_shapes(name, net.params[node_name], params)
            net.params[node_name] = params
        if state is not None:
            _check_shapes(name, net.states[node_name], state)
            net.states[node_name] = state
    return net
