from deeplearning4j_tpu.modelimport.keras import (  # noqa: F401
    KerasModelImport,
    KerasImportError,
)
