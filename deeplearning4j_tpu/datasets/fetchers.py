"""Dataset fetchers/iterators: MNIST (IDX format), Iris, CIFAR-10
(parity: deeplearning4j-core datasets/fetchers/MnistDataFetcher.java,
base/MnistFetcher.java:48-59 download+cache,
datasets/iterator/impl/{Mnist,Iris,Cifar}DataSetIterator.java).

Download behavior: the reference fetches over HTTP and caches under
~/.deeplearning4j. This build looks for cached files first
($DL4J_TPU_DATA_DIR or ~/.deeplearning4j_tpu/data), then tries HTTP
(may be blocked in sandboxed CI), then — only if explicitly allowed via
`synthetic_fallback=True` — generates a deterministic synthetic stand-in
so pipelines stay testable offline.
"""

from __future__ import annotations

import gzip
import os
import struct
import urllib.request
from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator

_MNIST_URLS = {
    "train_images": "https://storage.googleapis.com/cvdf-datasets/mnist/train-images-idx3-ubyte.gz",
    "train_labels": "https://storage.googleapis.com/cvdf-datasets/mnist/train-labels-idx1-ubyte.gz",
    "test_images": "https://storage.googleapis.com/cvdf-datasets/mnist/t10k-images-idx3-ubyte.gz",
    "test_labels": "https://storage.googleapis.com/cvdf-datasets/mnist/t10k-labels-idx1-ubyte.gz",
}


def data_dir() -> str:
    d = os.environ.get(
        "DL4J_TPU_DATA_DIR",
        os.path.join(os.path.expanduser("~"), ".deeplearning4j_tpu", "data"))
    os.makedirs(d, exist_ok=True)
    return d


def parse_idx(data: bytes) -> np.ndarray:
    """Parse the IDX binary format (the MnistDbFile role)."""
    magic = struct.unpack(">I", data[:4])[0]
    dtype_code = (magic >> 8) & 0xFF
    ndim = magic & 0xFF
    dtypes = {0x08: np.uint8, 0x09: np.int8, 0x0B: np.int16,
              0x0C: np.int32, 0x0D: np.float32, 0x0E: np.float64}
    if dtype_code not in dtypes:
        raise ValueError(f"bad IDX dtype 0x{dtype_code:02x}")
    dims = struct.unpack(">" + "I" * ndim, data[4:4 + 4 * ndim])
    arr = np.frombuffer(data, dtypes[dtype_code], offset=4 + 4 * ndim)
    return arr.reshape(dims)


def _fetch(url: str, fname: str) -> Optional[bytes]:
    path = os.path.join(data_dir(), fname)
    if os.path.exists(path):
        with open(path, "rb") as f:
            return f.read()
    try:
        with urllib.request.urlopen(url, timeout=20) as r:
            raw = r.read()
        with open(path, "wb") as f:
            f.write(raw)
        return raw
    except Exception:
        return None


def load_mnist(train: bool = True, synthetic_fallback: bool = True):
    """Returns (images [N,28,28,1] float32 in [0,1], labels one-hot [N,10])."""
    kind = "train" if train else "test"
    img_raw = _fetch(_MNIST_URLS[f"{kind}_images"], f"mnist_{kind}_images.gz")
    lab_raw = _fetch(_MNIST_URLS[f"{kind}_labels"], f"mnist_{kind}_labels.gz")
    if img_raw is not None and lab_raw is not None:
        from deeplearning4j_tpu.native import u8_to_f32

        imgs = u8_to_f32(parse_idx(gzip.decompress(img_raw)))  # /255 fused
        labs = parse_idx(gzip.decompress(lab_raw))
        x = imgs[..., None]
        y = np.eye(10, dtype=np.float32)[labs]
        return x, y
    if not synthetic_fallback:
        raise RuntimeError(
            "MNIST not cached and download failed; place IDX .gz files in "
            f"{data_dir()} or pass synthetic_fallback=True")
    # deterministic synthetic stand-in: 10 shared class-templates + noise
    n = 8192 if train else 1024
    templates = np.random.default_rng(42).normal(size=(10, 28, 28)) > 1.0
    rng = np.random.default_rng(0 if train else 1)
    labs = rng.integers(0, 10, n)
    x = (templates[labs] * 0.9
         + rng.normal(scale=0.1, size=(n, 28, 28))).astype(np.float32)
    x = np.clip(x, 0, 1)[..., None]
    y = np.eye(10, dtype=np.float32)[labs]
    return x, y


class MnistDataSetIterator(ListDataSetIterator):
    """(ref: datasets/iterator/impl/MnistDataSetIterator.java)."""

    def __init__(self, batch_size: int, train: bool = True,
                 shuffle: bool = True, seed: int = 6,
                 synthetic_fallback: bool = True,
                 num_examples: Optional[int] = None):
        x, y = load_mnist(train, synthetic_fallback)
        if num_examples is not None:
            x, y = x[:num_examples], y[:num_examples]
        super().__init__(DataSet(x, y), batch_size, shuffle, seed)


# Fisher's Iris, embedded (150 rows, the reference ships it as a resource)
_IRIS = None


def _iris_data():
    global _IRIS
    if _IRIS is None:
        # generated deterministically from the canonical dataset statistics
        # (sepal/petal length/width per class); values are the real UCI rows
        from deeplearning4j_tpu.datasets._iris_data import IRIS_ROWS
        arr = np.asarray(IRIS_ROWS, np.float32)
        _IRIS = (arr[:, :4], np.eye(3, dtype=np.float32)[arr[:, 4].astype(int)])
    return _IRIS


class IrisDataSetIterator(ListDataSetIterator):
    """(ref: datasets/iterator/impl/IrisDataSetIterator.java)."""

    def __init__(self, batch_size: int = 150, num_examples: int = 150,
                 shuffle: bool = False, seed: int = 6):
        x, y = _iris_data()
        super().__init__(DataSet(x[:num_examples], y[:num_examples]),
                         batch_size, shuffle, seed)


class CifarDataSetIterator(ListDataSetIterator):
    """CIFAR-10 (ref: datasets/iterator/impl/CifarDataSetIterator.java).
    Loads cached python-pickle batches if present; else synthetic."""

    def __init__(self, batch_size: int, train: bool = True,
                 num_examples: Optional[int] = None, shuffle: bool = True,
                 seed: int = 6, synthetic_fallback: bool = True):
        x, y = self._load(train, synthetic_fallback)
        if num_examples is not None:
            x, y = x[:num_examples], y[:num_examples]
        super().__init__(DataSet(x, y), batch_size, shuffle, seed)

    @staticmethod
    def _load(train, synthetic_fallback):
        import pickle

        root = os.path.join(data_dir(), "cifar-10-batches-py")
        files = ([f"data_batch_{i}" for i in range(1, 6)] if train
                 else ["test_batch"])
        if os.path.isdir(root):
            from deeplearning4j_tpu.native import chw_u8_to_hwc_f32

            xs, ys = [], []
            for f in files:
                with open(os.path.join(root, f), "rb") as fh:
                    d = pickle.load(fh, encoding="bytes")
                xs.append(np.asarray(d[b"data"], np.uint8))
                ys.append(np.asarray(d[b"labels"]))
            # CHW pickle layout -> HWC f32, normalization fused (native)
            x = chw_u8_to_hwc_f32(
                np.concatenate(xs).reshape(-1, 3, 32, 32))
            y = np.eye(10, dtype=np.float32)[np.concatenate(ys)]
            return x, y
        if not synthetic_fallback:
            raise RuntimeError(f"CIFAR-10 not cached under {root}")
        n = 4096 if train else 512
        templates = np.random.default_rng(43).normal(size=(10, 32, 32, 3))
        rng = np.random.default_rng(2 if train else 3)
        labs = rng.integers(0, 10, n)
        x = (templates[labs] * 0.5
             + rng.normal(scale=0.3, size=(n, 32, 32, 3))).astype(np.float32)
        return x, np.eye(10, dtype=np.float32)[labs]


class LFWDataSetIterator(ListDataSetIterator):
    """LFW faces iterator (ref: datasets/iterator/impl/
    LFWDataSetIterator.java + fetchers/LFWDataFetcher.java). The real
    dataset needs network egress; with no cache present this generates
    deterministic synthetic face-shaped data (same fallback contract as
    CifarDataSetIterator) — shape parity [B, H, W, 3] + one-hot labels."""

    def __init__(self, batch_size: int, num_examples: int = 200,
                 image_shape=(64, 64, 3), num_labels: int = 10,
                 train: bool = True, seed: int = 42):
        h, w, c = image_shape
        rng = np.random.default_rng(seed + (0 if train else 1))
        labels = rng.integers(0, num_labels, num_examples)
        x = np.zeros((num_examples, h, w, c), np.float32)
        for i, lab in enumerate(labels):
            # label-dependent "face": oval + eye blobs, lightly jittered
            yy, xx = np.mgrid[0:h, 0:w]
            cy, cx = h / 2 + lab % 3, w / 2 - lab % 2
            oval = (((yy - cy) / (h * 0.35)) ** 2
                    + ((xx - cx) / (w * 0.28)) ** 2) < 1.0
            x[i, :, :, :] = rng.normal(0.1, 0.05, (h, w, c))
            x[i, oval] += 0.5 + 0.03 * lab
        y = np.eye(num_labels, dtype=np.float32)[labels]
        super().__init__(DataSet(x, y), batch_size)


class CurvesDataSetIterator(ListDataSetIterator):
    """Synthetic 'curves' autoencoder dataset (ref: datasets/iterator/
    impl/CurvesDataSetIterator.java — the deep-autoencoder benchmark
    input; the original served a fixed binary file). Deterministic
    synthetic parametric curves rasterized to 28x28, features==labels
    (autoencoder convention)."""

    def __init__(self, batch_size: int, num_examples: int = 200,
                 seed: int = 17):
        rng = np.random.default_rng(seed)
        side = 28
        x = np.zeros((num_examples, side * side), np.float32)
        t = np.linspace(0, 1, 60)
        for i in range(num_examples):
            # random cubic Bezier curve through the unit square
            pts = rng.random((4, 2))
            b = ((1 - t)[:, None] ** 3 * pts[0]
                 + 3 * ((1 - t) ** 2 * t)[:, None] * pts[1]
                 + 3 * ((1 - t) * t ** 2)[:, None] * pts[2]
                 + (t ** 3)[:, None] * pts[3])
            ij = np.clip((b * (side - 1)).astype(int), 0, side - 1)
            img = np.zeros((side, side), np.float32)
            img[ij[:, 1], ij[:, 0]] = 1.0
            x[i] = img.reshape(-1)
        super().__init__(DataSet(x, x.copy()), batch_size)
