"""Datasets: DataSet containers, iterators (with async prefetch),
fetchers, and normalizers (parity: deeplearning4j-nn datasets/iterator/*
and deeplearning4j-core datasets/*)."""

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet  # noqa: F401
from deeplearning4j_tpu.datasets.iterators import (  # noqa: F401
    AsyncDataSetIterator,
    BenchmarkDataSetIterator,
    DataSetIterator,
    DevicePrefetchIterator,
    EarlyTerminationDataSetIterator,
    ListDataSetIterator,
    MultipleEpochsIterator,
)
from deeplearning4j_tpu.datasets.fetchers import (  # noqa: F401
    CifarDataSetIterator,
    IrisDataSetIterator,
    MnistDataSetIterator,
)
from deeplearning4j_tpu.datasets.normalizers import (  # noqa: F401
    ImagePreProcessingScaler,
    NormalizerMinMaxScaler,
    NormalizerStandardize,
)
from deeplearning4j_tpu.datasets.records import (  # noqa: F401
    CSVRecordReader,
    CSVSequenceRecordReader,
    CollectionRecordReader,
    CollectionSequenceRecordReader,
    RecordReaderDataSetIterator,
    RecordReaderMultiDataSetIterator,
    SequenceRecordReaderDataSetIterator,
)
