"""DataSet iterators (parity: deeplearning4j-nn datasets/iterator/ —
AsyncDataSetIterator.java:30 background prefetch thread + queue,
MultipleEpochsIterator.java, EarlyTerminationDataSetIterator.java,
impl/ListDataSetIterator.java, impl/BenchmarkDataSetIterator.java).

On TPU the iterator's job is to keep the host-side pipeline ahead of the
device: AsyncDataSetIterator prefetches batches on a daemon thread into a
bounded queue (the MagicQueue/AsyncPrefetchThread role) so `fit` never
waits on ETL.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, List, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet


class DataSetIterator:
    """Iterator contract: python-iterable + reset() (+ optional
    total_examples/batch metadata)."""

    def __iter__(self):
        self.reset()
        return self

    def __next__(self) -> DataSet:
        raise NotImplementedError

    def reset(self):
        pass

    def has_next(self) -> bool:
        raise NotImplementedError

    # camelCase compatibility
    def hasNext(self):
        return self.has_next()


class ListDataSetIterator(DataSetIterator):
    """Batches over an in-memory list of examples
    (ref: datasets/iterator/impl/ListDataSetIterator.java)."""

    def __init__(self, data: DataSet, batch_size: int = 32,
                 shuffle: bool = False, seed: int = 0):
        self.data = data
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self._epoch = 0
        self._batches: List[DataSet] = []
        self._pos = 0
        self.reset()

    def reset(self):
        d = self.data
        if self.shuffle:
            idx = np.random.default_rng(
                self.seed + self._epoch).permutation(d.num_examples())
            d = DataSet(d.features[idx],
                        None if d.labels is None else d.labels[idx],
                        None if d.features_mask is None else d.features_mask[idx],
                        None if d.labels_mask is None else d.labels_mask[idx])
        self._batches = d.batch_by(self.batch_size)
        self._pos = 0
        self._epoch += 1

    def has_next(self):
        return self._pos < len(self._batches)

    def __next__(self):
        if not self.has_next():
            raise StopIteration
        b = self._batches[self._pos]
        self._pos += 1
        return b


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch wrapper
    (ref: AsyncDataSetIterator.java:30,36 AsyncPrefetchThread)."""

    _SENTINEL = object()

    def __init__(self, base: Iterable, queue_size: int = 4):
        self.base = base
        self.queue_size = queue_size
        self._q: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._gen = 0  # restart generation: stale producers self-terminate
        self._exhausted = False

    def _start(self):
        self._gen += 1
        gen = self._gen
        q = queue.Queue(maxsize=self.queue_size)
        self._q = q
        self._error = None

        def producer():
            # capture q/gen locally: after a reset() the old thread must
            # never feed (or sentinel-terminate) the new queue
            def put(item) -> bool:
                while self._gen == gen:
                    try:
                        q.put(item, timeout=0.05)
                        return True
                    except queue.Full:
                        continue
                return False  # superseded by a restart

            try:
                for item in self.base:
                    if not put(item):
                        return
            except BaseException as e:  # surfaced on the consumer side
                if self._gen == gen:
                    self._error = e
            put(self._SENTINEL)

        self._thread = threading.Thread(target=producer, daemon=True,
                                        name="AsyncDataSetIterator-prefetch")
        self._thread.start()

    def __iter__(self):
        if hasattr(self.base, "reset"):
            self.base.reset()
        self._exhausted = False
        self._start()
        return self

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()
        self._exhausted = False
        self._start()

    def __next__(self):
        if self._exhausted:
            # iterator protocol: an exhausted iterator keeps raising
            # StopIteration until __iter__/reset explicitly starts a
            # new pass (restarting here silently fed wrapping
            # pipelines a second epoch)
            raise StopIteration
        if self._q is None:
            self._start()
        item = self._q.get()
        if item is self._SENTINEL:
            self._q = None
            self._exhausted = True
            if self._error is not None:
                raise self._error
            raise StopIteration
        return item

    # -------------------------------------------------------- shutdown
    def close(self, timeout_s: float = 5.0) -> None:
        """Stop the prefetch producer and JOIN it — the explicit
        shutdown the analyzer baseline carried as debt (a fit that
        raised used to leak the producer until process exit; the
        engine.StepHarness teardown calls this for attached
        iterators). Idempotent and non-terminal: a later
        __iter__()/reset() starts a fresh pass with a new producer."""
        self._gen += 1           # stale producers self-terminate
        q = self._q
        if q is not None:
            # drain so a producer blocked on a full queue re-checks
            # its generation promptly (its put() polls with a timeout,
            # so this is a latency nicety, not correctness)
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=timeout_s)
            if self._thread.is_alive():   # base iterator wedged in I/O
                raise TimeoutError(
                    "AsyncDataSetIterator prefetch thread did not "
                    f"exit within {timeout_s}s (base iterator blocked "
                    "in next()?)")
        self._thread = None
        self._q = None
        self._exhausted = True

    def join(self, timeout_s: float = 5.0) -> None:
        """Alias for close(): stop + join the prefetch thread."""
        self.close(timeout_s=timeout_s)

    def __enter__(self) -> "AsyncDataSetIterator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class MultipleEpochsIterator(DataSetIterator):
    """Replays a base iterator N times (ref: MultipleEpochsIterator.java)."""

    def __init__(self, epochs: int, base):
        self.epochs = epochs
        self.base = base
        self._epoch = 0
        self._inner = None

    def reset(self):
        self._epoch = 0
        self._inner = None
        if hasattr(self.base, "reset"):
            self.base.reset()

    def __next__(self):
        if self._inner is None:
            self._inner = iter(self.base)
        while True:
            try:
                return next(self._inner)
            except StopIteration:
                self._epoch += 1
                if self._epoch >= self.epochs:
                    raise
                if hasattr(self.base, "reset"):
                    self.base.reset()
                self._inner = iter(self.base)


class EarlyTerminationDataSetIterator(DataSetIterator):
    """Caps the number of batches (ref: EarlyTerminationDataSetIterator.java)."""

    def __init__(self, base, max_batches: int):
        self.base = base
        self.max_batches = max_batches
        self._count = 0
        self._inner = None

    def reset(self):
        self._count = 0
        self._inner = None
        if hasattr(self.base, "reset"):
            self.base.reset()

    def __next__(self):
        if self._count >= self.max_batches:
            raise StopIteration
        if self._inner is None:
            self._inner = iter(self.base)
        self._count += 1
        return next(self._inner)


class BenchmarkDataSetIterator(DataSetIterator):
    """Yields the same synthetic batch N times — zero-ETL throughput
    harness (ref: impl/BenchmarkDataSetIterator.java)."""

    def __init__(self, feature_shape, num_classes: int, num_batches: int,
                 seed: int = 0, label_shape=None):
        rng = np.random.default_rng(seed)
        x = rng.normal(size=feature_shape).astype(np.float32)
        if label_shape is not None:
            y = rng.normal(size=label_shape).astype(np.float32)
        else:
            y = np.eye(num_classes, dtype=np.float32)[
                rng.integers(0, num_classes, feature_shape[0])]
        self.batch = DataSet(x, y)
        self.num_batches = num_batches
        self._pos = 0

    def reset(self):
        self._pos = 0

    def has_next(self):
        return self._pos < self.num_batches

    def __next__(self):
        if not self.has_next():
            raise StopIteration
        self._pos += 1
        return self.batch


class DevicePrefetchIterator(DataSetIterator):
    """Double-buffered host->device input pipeline: the AsyncDataSetIterator
    -> device leg (ref: MagicQueue.java:35's device-affinity queue role).

    Stages up to `buffer_size` upcoming batches on the accelerator with
    asynchronous `jax.device_put` while the current step runs, so the h2d
    DMA of batch k+1 overlaps compute on batch k. Yields batches whose
    arrays are already device-resident (jax Arrays), in order.

    `transform(batch) -> pytree` optionally maps the host batch (e.g.
    normalize / reshard) before staging; by default (x, y[, masks]) tuples
    and DataSet objects are staged as-is. `sharding` (a jax.sharding
    .Sharding) places each staged array for multi-device data parallelism.
    """

    def __init__(self, base: Iterable, buffer_size: int = 2,
                 transform=None, sharding=None):
        if buffer_size < 1:
            raise ValueError(f"buffer_size must be >= 1, got {buffer_size}")
        self.base = base
        self.buffer_size = buffer_size
        self.transform = transform
        self.sharding = sharding
        self._src = None
        self._staged = None
        self._src_done = False

    def _put(self, item):
        import jax

        if self.transform is not None:
            item = self.transform(item)
        if hasattr(item, "features"):  # DataSet
            item = (item.features, item.labels,
                    getattr(item, "features_mask", None),
                    getattr(item, "labels_mask", None))
        kw = {} if self.sharding is None else {"device": self.sharding}
        return tuple(
            None if a is None else jax.device_put(a, **kw) for a in item
        ) if isinstance(item, (tuple, list)) else jax.device_put(item, **kw)

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()
        self._src = None
        self._staged = None
        self._src_done = False

    def __iter__(self):
        if self._staged is not None:
            # an iteration is already staged (has_next() or a prior
            # __iter__); keep it — restaging would drop the buffered
            # batches when base is a one-shot generator. reset() starts
            # a genuinely fresh pass.
            return self
        self._src = iter(self.base)
        self._src_done = False
        self._staged = []
        for _ in range(self.buffer_size):
            try:
                self._staged.append(self._put(next(self._src)))
            except StopIteration:
                self._src_done = True
                break
        return self

    def has_next(self):
        if self._staged is None:
            self.__iter__()
        return bool(self._staged)

    def __next__(self):
        if self._staged is None:
            self.__iter__()
        if not self._staged:
            # exhausted: clear the stage marker so the next __iter__
            # starts a fresh pass over base (multi-epoch reuse)
            self._staged = None
            raise StopIteration
        out = self._staged.pop(0)
        if not self._src_done:
            # never call next() again after exhaustion: a multi-epoch
            # base would hand us its following epoch
            try:
                self._staged.append(self._put(next(self._src)))
            except StopIteration:
                self._src_done = True
        return out

    # -------------------------------------------------------- shutdown
    def close(self, timeout_s: float = 5.0) -> None:
        """Stop the pipeline: drop the staged device buffer (donation
        safety — a staged batch that was never consumed is discarded,
        never re-yielded) and propagate close() to `base`, so wrapping
        an AsyncDataSetIterator no longer hides its producer thread
        from StepHarness.attach_data's `hasattr(source, "close")`
        check. Idempotent and non-terminal: a later __iter__()/reset()
        starts a fresh pass."""
        self._src = None
        self._staged = None
        self._src_done = False
        if hasattr(self.base, "close"):
            try:
                self.base.close(timeout_s=timeout_s)
            except TypeError:   # base close() without a timeout param
                self.base.close()

    def __enter__(self) -> "DevicePrefetchIterator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
