"""Data normalizers (parity: ND4J NormalizerStandardize /
NormalizerMinMaxScaler / ImagePreProcessingScaler, persisted as
normalizer.bin in ModelSerializer zips — util/ModelSerializer.java:40-41)."""

from __future__ import annotations

import numpy as np

_REGISTRY = {}


def register(cls):
    _REGISTRY[cls.__name__] = cls
    return cls


def normalizer_from_dict(d: dict):
    d = dict(d)
    kind = d.pop("type")
    if kind not in _REGISTRY:
        raise ValueError(f"Unknown normalizer '{kind}'; known {sorted(_REGISTRY)}")
    n = _REGISTRY[kind]()
    n.__dict__.update({k: (np.asarray(v) if isinstance(v, list) else v)
                       for k, v in d.items()})
    return n


class Normalizer:
    def fit(self, dataset_or_iterator):
        raise NotImplementedError

    def transform(self, dataset):
        raise NotImplementedError

    def pre_process(self, dataset):
        return self.transform(dataset)

    def to_dict(self) -> dict:
        d = {"type": type(self).__name__}
        for k, v in self.__dict__.items():
            # analyze: allow=jit-host-sync — host-numpy stats serialization
            d[k] = v.tolist() if isinstance(v, np.ndarray) else v
        return d

    def _iter_features(self, it):
        if hasattr(it, "features"):
            yield np.asarray(it.features)
            return
        for b in it:
            yield np.asarray(b.features if hasattr(b, "features") else b[0])


@register
class NormalizerStandardize(Normalizer):
    """Zero-mean unit-variance per feature."""

    def __init__(self):
        self.mean = None
        self.std = None

    def fit(self, data):
        feats = np.concatenate(list(self._iter_features(data)), axis=0)
        axes = tuple(range(feats.ndim - 1))
        self.mean = feats.mean(axis=axes)
        self.std = feats.std(axis=axes) + 1e-8
        return self

    def transform(self, ds):
        ds.features = (ds.features - self.mean) / self.std
        return ds

    def revert_features(self, x):
        return x * self.std + self.mean


@register
class NormalizerMinMaxScaler(Normalizer):
    """Scale features into [min_range, max_range]."""

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0):
        self.min_range = min_range
        self.max_range = max_range
        self.data_min = None
        self.data_max = None

    def fit(self, data):
        feats = np.concatenate(list(self._iter_features(data)), axis=0)
        axes = tuple(range(feats.ndim - 1))
        self.data_min = feats.min(axis=axes)
        self.data_max = feats.max(axis=axes)
        return self

    def transform(self, ds):
        span = np.maximum(self.data_max - self.data_min, 1e-8)
        scaled = (ds.features - self.data_min) / span
        ds.features = scaled * (self.max_range - self.min_range) + self.min_range
        return ds


@register
class ImagePreProcessingScaler(Normalizer):
    """Pixel scale [0, max_pixel] -> [a, b] (default [0,1]); stateless."""

    def __init__(self, a: float = 0.0, b: float = 1.0,
                 max_pixel: float = 255.0):
        self.a = a
        self.b = b
        self.max_pixel = max_pixel

    def fit(self, data):
        return self

    def transform(self, ds):
        ds.features = (ds.features / self.max_pixel) * (self.b - self.a) + self.a
        return ds


@register
class VGG16ImagePreProcessor(Normalizer):
    """ImageNet mean subtraction for VGG16-family inputs (ref
    TrainedModels.VGG16.getPreProcessor /
    VGG16ImagePreProcessor.java): subtracts the per-channel dataset
    mean, no scaling. Channel order follows the tensor's last axis
    (NHWC RGB by default, matching the importer's layout)."""

    MEAN_RGB = (123.68, 116.779, 103.939)

    def __init__(self, mean=None):
        import numpy as _np

        self.mean = _np.asarray(
            self.MEAN_RGB if mean is None else mean, _np.float32)

    def fit(self, data):
        return self

    def transform(self, ds):
        ds.features = ds.features - self.mean
        return ds
