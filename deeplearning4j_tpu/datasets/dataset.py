"""DataSet / MultiDataSet containers (parity: ND4J's DataSet/MultiDataSet
consumed throughout the reference, e.g. MultiLayerNetwork.fit(DataSet)).
Plain numpy holders — device placement happens inside the train step."""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


class DataSet:
    def __init__(self, features, labels=None, features_mask=None,
                 labels_mask=None):
        self.features = np.asarray(features)
        self.labels = None if labels is None else np.asarray(labels)
        self.features_mask = (None if features_mask is None
                              else np.asarray(features_mask))
        self.labels_mask = (None if labels_mask is None
                            else np.asarray(labels_mask))

    def num_examples(self) -> int:
        return self.features.shape[0]

    def split_test_and_train(self, n_train: int):
        tr = DataSet(self.features[:n_train],
                     None if self.labels is None else self.labels[:n_train])
        te = DataSet(self.features[n_train:],
                     None if self.labels is None else self.labels[n_train:])
        return tr, te

    def shuffle(self, seed: Optional[int] = None):
        idx = np.random.default_rng(seed).permutation(self.num_examples())
        self.features = self.features[idx]
        if self.labels is not None:
            self.labels = self.labels[idx]
        if self.features_mask is not None:
            self.features_mask = self.features_mask[idx]
        if self.labels_mask is not None:
            self.labels_mask = self.labels_mask[idx]
        return self

    def batch_by(self, batch_size: int) -> List["DataSet"]:
        out = []
        for i in range(0, self.num_examples(), batch_size):
            out.append(DataSet(
                self.features[i:i + batch_size],
                None if self.labels is None else self.labels[i:i + batch_size],
                None if self.features_mask is None
                else self.features_mask[i:i + batch_size],
                None if self.labels_mask is None
                else self.labels_mask[i:i + batch_size]))
        return out

    def __iter__(self):
        # tuple-unpacking compatibility: (x, y, fm, lm)
        return iter((self.features, self.labels, self.features_mask,
                     self.labels_mask))


class MultiDataSet:
    """Multiple input/label arrays (parity: ND4J MultiDataSet used by
    ComputationGraph.fit(MultiDataSetIterator), ComputationGraph.java:907)."""

    def __init__(self, features: Sequence, labels: Sequence,
                 features_masks: Optional[Sequence] = None,
                 labels_masks: Optional[Sequence] = None):
        self.features = [np.asarray(f) for f in features]
        self.labels = [np.asarray(l) for l in labels]
        self.features_mask = (None if features_masks is None else
                              [None if m is None else np.asarray(m)
                               for m in features_masks])
        self.labels_mask = (None if labels_masks is None else
                            [None if m is None else np.asarray(m)
                             for m in labels_masks])

    def num_examples(self) -> int:
        return self.features[0].shape[0]
