"""Record-reader data bridge (the DataVec role).

Parity: deeplearning4j-core datasets/datavec/
RecordReaderDataSetIterator.java (record stream -> DataSet batches with
label one-hot / regression columns),
SequenceRecordReaderDataSetIterator.java (sequence files -> padded+masked
[B,T,*] batches) and RecordReaderMultiDataSetIterator.java (named
readers + column-range subsets -> MultiDataSet); readers mirror DataVec's
CSVRecordReader / CSVSequenceRecordReader / CollectionRecordReader.

TPU-native notes: ragged sequences become padded static-shape batches
with masks (SURVEY §7 hard parts — static shapes), so downstream jit
steps compile once per batch geometry.
"""

from __future__ import annotations

import csv
import io
from typing import Iterable, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator


# ------------------------------------------------------------------ readers

class RecordReader:
    """A stream of records (lists of string/number values)."""

    def records(self) -> Iterable[List[str]]:
        raise NotImplementedError

    def __iter__(self):
        return iter(self.records())


class CSVRecordReader(RecordReader):
    """ref DataVec CSVRecordReader: optional skipped header lines,
    configurable delimiter/quote."""

    def __init__(self, path: Optional[str] = None, skip_lines: int = 0,
                 delimiter: str = ",", quotechar: str = '"',
                 text: Optional[str] = None):
        if (path is None) == (text is None):
            raise ValueError("give exactly one of path= or text=")
        self.path = path
        self.text = text
        self.skip_lines = skip_lines
        self.delimiter = delimiter
        self.quotechar = quotechar

    def records(self):
        fh = open(self.path) if self.path else io.StringIO(self.text)
        try:
            reader = csv.reader(fh, delimiter=self.delimiter,
                                quotechar=self.quotechar)
            for i, row in enumerate(reader):
                if i < self.skip_lines or not row:
                    continue
                yield [v.strip() for v in row]
        finally:
            fh.close()

    def to_matrix(self):
        """Whole-file all-numeric fast path: native C++ CSV->f32 parse
        (native/dl4j_tpu_native.cpp). Returns None when the content
        needs the general row path (non-numeric cells, quoting, or
        skip_lines)."""
        if self.skip_lines:
            return None
        try:
            from deeplearning4j_tpu.native import parse_csv_f32

            if self.path:
                with open(self.path, "rb") as f:
                    data = f.read()
            else:
                data = self.text.encode()
            if self.quotechar.encode() in data:
                return None
            return parse_csv_f32(data, self.delimiter)
        except ValueError:
            return None


class CollectionRecordReader(RecordReader):
    """In-memory records (ref CollectionRecordReader.java)."""

    def __init__(self, rows: Sequence[Sequence]):
        self.rows = [list(r) for r in rows]

    def records(self):
        return iter(self.rows)


class CSVSequenceRecordReader:
    """One CSV file per sequence; each line is one timestep
    (ref DataVec CSVSequenceRecordReader)."""

    def __init__(self, paths: Sequence[str], skip_lines: int = 0,
                 delimiter: str = ","):
        self.paths = list(paths)
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def sequences(self) -> Iterable[List[List[str]]]:
        for p in self.paths:
            reader = CSVRecordReader(p, self.skip_lines, self.delimiter)
            yield list(reader.records())

    def __iter__(self):
        return iter(self.sequences())


class CollectionSequenceRecordReader:
    """In-memory sequences of records."""

    def __init__(self, seqs: Sequence[Sequence[Sequence]]):
        self.seqs = [[list(r) for r in s] for s in seqs]

    def sequences(self):
        return iter(self.seqs)

    def __iter__(self):
        return iter(self.sequences())


# ----------------------------------------------------------- DataSet bridge

def _one_hot(idx: np.ndarray, n: int) -> np.ndarray:
    out = np.zeros((len(idx), n), np.float32)
    out[np.arange(len(idx)), idx.astype(int)] = 1.0
    return out


class RecordReaderDataSetIterator(DataSetIterator):
    """records -> DataSet batches
    (ref RecordReaderDataSetIterator.java).

    Classification: `label_index` column -> one-hot over `num_classes`.
    Regression: `regression=True` with `label_index`(..`label_index_to`)
    as continuous label columns. No label args -> features only."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: Optional[int] = None,
                 num_classes: Optional[int] = None,
                 regression: bool = False,
                 label_index_to: Optional[int] = None):
        if label_index is not None and not regression \
                and num_classes is None:
            raise ValueError(
                "classification needs num_classes (or set regression=True)")
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self.label_index_to = label_index_to
        self._it = None
        self._buf: Optional[DataSet] = None
        self._native_checked = False
        self._native_batches = None

    def reset(self):
        self._it = None
        self._buf = None
        self._native_checked = False
        self._native_batches = None

    def _rows(self):
        if self._it is None:
            self._it = iter(self.reader)
        return self._it

    def _split(self, rows: List[List[str]]) -> DataSet:
        arr = np.asarray(rows, dtype=object)
        li = self.label_index
        if li is None:
            return DataSet(np.asarray(arr, np.float32))
        lto = self.label_index_to if self.label_index_to is not None else li
        cols = list(range(arr.shape[1]))
        label_cols = [c for c in cols if li <= c <= lto]
        feat_cols = [c for c in cols if c not in label_cols]
        feats = arr[:, feat_cols].astype(np.float32)
        labels = arr[:, label_cols].astype(np.float32)
        if not self.regression:
            labels = _one_hot(labels[:, 0], self.num_classes)
        return DataSet(feats, labels)

    def _try_native(self):
        """One-shot whole-file native parse; leaves per-row iteration as
        the fallback. Populates a batch queue."""
        if self._native_checked:
            return
        self._native_checked = True
        m = getattr(self.reader, "to_matrix", lambda: None)()
        if m is None or m.size == 0:
            return
        self._native_batches = [
            m[i:i + self.batch_size]
            for i in range(0, m.shape[0], self.batch_size)]

    def has_next(self) -> bool:
        if self._buf is not None:
            return True
        self._try_native()
        if self._native_batches is not None:
            if not self._native_batches:
                return False
            block = self._native_batches.pop(0)
            self._buf = self._split([list(r) for r in block])
            return True
        rows = []
        for row in self._rows():
            rows.append(row)
            if len(rows) == self.batch_size:
                break
        if not rows:
            return False
        self._buf = self._split(rows)
        return True

    def __next__(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        out, self._buf = self._buf, None
        return out


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """sequences -> padded+masked [B, T, *] DataSet batches
    (ref SequenceRecordReaderDataSetIterator.java ALIGN_END=False;
    variable lengths produce masks, the TPU static-shape idiom)."""

    def __init__(self, reader, batch_size: int,
                 label_index: Optional[int] = None,
                 num_classes: Optional[int] = None,
                 regression: bool = False):
        if label_index is not None and not regression \
                and num_classes is None:
            raise ValueError(
                "classification needs num_classes (or set regression=True)")
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self._it = None
        self._buf = None

    def reset(self):
        self._it = None
        self._buf = None

    def _seqs(self):
        if self._it is None:
            self._it = iter(self.reader.sequences())
        return self._it

    def _build(self, seqs) -> DataSet:
        B = len(seqs)
        T = max(len(s) for s in seqs)
        li = self.label_index
        n_cols = len(seqs[0][0])
        f_dim = n_cols - (0 if li is None else 1)
        feats = np.zeros((B, T, f_dim), np.float32)
        fmask = np.zeros((B, T), np.float32)
        labels = None
        lmask = None
        if li is not None:
            ldim = 1 if self.regression else self.num_classes
            labels = np.zeros((B, T, ldim), np.float32)
            lmask = np.zeros((B, T), np.float32)
        for b, seq in enumerate(seqs):
            for t, row in enumerate(seq):
                vals = [float(v) for v in row]
                if li is None:
                    feats[b, t] = vals
                else:
                    lab = vals.pop(li)
                    feats[b, t] = vals
                    if self.regression:
                        labels[b, t, 0] = lab
                    else:
                        labels[b, t, int(lab)] = 1.0
                    lmask[b, t] = 1.0
                fmask[b, t] = 1.0
        return DataSet(feats, labels, fmask, lmask if li is not None
                       else None)

    def has_next(self) -> bool:
        if self._buf is not None:
            return True
        seqs = []
        for s in self._seqs():
            seqs.append(s)
            if len(seqs) == self.batch_size:
                break
        if not seqs:
            return False
        self._buf = self._build(seqs)
        return True

    def __next__(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        out, self._buf = self._buf, None
        return out


class RecordReaderMultiDataSetIterator:
    """Named readers + column-range subsets -> MultiDataSet batches
    (ref RecordReaderMultiDataSetIterator.java Builder:
    addReader / addInput(name, from, to) / addOutputOneHot /
    addOutput)."""

    class Builder:
        def __init__(self, batch_size: int):
            self.batch_size = batch_size
            self._readers = {}
            self._inputs = []   # (reader, from, to)
            self._outputs = []  # (reader, from, to, one_hot_classes|None)

        def add_reader(self, name: str, reader: RecordReader):
            self._readers[name] = reader
            return self

        def add_input(self, name: str, col_from: Optional[int] = None,
                      col_to: Optional[int] = None):
            self._inputs.append((name, col_from, col_to))
            return self

        def add_output(self, name: str, col_from: int, col_to: int):
            self._outputs.append((name, col_from, col_to, None))
            return self

        def add_output_one_hot(self, name: str, col: int,
                               num_classes: int):
            self._outputs.append((name, col, col, num_classes))
            return self

        def build(self) -> "RecordReaderMultiDataSetIterator":
            if not self._inputs or not self._outputs:
                raise ValueError("need at least one input and one output")
            for name, *_ in self._inputs + self._outputs:
                if name not in self._readers:
                    raise ValueError(f"no reader named '{name}'")
            return RecordReaderMultiDataSetIterator(self)

    def __init__(self, builder: "RecordReaderMultiDataSetIterator.Builder"):
        self._b = builder
        self._its = None

    def reset(self):
        self._its = None

    def _rows(self):
        if self._its is None:
            self._its = {n: iter(r) for n, r in self._b._readers.items()}
        return self._its

    def __iter__(self):
        self.reset()
        return self

    def __next__(self) -> MultiDataSet:
        its = self._rows()
        rows = {n: [] for n in its}
        for _ in range(self._b.batch_size):
            try:
                vals = {n: next(it) for n, it in its.items()}
            except StopIteration:
                break
            for n, v in vals.items():
                rows[n].append(v)
        if not next(iter(rows.values())):
            raise StopIteration
        arrays = {n: np.asarray(r, dtype=object) for n, r in rows.items()}

        def cols(arr, f, t):
            f = 0 if f is None else f
            t = arr.shape[1] - 1 if t is None else t
            return arr[:, f:t + 1].astype(np.float32)

        feats = [cols(arrays[n], f, t) for n, f, t in self._b._inputs]
        labs = []
        for n, f, t, oh in self._b._outputs:
            c = cols(arrays[n], f, t)
            labs.append(_one_hot(c[:, 0], oh) if oh else c)
        return MultiDataSet(feats, labs)
