"""Denoising AutoEncoder layer.

Parity: nn/conf/layers/AutoEncoder.java + nn/layers/feedforward/autoencoder/.
Supervised forward = encoder; unsupervised `pretrain_loss` = reconstruction
error after input corruption (masking noise with probability
`corruption_level`), matching the reference's denoising-AE pretraining.
(The reference's RBM layer is legacy/deprecated even there; AutoEncoder and
VariationalAutoencoder cover the pretrain capability.)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf.inputs import InputType, InputTypeFeedForward
from deeplearning4j_tpu.nn.layers.base import BaseLayer
from deeplearning4j_tpu.nn.layers.core import DenseLayer
from deeplearning4j_tpu.nn.losses import get_loss
from deeplearning4j_tpu.nn.weights import init_weights


@dataclass(kw_only=True)
class AutoEncoder(BaseLayer):
    corruption_level: float = 0.3
    sparsity: float = 0.0
    loss: str = "mse"
    activation: Optional[str] = "sigmoid"

    def set_n_in(self, input_type: InputType) -> None:
        self.n_in = input_type.size if isinstance(
            input_type, InputTypeFeedForward) else input_type.arrays_per_example()

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init_params(self, key, input_type, dtype=jnp.float32):
        kw, _ = jax.random.split(key)
        W = init_weights(self.weight_init, kw, (self.n_in, self.n_out),
                         fan_in=self.n_in, fan_out=self.n_out, dtype=dtype)
        return {
            "W": W,                                   # tied weights: decode with W.T
            "b": jnp.zeros((self.n_out,), dtype),     # hidden bias
            "vb": jnp.zeros((self.n_in,), dtype),     # visible (decode) bias
        }

    def encode(self, params, x):
        return get_activation(self.activation)(x @ params["W"] + params["b"])

    def decode(self, params, h):
        return get_activation(self.activation)(h @ params["W"].T + params["vb"])

    def apply(self, params, x, *, train=False, rng=None, state=None, mask=None):
        x = self._maybe_dropout_input(x, train, rng)
        return self.encode(params, x), state

    def pretrain_loss(self, params, x, rng):
        if self.corruption_level > 0.0 and rng is not None:
            keep = jax.random.bernoulli(rng, 1.0 - self.corruption_level, x.shape)
            corrupted = jnp.where(keep, x, 0.0)
        else:
            corrupted = x
        recon_pre = self.encode(params, corrupted) @ params["W"].T + params["vb"]
        per_ex = get_loss(self.loss)(x, recon_pre, self.activation)
        return jnp.mean(per_ex)
