"""Convolutional layers: Convolution2D/1D, Subsampling (pooling), ZeroPadding,
LocalResponseNormalization.

Parity targets (reference):
- ConvolutionLayer: nn/conf/layers/ConvolutionLayer.java +
  nn/layers/convolution/ConvolutionLayer.java (cuDNN helper hook at :74-84)
- SubsamplingLayer: nn/layers/convolution/subsampling/SubsamplingLayer.java
- LRN: nn/layers/normalization/LocalResponseNormalization.java

TPU-first design: the reference's cuDNN helper tier (algorithm selection
GEMM/FFT/Winograd, CudnnConvolutionHelper.java:151-210) is unnecessary —
`lax.conv_general_dilated` in NHWC/HWIO layout lowers to MXU-tiled convs and
XLA picks the algorithm. Padding modes follow the reference's ConvolutionMode
(truncate/same) as static shape math.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf.inputs import (
    InputType,
    InputTypeConvolutional,
    InputTypeRecurrent,
)
from deeplearning4j_tpu.nn.layers.base import BaseLayer, Layer
from deeplearning4j_tpu.nn.weights import init_weights

_DIMS_NHWC = ("NHWC", "HWIO", "NHWC")


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def _out_dim(size, k, s, pad, mode, dilation=1):
    if mode == "same":
        return -(-size // s)  # ceil
    k_eff = k + (k - 1) * (dilation - 1)
    return (size + 2 * pad - k_eff) // s + 1


def _explicit_padding(mode, pad):
    """Return lax-style padding config for one spatial dim."""
    return pad  # numeric pads handled by caller; 'same' uses lax SAME


@dataclass(kw_only=True)
class ConvolutionLayer(BaseLayer):
    """2D convolution over NHWC input. kernel/stride/padding are (h, w) pairs.

    convolution_mode: 'truncate' (explicit padding, floor division — reference
    default) or 'same' (SAME padding, stride-ceil output).
    """

    kernel_size: Sequence[int] = (5, 5)
    stride: Sequence[int] = (1, 1)
    padding: Sequence[int] = (0, 0)
    convolution_mode: str = "truncate"
    activation: Optional[str] = "identity"
    dilation: Sequence[int] = (1, 1)

    def set_n_in(self, input_type: InputType) -> None:
        if not isinstance(input_type, InputTypeConvolutional):
            raise ValueError(f"ConvolutionLayer needs CNN input, got {input_type}")
        self.n_in = input_type.channels

    def output_type(self, input_type: InputType) -> InputType:
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        dh, dw = _pair(self.dilation)
        mode = self.convolution_mode
        h = _out_dim(input_type.height, kh, sh, ph, mode, dh)
        w = _out_dim(input_type.width, kw, sw, pw, mode, dw)
        if h <= 0 or w <= 0:
            raise ValueError(
                f"Invalid conv output {h}x{w} from {input_type} with "
                f"k={self.kernel_size} s={self.stride} p={self.padding}"
            )
        return InputType.convolutional(h, w, self.n_out)

    def init_params(self, key, input_type, dtype=jnp.float32):
        kh, kw = _pair(self.kernel_size)
        fan_in = self.n_in * kh * kw
        fan_out = self.n_out * kh * kw
        W = init_weights(
            self.weight_init, key, (kh, kw, self.n_in, self.n_out),
            fan_in=fan_in, fan_out=fan_out, dtype=dtype,
        )
        b = jnp.full((self.n_out,), self.bias_init, dtype)
        return {"W": W, "b": b}

    def apply(self, params, x, *, train=False, rng=None, state=None, mask=None):
        x = self._maybe_dropout_input(x, train, rng)
        sh, sw = _pair(self.stride)
        dh, dw = _pair(self.dilation)
        if self.convolution_mode == "same":
            padding = "SAME"
        else:
            ph, pw = _pair(self.padding)
            padding = ((ph, ph), (pw, pw))
        y = lax.conv_general_dilated(
            x, params["W"],
            window_strides=(sh, sw),
            padding=padding,
            rhs_dilation=(dh, dw),
            dimension_numbers=_DIMS_NHWC,
        )
        y = y + params["b"]
        return get_activation(self.activation)(y), state


@dataclass(kw_only=True)
class Convolution1DLayer(BaseLayer):
    """1D convolution over [B, T, C] (recurrent-typed) input."""

    kernel_size: int = 3
    stride: int = 1
    padding: int = 0
    convolution_mode: str = "same"
    activation: Optional[str] = "identity"

    def set_n_in(self, input_type: InputType) -> None:
        if not isinstance(input_type, InputTypeRecurrent):
            raise ValueError(f"Convolution1D needs recurrent input, got {input_type}")
        self.n_in = input_type.size

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timeseries_length
        if t is not None:
            t = _out_dim(t, self.kernel_size, self.stride, self.padding,
                         self.convolution_mode)
        return InputType.recurrent(self.n_out, t)

    def init_params(self, key, input_type, dtype=jnp.float32):
        fan_in = self.n_in * self.kernel_size
        fan_out = self.n_out * self.kernel_size
        W = init_weights(
            self.weight_init, key, (self.kernel_size, self.n_in, self.n_out),
            fan_in=fan_in, fan_out=fan_out, dtype=dtype,
        )
        b = jnp.full((self.n_out,), self.bias_init, dtype)
        return {"W": W, "b": b}

    def apply(self, params, x, *, train=False, rng=None, state=None, mask=None):
        x = self._maybe_dropout_input(x, train, rng)
        if self.convolution_mode == "same":
            padding = "SAME"
        else:
            padding = ((self.padding, self.padding),)
        y = lax.conv_general_dilated(
            x, params["W"],
            window_strides=(self.stride,),
            padding=padding,
            dimension_numbers=("NHC", "HIO", "NHC"),
        )
        return get_activation(self.activation)(y + params["b"]), state


@dataclass(kw_only=True)
class SubsamplingLayer(Layer):
    """Spatial pooling (max/avg/pnorm/sum) over NHWC input via reduce_window."""

    pooling_type: str = "max"
    kernel_size: Sequence[int] = (2, 2)
    stride: Sequence[int] = (2, 2)
    padding: Sequence[int] = (0, 0)
    convolution_mode: str = "truncate"
    pnorm: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        h = _out_dim(input_type.height, kh, sh, ph, self.convolution_mode)
        w = _out_dim(input_type.width, kw, sw, pw, self.convolution_mode)
        return InputType.convolutional(h, w, input_type.channels)

    def _padding_config(self):
        if self.convolution_mode == "same":
            return "SAME"
        ph, pw = _pair(self.padding)
        return ((0, 0), (ph, ph), (pw, pw), (0, 0))

    def apply(self, params, x, *, train=False, rng=None, state=None, mask=None):
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        window = (1, kh, kw, 1)
        strides = (1, sh, sw, 1)
        pad = self._padding_config()
        pt = self.pooling_type.lower()
        if pt == "max":
            y = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pad)
        elif pt in ("avg", "sum"):
            y = lax.reduce_window(x, 0.0, lax.add, window, strides, pad)
            if pt == "avg":
                y = y / (kh * kw)
        elif pt == "pnorm":
            p = float(self.pnorm)
            y = lax.reduce_window(
                jnp.abs(x) ** p, 0.0, lax.add, window, strides, pad
            ) ** (1.0 / p)
        else:
            raise ValueError(f"Unknown pooling type {self.pooling_type}")
        return y, state


@dataclass(kw_only=True)
class Subsampling1DLayer(Layer):
    """Temporal pooling over [B, T, C]."""

    pooling_type: str = "max"
    kernel_size: int = 2
    stride: int = 2
    padding: int = 0
    pnorm: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timeseries_length
        if t is not None:
            t = _out_dim(t, self.kernel_size, self.stride, self.padding, "truncate")
        return InputType.recurrent(input_type.size, t)

    def apply(self, params, x, *, train=False, rng=None, state=None, mask=None):
        window = (1, self.kernel_size, 1)
        strides = (1, self.stride, 1)
        pad = ((0, 0), (self.padding, self.padding), (0, 0))
        pt = self.pooling_type.lower()
        if pt == "max":
            y = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pad)
        elif pt == "avg":
            y = lax.reduce_window(x, 0.0, lax.add, window, strides, pad)
            y = y / self.kernel_size
        elif pt == "sum":
            y = lax.reduce_window(x, 0.0, lax.add, window, strides, pad)
        elif pt == "pnorm":
            p = float(self.pnorm)
            y = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add,
                                  window, strides, pad) ** (1.0 / p)
        else:
            raise ValueError(
                f"Unknown pooling_type '{self.pooling_type}' "
                "(known: max, avg, sum, pnorm)")
        return y, state


@dataclass(kw_only=True)
class ZeroPaddingLayer(Layer):
    """Zero-pads spatial dims of NHWC input. padding = (top, bottom, left, right)
    or (h, w) symmetric."""

    padding: Sequence[int] = (1, 1)

    def _pads(self):
        p = tuple(int(v) for v in self.padding)
        if len(p) == 2:
            return (p[0], p[0], p[1], p[1])
        if len(p) == 4:
            return p
        raise ValueError(f"padding must have 2 or 4 elements, got {p}")

    def output_type(self, input_type: InputType) -> InputType:
        t, b, l, r = self._pads()
        return InputType.convolutional(
            input_type.height + t + b, input_type.width + l + r,
            input_type.channels,
        )

    def apply(self, params, x, *, train=False, rng=None, state=None, mask=None):
        t, b, l, r = self._pads()
        return jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0))), state


@dataclass(kw_only=True)
class LocalResponseNormalization(Layer):
    """Cross-channel LRN: x / (k + alpha*sum_window(x^2))^beta over NHWC.

    On TPU this is a channel-axis reduce_window — elementwise-heavy and
    bandwidth-bound, fused by XLA.
    """

    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75

    def apply(self, params, x, *, train=False, rng=None, state=None, mask=None):
        half = self.n // 2
        sq = x * x
        window = (1, 1, 1, self.n)
        strides = (1, 1, 1, 1)
        pad = ((0, 0), (0, 0), (0, 0), (half, half))
        ssum = lax.reduce_window(sq, 0.0, lax.add, window, strides, pad)
        denom = (self.k + self.alpha * ssum) ** self.beta
        return x / denom, state
