from deeplearning4j_tpu.nn.layers.base import Layer, BaseLayer  # noqa: F401
from deeplearning4j_tpu.nn.layers.core import (  # noqa: F401
    DenseLayer,
    ActivationLayer,
    DropoutLayer,
    EmbeddingLayer,
    CenterLossOutputLayer,
    OutputLayer,
    RnnOutputLayer,
    LossLayer,
    GlobalPoolingLayer,
)
from deeplearning4j_tpu.nn.layers.conv import (  # noqa: F401
    ConvolutionLayer,
    Convolution1DLayer,
    SubsamplingLayer,
    Subsampling1DLayer,
    ZeroPaddingLayer,
    LocalResponseNormalization,
)
from deeplearning4j_tpu.nn.layers.norm import BatchNormalization  # noqa: F401
from deeplearning4j_tpu.nn.layers.recurrent import (  # noqa: F401
    LSTM,
    GravesLSTM,
    GravesBidirectionalLSTM,
)
from deeplearning4j_tpu.nn.layers.variational import VariationalAutoencoder  # noqa: F401
from deeplearning4j_tpu.nn.layers.feedforward import AutoEncoder  # noqa: F401
from deeplearning4j_tpu.nn.layers.rbm import RBM  # noqa: F401
