"""BatchNormalization.

Parity: nn/conf/layers/BatchNormalization.java +
nn/layers/normalization/BatchNormalization.java (cuDNN helper hook at
:56-64). Running mean/var live in the layer's *state* pytree (not params), so
`jax.grad` never differentiates them; the train-mode state update is returned
functionally — this is the TPU-native replacement for the reference's mutable
running-stat arrays.

Works on [B, C] (feed-forward), [B, T, C] (recurrent), and [B, H, W, C]
(NHWC conv) inputs — stats are taken over all axes but the channel axis.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.conf.inputs import (
    InputType,
    InputTypeConvolutional,
    InputTypeFeedForward,
    InputTypeRecurrent,
)
from deeplearning4j_tpu.nn.layers.base import Layer


@dataclass(kw_only=True)
class BatchNormalization(Layer):
    n_out: Optional[int] = None   # channel count, inferred
    decay: float = 0.9            # EMA decay for running stats (reference default)
    eps: float = 1e-5
    gamma: float = 1.0            # init values
    beta: float = 0.0
    lock_gamma_beta: bool = False # if True, gamma/beta fixed (not trained)

    def has_params(self) -> bool:
        return True

    def _channels(self, input_type: InputType) -> int:
        if isinstance(input_type, InputTypeConvolutional):
            return input_type.channels
        if isinstance(input_type, (InputTypeFeedForward, InputTypeRecurrent)):
            return input_type.size
        raise ValueError(f"BatchNormalization: unsupported input {input_type}")

    def set_n_in(self, input_type: InputType) -> None:
        self.n_out = self._channels(input_type)

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def init_params(self, key, input_type, dtype=jnp.float32):
        c = self.n_out or self._channels(input_type)
        if self.lock_gamma_beta:
            return {}
        return {
            "gamma": jnp.full((c,), self.gamma, dtype),
            "beta": jnp.full((c,), self.beta, dtype),
        }

    def init_state(self, input_type, dtype=jnp.float32):
        c = self.n_out or self._channels(input_type)
        return {"mean": jnp.zeros((c,), dtype), "var": jnp.ones((c,), dtype)}

    def apply(self, params, x, *, train=False, rng=None, state=None, mask=None):
        from deeplearning4j_tpu.nn.dtype import is_low_precision

        # Mixed-precision policy: per-channel statistics accumulate in f32
        # (bf16 variance/EMA drifts), but activations stay in the compute
        # dtype end-to-end — the normalization is folded into one per-
        # element multiply-add (x*scale + shift) with [C]-sized f32
        # scale/shift cast down, so BN adds no f32 HBM traffic and fuses
        # with neighboring ops.
        in_dtype = x.dtype
        stat_dtype = jnp.float32 if is_low_precision(in_dtype) else in_dtype
        axes = tuple(range(x.ndim - 1))
        if train:
            mean = jnp.mean(x, axis=axes, dtype=stat_dtype)
            var = jnp.var(x.astype(stat_dtype), axis=axes)
            new_state = None
            if state is not None:
                d = self.decay
                new_state = {
                    "mean": d * state["mean"] + (1.0 - d) * mean,
                    "var": d * state["var"] + (1.0 - d) * var,
                }
        else:
            if state is not None:
                mean, var = state["mean"], state["var"]
            else:
                mean = jnp.mean(x, axis=axes, dtype=stat_dtype)
                var = jnp.var(x.astype(stat_dtype), axis=axes)
            new_state = state

        scale = lax.rsqrt(var + self.eps)
        if not self.lock_gamma_beta and params:
            scale = scale * params["gamma"].astype(stat_dtype)
            shift = params["beta"].astype(stat_dtype) - mean * scale
        elif self.lock_gamma_beta:
            scale = scale * self.gamma
            shift = self.beta - mean * scale
        else:
            shift = -mean * scale
        return x * scale.astype(in_dtype) + shift.astype(in_dtype), new_state
