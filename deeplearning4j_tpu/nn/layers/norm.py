"""BatchNormalization.

Parity: nn/conf/layers/BatchNormalization.java +
nn/layers/normalization/BatchNormalization.java (cuDNN helper hook at
:56-64). Running mean/var live in the layer's *state* pytree (not params), so
`jax.grad` never differentiates them; the train-mode state update is returned
functionally — this is the TPU-native replacement for the reference's mutable
running-stat arrays.

Works on [B, C] (feed-forward), [B, T, C] (recurrent), and [B, H, W, C]
(NHWC conv) inputs — stats are taken over all axes but the channel axis.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.conf.inputs import (
    InputType,
    InputTypeConvolutional,
    InputTypeFeedForward,
    InputTypeRecurrent,
)
from deeplearning4j_tpu.nn.layers.base import Layer
from functools import partial


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def _bn_train(x, gamma, beta, eps):
    """Train-mode batchnorm with a hand-written 2-pass backward.

    Autodiff of the naive formulation emits three separate full
    reductions over the activation tensor in the backward (d-gamma,
    d-beta, and the mean/var chain) — profiled at ~25% of a ResNet50
    step. The classic fused backward needs only two passes:
      pass 1: dbeta = sum(dy), dgamma = sum(dy * xhat)  (sibling
              reductions over one read, multi-output-fused by XLA)
      pass 2: dx = gamma*r * (dy - xhat*dgamma/N - dbeta/N)
    This is the cuDNN-helper-tier equivalent for BN
    (CudnnBatchNormalizationHelper.java) realized as a custom VJP.

    Returns (y, mean, var). Cotangents through mean/var are treated as
    zero: they feed only the running-stat EMA, which is never
    differentiated (it is aux state in the train step).
    """
    y, mean, var, _ = _bn_fwd_impl(x, gamma, beta, eps)
    return y, mean, var


def _bn_stats(x, axes, st):
    """Per-channel mean/var. Low-precision inputs (bf16/f16) use the
    one-pass E[x^2]-E[x]^2 form with f32 accumulation — two sibling
    reductions over one read, multi-output-fused by XLA, saving a full
    HBM pass; the f32 accumulator's extra mantissa over the input dtype
    bounds the cancellation below the input's own quantization. Full-
    precision inputs use the two-pass mean-then-deviations form: at
    x.dtype==f32 the one-pass form cancels catastrophically when
    |mean| >> std (e.g. unnormalized ~1e4 inputs)."""
    mean = jnp.mean(x, axis=axes, dtype=st)
    if st == x.dtype:
        var = jnp.mean(jnp.square(x - mean), axis=axes)
    else:
        mean2 = jnp.mean(jnp.square(x.astype(st)), axis=axes)
        var = jnp.maximum(mean2 - jnp.square(mean), 0.0)
    return mean, var


def _bn_fwd_impl(x, gamma, beta, eps):
    axes = tuple(range(x.ndim - 1))
    st = jnp.promote_types(x.dtype, jnp.float32)   # f32 accum; f64 in
    mean, var = _bn_stats(x, axes, st)             # gradcheck mode
    r = lax.rsqrt(var + eps)
    scale = gamma.astype(st) * r
    shift = beta.astype(st) - mean * scale
    y = x * scale.astype(x.dtype) + shift.astype(x.dtype)
    return y, mean, var, r


def _bn_train_fwd(x, gamma, beta, eps):
    y, mean, var, r = _bn_fwd_impl(x, gamma, beta, eps)
    return (y, mean, var), (x, gamma, mean, r)


def _bn_train_bwd(eps, res, cts):
    dy, _, _ = cts   # mean/var cotangents: zero by construction (EMA aux)
    x, gamma, mean, r = res
    axes = tuple(range(x.ndim - 1))
    n = x.size // x.shape[-1]
    mean_c = mean.astype(x.dtype)
    r_c = r.astype(x.dtype)
    st = jnp.promote_types(x.dtype, jnp.float32)
    xhat = (x - mean_c) * r_c
    dyf = dy.astype(st)
    dgamma = jnp.sum(dyf * xhat.astype(st), axis=axes)
    dbeta = jnp.sum(dyf, axis=axes)
    k = (gamma.astype(st) * r).astype(x.dtype)
    dx = k * (dy - (xhat * (dgamma / n).astype(x.dtype))
              - (dbeta / n).astype(x.dtype))
    return dx, dgamma.astype(gamma.dtype), dbeta.astype(gamma.dtype)


_bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)


@dataclass(kw_only=True)
class BatchNormalization(Layer):
    n_out: Optional[int] = None   # channel count, inferred
    decay: float = 0.9            # EMA decay for running stats (reference default)
    eps: float = 1e-5
    gamma: float = 1.0            # init values
    beta: float = 0.0
    lock_gamma_beta: bool = False # if True, gamma/beta fixed (not trained)
    # SEMANTICS DELTA vs BatchNormalization.java (opt-in, default 1 =
    # exact reference parity): stat_sample=k computes train-mode batch
    # statistics from the LEADING ceil(B/k) examples of the minibatch
    # (a contiguous ghost batch — unbiased when batches are shuffled,
    # which the iterators do). Normalization and gradients stay exact
    # with respect to those sampled statistics; the EMA tracks them.
    # Cuts the statistics pass's HBM reads to 1/k of the activation —
    # the measured exact-BN throughput floor on TPU is set by those
    # reads (PERF.md revised roofline). A contiguous slice (not a
    # strided one) so XLA keeps it inside the surrounding fusions;
    # expect slightly noisier statistics (ghost batch norm with
    # virtual batch B/k).
    stat_sample: int = 1

    def has_params(self) -> bool:
        return True

    def _channels(self, input_type: InputType) -> int:
        if isinstance(input_type, InputTypeConvolutional):
            return input_type.channels
        if isinstance(input_type, (InputTypeFeedForward, InputTypeRecurrent)):
            return input_type.size
        raise ValueError(f"BatchNormalization: unsupported input {input_type}")

    def set_n_in(self, input_type: InputType) -> None:
        self.n_out = self._channels(input_type)

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def init_params(self, key, input_type, dtype=jnp.float32):
        c = self.n_out or self._channels(input_type)
        if self.lock_gamma_beta:
            return {}
        return {
            "gamma": jnp.full((c,), self.gamma, dtype),
            "beta": jnp.full((c,), self.beta, dtype),
        }

    def init_state(self, input_type, dtype=jnp.float32):
        c = self.n_out or self._channels(input_type)
        return {"mean": jnp.zeros((c,), dtype), "var": jnp.ones((c,), dtype)}

    def apply(self, params, x, *, train=False, rng=None, state=None, mask=None):
        from deeplearning4j_tpu.nn.dtype import is_low_precision

        # Mixed-precision policy: per-channel statistics accumulate in f32
        # (bf16 variance/EMA drifts), but activations stay in the compute
        # dtype end-to-end — the normalization is folded into one per-
        # element multiply-add (x*scale + shift) with [C]-sized f32
        # scale/shift cast down, so BN adds no f32 HBM traffic and fuses
        # with neighboring ops.
        in_dtype = x.dtype
        stat_dtype = jnp.float32 if is_low_precision(in_dtype) else in_dtype
        axes = tuple(range(x.ndim - 1))

        def batch_stats(x):
            return _bn_stats(x, axes, stat_dtype)

        if train:
            # fused-backward path (see _bn_train): gamma/beta as arrays
            c = x.shape[-1]
            if not self.lock_gamma_beta and params:
                gamma, beta = params["gamma"], params["beta"]
            else:
                g0 = self.gamma if self.lock_gamma_beta else 1.0
                b0 = self.beta if self.lock_gamma_beta else 0.0
                gamma = jnp.full((c,), g0, stat_dtype)
                beta = jnp.full((c,), b0, stat_dtype)
            if self.stat_sample > 1:
                # ghost/sampled statistics: stats from the leading
                # ghost batch, exact autodiff through them (the mean/
                # var chains reduce over the sample only; dgamma/dbeta
                # stay full-tensor by definition of the affine).
                k = int(self.stat_sample)
                nb = (x.shape[0] - 1) // k + 1
                xs = lax.slice(x, (0,) * x.ndim,
                               (nb,) + tuple(x.shape[1:]))
                mean, var = _bn_stats(xs, axes, stat_dtype)
                r = lax.rsqrt(var + self.eps)
                scale = gamma.astype(stat_dtype) * r
                shift = beta.astype(stat_dtype) - mean * scale
                y = x * scale.astype(in_dtype) + shift.astype(in_dtype)
            else:
                y, mean, var = _bn_train(x, gamma, beta, self.eps)
            new_state = None
            if state is not None:
                d = self.decay
                new_state = {
                    "mean": d * state["mean"] + (1.0 - d) * mean,
                    "var": d * state["var"] + (1.0 - d) * var,
                }
            return y, new_state
        else:
            if state is not None:
                mean, var = state["mean"], state["var"]
            else:
                mean, var = batch_stats(x)
            new_state = state

        scale = lax.rsqrt(var + self.eps)
        if not self.lock_gamma_beta and params:
            scale = scale * params["gamma"].astype(stat_dtype)
            shift = params["beta"].astype(stat_dtype) - mean * scale
        elif self.lock_gamma_beta:
            scale = scale * self.gamma
            shift = self.beta - mean * scale
        else:
            shift = -mean * scale
        return x * scale.astype(in_dtype) + shift.astype(in_dtype), new_state
