"""Core feed-forward layers: Dense, Activation, Dropout, Embedding,
Output/RnnOutput/Loss, GlobalPooling.

Parity targets (reference):
- DenseLayer: nn/conf/layers/DenseLayer.java + nn/layers/feedforward/dense/
- OutputLayer: nn/conf/layers/OutputLayer.java; score at
  MultiLayerNetwork.java:2138 (loss mean over minibatch + l1/l2 terms)
- EmbeddingLayer: nn/conf/layers/EmbeddingLayer.java (integer-index lookup)
- GlobalPoolingLayer: nn/conf/layers/GlobalPoolingLayer.java (mask-aware
  pooling over time or spatial dims)

TPU notes: Dense is a single [B, nIn] x [nIn, nOut] matmul — kept bf16-friendly
and large so XLA tiles it onto the MXU; the activation fuses into the matmul
epilogue. Embedding lookup is `take` (gather), which XLA lowers efficiently;
no sparse-update machinery is needed because gradients flow through gather's
transpose (scatter-add) automatically.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf.inputs import (
    InputType,
    InputTypeConvolutional,
    InputTypeFeedForward,
    InputTypeRecurrent,
)
from deeplearning4j_tpu.nn.layers.base import BaseLayer, Layer
from deeplearning4j_tpu.nn.losses import get_loss
from deeplearning4j_tpu.nn.weights import init_weights


@dataclass(kw_only=True)
class DenseLayer(BaseLayer):
    """Fully connected layer: y = act(x @ W + b)."""

    def set_n_in(self, input_type: InputType) -> None:
        if isinstance(input_type, InputTypeFeedForward):
            self.n_in = input_type.size
        elif isinstance(input_type, InputTypeRecurrent):
            # Dense applied per-timestep over [B, T, C]
            self.n_in = input_type.size
        else:
            raise ValueError(
                f"DenseLayer needs feed-forward input, got {input_type}"
            )

    def output_type(self, input_type: InputType) -> InputType:
        if isinstance(input_type, InputTypeRecurrent):
            return InputType.recurrent(self.n_out, input_type.timeseries_length)
        return InputType.feed_forward(self.n_out)

    def init_params(self, key, input_type, dtype=jnp.float32):
        wkey, _ = jax.random.split(key)
        W = init_weights(
            self.weight_init, wkey, (self.n_in, self.n_out),
            fan_in=self.n_in, fan_out=self.n_out, dtype=dtype,
        )
        b = jnp.full((self.n_out,), self.bias_init, dtype)
        return {"W": W, "b": b}

    def apply(self, params, x, *, train=False, rng=None, state=None, mask=None):
        x = self._maybe_dropout_input(x, train, rng)
        y = x @ params["W"] + params["b"]
        return get_activation(self.activation)(y), state


@dataclass(kw_only=True)
class ActivationLayer(Layer):
    """Applies an activation function elementwise (no params)."""

    activation: str = "relu"

    def apply(self, params, x, *, train=False, rng=None, state=None, mask=None):
        return get_activation(self.activation)(x), state


@dataclass(kw_only=True)
class DropoutLayer(Layer):
    """Standalone inverted-dropout layer (identity at inference)."""

    dropout: float = 0.5

    def apply(self, params, x, *, train=False, rng=None, state=None, mask=None):
        return self._maybe_dropout_input(x, train, rng), state


@dataclass(kw_only=True)
class EmbeddingLayer(BaseLayer):
    """Lookup-table layer: integer indices [B] or [B,1] -> vectors [B, nOut].

    Reference equivalent feeds one-hot through a weight matrix; on TPU a
    gather is strictly better (no materialized one-hot).
    """

    activation: Optional[str] = "identity"

    def set_n_in(self, input_type: InputType) -> None:
        if isinstance(input_type, InputTypeFeedForward):
            self.n_in = input_type.size

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init_params(self, key, input_type, dtype=jnp.float32):
        W = init_weights(
            self.weight_init, key, (self.n_in, self.n_out),
            fan_in=self.n_in, fan_out=self.n_out, dtype=dtype,
        )
        b = jnp.full((self.n_out,), self.bias_init, dtype)
        return {"W": W, "b": b}

    def apply(self, params, x, *, train=False, rng=None, state=None, mask=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 2 and idx.shape[-1] == 1:
            idx = idx[:, 0]
        y = jnp.take(params["W"], idx, axis=0) + params["b"]
        return get_activation(self.activation)(y), state


@dataclass(kw_only=True)
class BaseOutputLayer(BaseLayer):
    """Shared logic for output layers: loss computation over pre-activations."""

    loss: str = "mcxent"
    activation: Optional[str] = "softmax"

    def compute_per_example_loss(self, labels, pre_output, mask=None):
        return get_loss(self.loss)(labels, pre_output, self.activation, mask)

    def pre_output(self, params, x):
        return x @ params["W"] + params["b"]

    def per_example_loss_from_input(self, params, x, labels, mask=None):
        """Loss seen from the layer's *input* activations; the hook output
        layers override when the loss needs the features themselves
        (center loss)."""
        return self.compute_per_example_loss(
            labels, self.pre_output(params, x), mask=mask)


@dataclass(kw_only=True)
class OutputLayer(BaseOutputLayer):
    """Dense + loss head for classification/regression."""

    def set_n_in(self, input_type: InputType) -> None:
        if isinstance(input_type, InputTypeRecurrent):
            raise ValueError(
                "OutputLayer got recurrent [B, T, C] input; use RnnOutputLayer "
                "for per-timestep outputs, or insert a "
                "RnnToFeedForwardPreProcessor / GlobalPoolingLayer first"
            )
        if isinstance(input_type, InputTypeFeedForward):
            self.n_in = input_type.size
        else:
            raise ValueError(f"OutputLayer needs flat input, got {input_type}")

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    init_params = DenseLayer.init_params

    def apply(self, params, x, *, train=False, rng=None, state=None, mask=None):
        x = self._maybe_dropout_input(x, train, rng)
        return get_activation(self.activation)(self.pre_output(params, x)), state


@dataclass(kw_only=True)
class CenterLossOutputLayer(OutputLayer):
    """Softmax + center loss: L = Lsoftmax + (lambda/2)·||f - c_y||²
    (ref: nn/conf/layers/CenterLossOutputLayer.java,
    nn/layers/training/CenterLossOutputLayer.java). The reference updates
    centers with an alpha moving average outside the optimizer; here the
    centers are parameters trained by the same gradient step (the center
    term's gradient wrt c_y is alpha-like), scaled by `alpha`.
    """

    alpha: float = 0.05
    lambda_: float = 2e-4

    def init_params(self, key, input_type, dtype=jnp.float32):
        p = DenseLayer.init_params(self, key, input_type, dtype)
        p["centers"] = jnp.zeros((self.n_out, self.n_in), dtype)
        return p

    def per_example_loss_from_input(self, params, x, labels, mask=None):
        base = self.compute_per_example_loss(
            labels, self.pre_output(params, x), mask=mask)
        # centers of each example's class: labels one-hot [B, nClasses]
        lab2d = labels if labels.ndim == 2 else labels.reshape(
            -1, labels.shape[-1])
        x2d = x if x.ndim == 2 else x.reshape(-1, x.shape[-1])
        cy = lab2d @ params["centers"]                  # [B, nIn]
        center_term = 0.5 * jnp.sum((x2d - cy) ** 2, axis=-1)
        # alpha scales how fast centers chase features (gradient wrt
        # centers is alpha * lambda * (c_y - f))
        center_term = center_term.reshape(base.shape)
        if mask is not None:
            m = mask if mask.ndim == base.ndim else mask.reshape(base.shape)
            center_term = center_term * m
        return base + self.lambda_ * self.alpha * center_term


@dataclass(kw_only=True)
class RnnOutputLayer(BaseOutputLayer):
    """Per-timestep output head over [B, T, C] activations."""

    def set_n_in(self, input_type: InputType) -> None:
        if isinstance(input_type, InputTypeRecurrent):
            self.n_in = input_type.size
        else:
            raise ValueError(f"RnnOutputLayer needs recurrent input, got {input_type}")

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, getattr(input_type, "timeseries_length", None))

    init_params = DenseLayer.init_params

    def apply(self, params, x, *, train=False, rng=None, state=None, mask=None):
        x = self._maybe_dropout_input(x, train, rng)
        return get_activation(self.activation)(self.pre_output(params, x)), state


@dataclass(kw_only=True)
class LossLayer(BaseOutputLayer):
    """Loss-only head: no weights, input passes straight to the loss
    (ref: nn/conf/layers/LossLayer.java)."""

    activation: Optional[str] = "identity"

    def has_params(self) -> bool:
        return False

    def init_params(self, key, input_type, dtype=jnp.float32):
        return {}

    def pre_output(self, params, x):
        return x

    def apply(self, params, x, *, train=False, rng=None, state=None, mask=None):
        return get_activation(self.activation)(x), state


@dataclass(kw_only=True)
class GlobalPoolingLayer(Layer):
    """Mask-aware global pooling over time ([B,T,C] -> [B,C]) or spatial dims
    ([B,H,W,C] -> [B,C]). pooling_type: max | avg | sum | pnorm."""

    pooling_type: str = "max"
    pnorm: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        if isinstance(input_type, InputTypeRecurrent):
            return InputType.feed_forward(input_type.size)
        if isinstance(input_type, InputTypeConvolutional):
            return InputType.feed_forward(input_type.channels)
        return input_type

    def apply(self, params, x, *, train=False, rng=None, state=None, mask=None):
        if x.ndim == 3:
            axes = (1,)
        elif x.ndim == 4:
            axes = (1, 2)
        else:
            raise ValueError(f"GlobalPooling needs rank 3 or 4 input, got {x.shape}")

        pt = self.pooling_type.lower()
        if mask is not None and x.ndim == 3:
            m = mask[..., None]
            if pt == "max":
                x = jnp.where(m > 0, x, -jnp.inf)
            else:
                x = x * m
            count = jnp.maximum(jnp.sum(mask, axis=1, keepdims=True), 1.0)
        else:
            count = None

        if pt == "max":
            return jnp.max(x, axis=axes), state
        if pt == "sum":
            return jnp.sum(x, axis=axes), state
        if pt == "avg":
            s = jnp.sum(x, axis=axes)
            if count is not None:
                return s / count, state
            denom = 1.0
            for a in axes:
                denom *= x.shape[a]
            return s / denom, state
        if pt == "pnorm":
            p = float(self.pnorm)
            return jnp.sum(jnp.abs(x) ** p, axis=axes) ** (1.0 / p), state
        raise ValueError(f"Unknown pooling type {self.pooling_type}")

    def feed_forward_mask(self, mask, input_type):
        return None  # time dim is reduced away
