"""Recurrent layers: LSTM, GravesLSTM (peepholes), GravesBidirectionalLSTM.

Parity: nn/conf/layers/{LSTM,GravesLSTM,GravesBidirectionalLSTM}.java and the
hand-written per-timestep loops in nn/layers/recurrent/LSTMHelpers.java:182
(forward) and :448 (backward).

TPU-first design: the time loop is `lax.scan` (compiled once, not unrolled);
the four gate matmuls are fused into ONE [*, 4H] matmul per step so the MXU
sees a single large GEMM; the input projection x @ W for ALL timesteps is
hoisted out of the scan as one [B*T, nIn] x [nIn, 4H] matmul. Backward comes
from `jax.grad` differentiating the scan — no hand-written BPTT.

Gate packing order along the 4H axis: [i (input), f (forget), o (output),
g (cell candidate)].

Masking: mask [B, T] freezes the carry where mask==0 (variable-length
sequences in a static-shape batch).

Streaming inference (`rnnTimeStep`, MultiLayerNetwork.java:2526): each layer
exposes `step(params, x_t, carry)`; the container threads carries.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf.inputs import InputType, InputTypeRecurrent
from deeplearning4j_tpu.nn.layers.base import BaseLayer
from deeplearning4j_tpu.nn.weights import init_weights


def _lstm_cell(gates_t, c_prev, gate_act, cell_act, peepholes=None):
    """One LSTM cell update given the pre-activation fused gates [B, 4H]."""
    H = c_prev.shape[-1]
    i_g, f_g, o_g, g_g = jnp.split(gates_t, 4, axis=-1)
    if peepholes is not None:
        p_i, p_f, p_o = peepholes
        i_g = i_g + c_prev * p_i
        f_g = f_g + c_prev * p_f
    i = gate_act(i_g)
    f = gate_act(f_g)
    g = cell_act(g_g)
    c = f * c_prev + i * g
    if peepholes is not None:
        o_g = o_g + c * p_o
    o = gate_act(o_g)
    h = o * cell_act(c)
    return h, c


@dataclass(kw_only=True)
class LSTM(BaseLayer):
    """Standard LSTM over [B, T, nIn] -> [B, T, nOut]."""

    activation: Optional[str] = "tanh"
    gate_activation: str = "sigmoid"
    forget_gate_bias_init: float = 1.0
    # Recompute gate pre-activations in the backward pass instead of
    # saving the per-step gate stacks (the cuDNN-LSTM recompute
    # tradeoff, LSTMHelpers.java:448's fwdPassOutputAsArrays role):
    # BPTT then streams only the [T,B,H] h/c carries from HBM instead
    # of several [T,B,4H] residual stacks. Costs one extra RW matmul
    # per step in backward; wins when the saved-stack HBM traffic is
    # the bottleneck (large B*T; PERF.md LSTM roofline).
    bptt_remat: bool = False

    _peepholes: bool = False  # GravesLSTM flips this

    def set_n_in(self, input_type: InputType) -> None:
        if not isinstance(input_type, InputTypeRecurrent):
            raise ValueError(f"LSTM needs recurrent input, got {input_type}")
        self.n_in = input_type.size

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, getattr(input_type, "timeseries_length", None))

    def init_params(self, key, input_type, dtype=jnp.float32):
        kW, kR, kP = jax.random.split(key, 3)
        H = self.n_out
        W = init_weights(self.weight_init, kW, (self.n_in, 4 * H),
                         fan_in=self.n_in, fan_out=H, dtype=dtype)
        RW = init_weights(self.weight_init, kR, (H, 4 * H),
                          fan_in=H, fan_out=H, dtype=dtype)
        b = jnp.zeros((4 * H,), dtype)
        # forget-gate bias block = index 1 in [i, f, o, g] packing
        b = b.at[H:2 * H].set(self.forget_gate_bias_init)
        params = {"W": W, "RW": RW, "b": b}
        if self._peepholes:
            params["P"] = init_weights(
                self.weight_init, kP, (3, H), fan_in=H, fan_out=H, dtype=dtype
            )
        return params

    # ---- single-step cell (streaming inference + scan body) ----
    def step(self, params, x_t, carry):
        """x_t [B, nIn], carry (h [B,H], c [B,H]) -> (y_t [B,H], new carry)."""
        h_prev, c_prev = carry
        gate_act = get_activation(self.gate_activation)
        cell_act = get_activation(self.activation)
        gates = x_t @ params["W"] + h_prev @ params["RW"] + params["b"]
        peep = tuple(params["P"]) if self._peepholes else None
        h, c = _lstm_cell(gates, c_prev, gate_act, cell_act, peep)
        return h, (h, c)

    def initial_carry(self, batch_size, dtype=jnp.float32):
        H = self.n_out
        return (jnp.zeros((batch_size, H), dtype), jnp.zeros((batch_size, H), dtype))

    def _scan(self, params, x, mask, carry0, reverse=False):
        """Run the full sequence. x [B, T, nIn] -> outputs [B, T, H]."""
        B, T, _ = x.shape
        gate_act = get_activation(self.gate_activation)
        cell_act = get_activation(self.activation)
        peep = tuple(params["P"]) if self._peepholes else None

        # Hoist the input projection for all timesteps: one big MXU
        # matmul. Project AFTER going time-major when the input is the
        # smaller tensor (nIn <= 4H — every stacked layer, and any
        # vocab < 4H): the layout swap then moves [B,T,nIn] bytes
        # instead of the up-to-4x bigger [B,T,4H] projection. Same
        # contraction, bit-identical outputs — the program lint's
        # transpose-churn byte accounting flagged the old order
        # (PERF.md item-1 baseline audit).
        if x.shape[-1] <= 4 * self.n_out:
            xw_t = (jnp.swapaxes(x, 0, 1) @ params["W"]
                    + params["b"])                  # [T, B, 4H]
        else:
            xw_t = jnp.swapaxes(x @ params["W"] + params["b"], 0, 1)
        mask_t = None if mask is None else jnp.swapaxes(mask, 0, 1)  # [T, B]

        def body(carry, inputs):
            h_prev, c_prev = carry
            if mask_t is None:
                gates_t = inputs
                m = None
            else:
                gates_t, m = inputs
            gates = gates_t + h_prev @ params["RW"]
            h, c = _lstm_cell(gates, c_prev, gate_act, cell_act, peep)
            if m is not None:
                keep = m[:, None]
                h = jnp.where(keep > 0, h, h_prev)
                c = jnp.where(keep > 0, c, c_prev)
            return (h, c), h

        xs = xw_t if mask_t is None else (xw_t, mask_t)
        if self.bptt_remat:
            # prevent_cse=False is safe under scan (each iteration is
            # its own remat scope) and lets XLA fuse the recompute.
            body = jax.checkpoint(body, prevent_cse=False)
        carry, hs = lax.scan(body, carry0, xs, reverse=reverse)
        return jnp.swapaxes(hs, 0, 1), carry        # back to [B, T, H]

    def apply(self, params, x, *, train=False, rng=None, state=None, mask=None):
        x = self._maybe_dropout_input(x, train, rng)
        carry0 = state if state is not None else self.initial_carry(x.shape[0], x.dtype)
        out, carry = self._scan(params, x, mask, carry0)
        return out, carry

    def feed_forward_mask(self, mask, input_type):
        return mask


@dataclass(kw_only=True)
class GravesLSTM(LSTM):
    """LSTM with peephole connections (Graves 2013 formulation), the
    reference's workhorse recurrent layer."""

    _peepholes: bool = True


@dataclass(kw_only=True)
class GravesBidirectionalLSTM(BaseLayer):
    """Bidirectional peephole LSTM; forward and backward passes concatenated
    on the feature axis -> [B, T, 2*nOut]."""

    activation: Optional[str] = "tanh"
    gate_activation: str = "sigmoid"
    forget_gate_bias_init: float = 1.0

    def _directional(self) -> GravesLSTM:
        return GravesLSTM(
            n_in=self.n_in, n_out=self.n_out, activation=self.activation,
            gate_activation=self.gate_activation,
            forget_gate_bias_init=self.forget_gate_bias_init,
            weight_init=self.weight_init, bias_init=self.bias_init,
        )

    def set_n_in(self, input_type: InputType) -> None:
        if not isinstance(input_type, InputTypeRecurrent):
            raise ValueError(f"BiLSTM needs recurrent input, got {input_type}")
        self.n_in = input_type.size

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(2 * self.n_out, getattr(input_type, "timeseries_length", None))

    def init_params(self, key, input_type, dtype=jnp.float32):
        kf, kb = jax.random.split(key)
        sub = self._directional()
        return {
            "fwd": sub.init_params(kf, input_type, dtype),
            "bwd": sub.init_params(kb, input_type, dtype),
        }

    def apply(self, params, x, *, train=False, rng=None, state=None, mask=None):
        x = self._maybe_dropout_input(x, train, rng)
        sub = self._directional()
        zero = sub.initial_carry(x.shape[0], x.dtype)
        # The forward direction carries state across calls (TBPTT chunks /
        # streaming); the backward direction is anti-causal, so it must
        # restart from zero within each window — carrying it would leak
        # future state backwards.
        c0_fwd = state[0] if state is not None else zero
        fwd, cf = sub._scan(params["fwd"], x, mask, c0_fwd)
        bwd, cb = sub._scan(params["bwd"], x, mask, zero, reverse=True)
        return jnp.concatenate([fwd, bwd], axis=-1), (cf, cb)

    def feed_forward_mask(self, mask, input_type):
        return mask
