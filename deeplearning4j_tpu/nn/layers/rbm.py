"""Restricted Boltzmann Machine layer with CD-k pretraining.

Parity: nn/conf/layers/RBM.java (HiddenUnit/VisibleUnit enums :85-88,
k/sparsity :104-105) + nn/layers/feedforward/rbm/RBM.java
(contrastiveDivergence :102, propUp :324, propDown :390).

TPU-native redesign: the reference hand-computes the four CD matrices
(v0 h0 / vk hk outer products). Here CD-k is expressed as the gradient
of a FREE-ENERGY DIFFERENCE surrogate,

    L(theta) = mean F(v_data) - mean F(stop_gradient(v_model))

where v_model is the k-step Gibbs sample. d/dtheta of that difference
IS the CD-k update (the standard energy-based-model identity), so the
layer plugs into the same jax.grad-driven greedy pretraining machinery
as AutoEncoder/VAE (MultiLayerNetwork.pretrain) — no bespoke update
path, and XLA fuses the whole Gibbs chain into one compiled step.

Supervised forward = propUp (the hidden activation), matching the
reference's use of RBM as a feed-forward layer after pretraining.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf.inputs import InputType, InputTypeFeedForward
from deeplearning4j_tpu.nn.layers.base import BaseLayer
from deeplearning4j_tpu.nn.weights import init_weights

_UNITS = ("BINARY", "GAUSSIAN", "RECTIFIED", "IDENTITY")


@dataclass(kw_only=True)
class RBM(BaseLayer):
    hidden_unit: str = "BINARY"
    visible_unit: str = "BINARY"
    k: int = 1                      # CD-k Gibbs steps
    sparsity: float = 0.0           # hidden sparsity target penalty
    activation: Optional[str] = "sigmoid"

    def __post_init__(self):
        hu = self.hidden_unit.upper()
        vu = self.visible_unit.upper()
        if hu not in _UNITS or vu not in _UNITS:
            raise ValueError(
                f"hidden/visible unit must be one of {_UNITS}: "
                f"{self.hidden_unit}/{self.visible_unit}")
        self.hidden_unit = hu
        self.visible_unit = vu

    # ----------------------------------------------------------- config
    def set_n_in(self, input_type: InputType) -> None:
        self.n_in = input_type.size if isinstance(
            input_type, InputTypeFeedForward) \
            else input_type.arrays_per_example()

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init_params(self, key, input_type, dtype=jnp.float32):
        kw, _ = jax.random.split(key)
        W = init_weights(self.weight_init, kw, (self.n_in, self.n_out),
                         fan_in=self.n_in, fan_out=self.n_out,
                         dtype=dtype)
        return {
            "W": W,
            "b": jnp.zeros((self.n_out,), dtype),    # hidden bias
            "vb": jnp.zeros((self.n_in,), dtype),    # visible bias
        }

    # ----------------------------------------------- conditional units
    def prop_up(self, params, v):
        """P(h|v) mean (RBM.java propUp :324)."""
        z = v @ params["W"] + params["b"]
        if self.hidden_unit == "BINARY":
            return jax.nn.sigmoid(z)
        if self.hidden_unit == "RECTIFIED":
            return jax.nn.relu(z)
        return z  # GAUSSIAN / IDENTITY mean

    def prop_down(self, params, h):
        """P(v|h) mean (RBM.java propDown :390)."""
        z = h @ params["W"].T + params["vb"]
        if self.visible_unit == "BINARY":
            return jax.nn.sigmoid(z)
        if self.visible_unit == "RECTIFIED":
            return jax.nn.relu(z)
        return z

    def _sample_h(self, params, v, rng):
        p = self.prop_up(params, v)
        if self.hidden_unit == "BINARY":
            return p, jax.random.bernoulli(rng, p).astype(v.dtype)
        if self.hidden_unit == "GAUSSIAN":
            return p, p + jax.random.normal(rng, p.shape, p.dtype)
        return p, p  # RECTIFIED/IDENTITY: mean-field

    def _sample_v(self, params, h, rng):
        p = self.prop_down(params, h)
        if self.visible_unit == "BINARY":
            return p, jax.random.bernoulli(rng, p).astype(h.dtype)
        if self.visible_unit == "GAUSSIAN":
            return p, p + jax.random.normal(rng, p.shape, p.dtype)
        return p, p

    # ------------------------------------------------------ free energy
    def free_energy(self, params, v):
        """F(v) = vis_term - hidden_term, mean over the batch.

        The hidden term comes from integrating the hidden units out of
        the joint energy, so it is UNIT-SPECIFIC: sum softplus(vW+b)
        for BINARY hidden units, sum (vW+b)^2/2 for unit-variance
        GAUSSIAN hidden units. RECTIFIED/IDENTITY hidden units have no
        closed-form free energy — pretrain_loss rejects them so the
        CD-k-as-free-energy-gradient identity is never silently wrong
        (the reference instead builds unit-specific CD matrices,
        RBM.java contrastiveDivergence :102)."""
        z = v @ params["W"] + params["b"]
        if self.hidden_unit == "BINARY":
            hidden_term = jnp.sum(jax.nn.softplus(z), axis=-1)
        elif self.hidden_unit == "GAUSSIAN":
            hidden_term = 0.5 * jnp.sum(z * z, axis=-1)
        else:
            raise NotImplementedError(
                f"free_energy has no closed form for "
                f"{self.hidden_unit} hidden units; CD pretraining "
                "supports BINARY/GAUSSIAN hidden units only")
        if self.visible_unit == "GAUSSIAN":
            vis_term = 0.5 * jnp.sum((v - params["vb"]) ** 2, axis=-1)
        else:
            vis_term = -v @ params["vb"]
        return jnp.mean(vis_term - hidden_term)

    # ------------------------------------------------------- pretrain
    def gibbs_sample(self, params, v0, rng, k: Optional[int] = None):
        """k alternating Gibbs steps from v0; returns the final visible
        sample (RBM.java's sampleHiddenGivenVisible/sampleVisibleGiven-
        Hidden chain :143)."""
        k = self.k if k is None else k
        v = v0
        for i in range(max(k, 1)):
            rh, rv = jax.random.split(jax.random.fold_in(rng, i))
            _, h = self._sample_h(params, v, rh)
            _, v = self._sample_v(params, h, rv)
        return v

    def pretrain_loss(self, params, x, rng):
        """CD-k as the free-energy-difference surrogate (see module
        docstring); optional sparsity penalty pulls the mean hidden
        activation toward `sparsity` (RBM.java sparsity :64)."""
        if rng is None:
            rng = jax.random.PRNGKey(0)
        v_model = jax.lax.stop_gradient(
            self.gibbs_sample(params, x, rng))
        loss = (self.free_energy(params, x)
                - self.free_energy(params, v_model))
        if self.sparsity > 0.0:
            h_mean = jnp.mean(self.prop_up(params, x), axis=0)
            loss = loss + jnp.mean((h_mean - self.sparsity) ** 2)
        return loss

    def reconstruction_error(self, params, x, rng=None):
        """Mean-squared reconstruction error after one up-down pass —
        the monitorable proxy the reference logs during CD."""
        v1 = self.prop_down(params, self.prop_up(params, x))
        return jnp.mean((x - v1) ** 2)

    # ------------------------------------------------------- forward
    def apply(self, params, x, *, train=False, rng=None, state=None,
              mask=None):
        x = self._maybe_dropout_input(x, train, rng)
        return self.prop_up(params, x), state
