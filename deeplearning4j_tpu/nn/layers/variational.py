"""Variational autoencoder layer.

Parity: nn/conf/layers/variational/VariationalAutoencoder.java +
nn/layers/variational/VariationalAutoencoder.java (1,142 LoC of hand-written
forward/backward in the reference; here the ELBO is a pure function and
`jax.grad` derives everything).

Used two ways, like the reference:
- unsupervised pretraining: `pretrain_loss` = negative ELBO
  (reconstruction log-prob under the chosen distribution + KL(q(z|x) || N(0,I)))
- supervised forward pass: `apply` runs the encoder mean path
  (reference behavior: activate() returns the latent mean).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf.inputs import InputType, InputTypeFeedForward
from deeplearning4j_tpu.nn.layers.base import BaseLayer
from deeplearning4j_tpu.nn.weights import init_weights

# math.log, not jnp.log: module constants must never trigger device/backend
# initialization at import time (breaks CPU-platform selection in dryruns).
_HALF_LOG_2PI = 0.5 * math.log(2.0 * math.pi)


def _mlp_init(key, sizes, weight_init, dtype):
    params = []
    keys = jax.random.split(key, len(sizes) - 1)
    for k, (a, b) in zip(keys, zip(sizes[:-1], sizes[1:])):
        W = init_weights(weight_init, k, (a, b), fan_in=a, fan_out=b, dtype=dtype)
        params.append({"W": W, "b": jnp.zeros((b,), dtype)})
    return params

def _mlp_apply(params, x, act):
    for p in params:
        x = act(x @ p["W"] + p["b"])
    return x


@dataclass(kw_only=True)
class VariationalAutoencoder(BaseLayer):
    encoder_layer_sizes: Sequence[int] = (100,)
    decoder_layer_sizes: Sequence[int] = (100,)
    latent_size: int = 32              # == n_out for the supervised path
    reconstruction_distribution: str = "gaussian"  # gaussian | bernoulli
    pzx_activation: str = "identity"   # activation on latent mean/logvar heads
    num_samples: int = 1
    activation: Optional[str] = "tanh"

    def __post_init__(self):
        if self.n_out is None:
            self.n_out = self.latent_size

    def set_n_in(self, input_type: InputType) -> None:
        self.n_in = input_type.arrays_per_example() if not isinstance(
            input_type, InputTypeFeedForward) else input_type.size

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.latent_size)

    def init_params(self, key, input_type, dtype=jnp.float32):
        k_enc, k_mu, k_lv, k_dec, k_out = jax.random.split(key, 5)
        enc_sizes = [self.n_in, *self.encoder_layer_sizes]
        dec_sizes = [self.latent_size, *self.decoder_layer_sizes]
        eh = enc_sizes[-1]
        dh = dec_sizes[-1]
        # gaussian reconstruction emits mean+logvar; bernoulli emits logits
        out_mult = 2 if self.reconstruction_distribution == "gaussian" else 1
        wi = self.weight_init
        return {
            "encoder": _mlp_init(k_enc, enc_sizes, wi, dtype),
            "mu": {
                "W": init_weights(wi, k_mu, (eh, self.latent_size),
                                  fan_in=eh, fan_out=self.latent_size, dtype=dtype),
                "b": jnp.zeros((self.latent_size,), dtype),
            },
            "logvar": {
                "W": init_weights(wi, k_lv, (eh, self.latent_size),
                                  fan_in=eh, fan_out=self.latent_size, dtype=dtype),
                "b": jnp.zeros((self.latent_size,), dtype),
            },
            "decoder": _mlp_init(k_dec, dec_sizes, wi, dtype),
            "out": {
                "W": init_weights(wi, k_out, (dh, out_mult * self.n_in),
                                  fan_in=dh, fan_out=out_mult * self.n_in, dtype=dtype),
                "b": jnp.zeros((out_mult * self.n_in,), dtype),
            },
        }

    def encode(self, params, x):
        act = get_activation(self.activation)
        h = _mlp_apply(params["encoder"], x, act)
        head_act = get_activation(self.pzx_activation)
        mu = head_act(h @ params["mu"]["W"] + params["mu"]["b"])
        logvar = head_act(h @ params["logvar"]["W"] + params["logvar"]["b"])
        return mu, logvar

    def decode(self, params, z):
        act = get_activation(self.activation)
        h = _mlp_apply(params["decoder"], z, act)
        return h @ params["out"]["W"] + params["out"]["b"]

    def apply(self, params, x, *, train=False, rng=None, state=None, mask=None):
        x = self._maybe_dropout_input(x, train, rng)
        mu, _ = self.encode(params, x)
        return mu, state

    def reconstruct(self, params, x, rng=None):
        """Encode → (sample or mean) → decode → reconstruction mean."""
        mu, logvar = self.encode(params, x)
        z = mu if rng is None else mu + jnp.exp(0.5 * logvar) * jax.random.normal(
            rng, mu.shape, mu.dtype)
        out = self.decode(params, z)
        if self.reconstruction_distribution == "gaussian":
            return jnp.split(out, 2, axis=-1)[0]
        return jax.nn.sigmoid(out)

    def pretrain_loss(self, params, x, rng):
        """Negative ELBO, mean over the batch."""
        mu, logvar = self.encode(params, x)
        total = 0.0
        keys = jax.random.split(rng, self.num_samples)
        for k in keys:
            eps = jax.random.normal(k, mu.shape, mu.dtype)
            z = mu + jnp.exp(0.5 * logvar) * eps
            out = self.decode(params, z)
            if self.reconstruction_distribution == "gaussian":
                r_mu, r_logvar = jnp.split(out, 2, axis=-1)
                logp = -0.5 * ((x - r_mu) ** 2 * jnp.exp(-r_logvar)
                               + r_logvar) - _HALF_LOG_2PI
            else:  # bernoulli with logits
                logp = x * jax.nn.log_sigmoid(out) + (1 - x) * jax.nn.log_sigmoid(-out)
            total = total + jnp.sum(logp, axis=-1)
        recon = total / self.num_samples
        kl = 0.5 * jnp.sum(jnp.exp(logvar) + mu * mu - 1.0 - logvar, axis=-1)
        return jnp.mean(-recon + kl)
