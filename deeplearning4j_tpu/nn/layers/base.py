"""Layer base classes.

Design: the reference splits each layer into a *config* class
(nn/conf/layers/*.java) and an *implementation* class (nn/layers/*.java)
with hand-written `activate`/`backpropGradient` (ref: nn/api/Layer.java:119,202).
In a functional JAX framework that split disappears: a layer is a frozen
dataclass of hyperparameters carrying two pure functions —
`init_params(key, input_type) -> pytree` and
`apply(params, x, ...) -> (y, state)` — and the backward pass is derived by
`jax.grad` over the whole network. Shape inference (`output_type`) mirrors
the reference's InputType propagation (nn/conf/inputs/InputType.java:62-94).

Mutable per-layer state (BatchNorm running stats, RNN carry for streaming
inference) lives in a separate `state` pytree threaded through `apply`,
keeping params/state separation explicit for `jax.grad`.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType


@dataclass(kw_only=True)
class Layer:
    """Base hyperparameter container for all layers.

    Fields set to None inherit the network-level default from
    NeuralNetConfiguration at build() time (mirroring the reference's
    global-config → per-layer override flow,
    NeuralNetConfiguration.java:521-563).
    """

    name: Optional[str] = None
    # frozen layers keep their params fixed during fit (ref:
    # nn/layers/FrozenLayer.java — here a flag instead of a wrapper class)
    frozen: bool = False
    # None = inherit the global NeuralNetConfiguration default at build()
    dropout: Optional[float] = None  # inverted dropout on layer *input* in training
    l1: Optional[float] = None
    l2: Optional[float] = None
    updater: Optional[str] = None          # per-layer updater override
    learning_rate: Optional[float] = None  # per-layer LR override
    bias_learning_rate: Optional[float] = None

    # ---- shape inference ----
    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def set_n_in(self, input_type: InputType) -> None:
        """Infer nIn from the incoming InputType (no-op for param-free layers)."""

    # ---- parameters ----
    def init_params(self, key, input_type: InputType, dtype=jnp.float32) -> Dict[str, Any]:
        return {}

    def init_state(self, input_type: InputType, dtype=jnp.float32) -> Dict[str, Any]:
        return {}

    def has_params(self) -> bool:
        return False

    # ---- forward ----
    def apply(self, params, x, *, train: bool = False, rng=None, state=None, mask=None):
        """Returns (output, new_state)."""
        raise NotImplementedError

    # ---- masking ----
    def feed_forward_mask(self, mask, input_type: InputType):
        """Propagate a [batch] or [batch, time] mask through this layer
        (ref: nn/api/Layer.java:309 feedForwardMaskArray)."""
        return mask

    # ---- regularization ----
    def regularization_loss(self, params) -> jnp.ndarray:
        """L1/L2 penalty over this layer's weight (non-bias) params."""
        l1 = self.l1 or 0.0
        l2 = self.l2 or 0.0
        if not params or (l1 == 0.0 and l2 == 0.0):
            return jnp.asarray(0.0)
        reg = 0.0
        # Walk leaves with their paths so nested param dicts (BiLSTM fwd/bwd,
        # VAE sub-nets) are handled: a leaf is a bias iff its own dict key
        # starts with 'b' (b, vb, beta, ...); biases are exempt per the
        # reference default.
        leaves_with_path = jax.tree_util.tree_flatten_with_path(params)[0]
        for path, leaf in leaves_with_path:
            last = path[-1]
            key_name = getattr(last, "key", None) or getattr(last, "name", "")
            if str(key_name).startswith("b") or str(key_name) == "centers":
                continue
            if l2:
                reg = reg + 0.5 * l2 * jnp.sum(leaf * leaf)
            if l1:
                reg = reg + l1 * jnp.sum(jnp.abs(leaf))
        return jnp.asarray(reg)

    # ---- input dropout (shared by all layers) ----
    def _maybe_dropout_input(self, x, train, rng):
        if not train or not self.dropout or self.dropout <= 0.0:
            return x
        if rng is None:
            raise ValueError(
                f"Layer {self.name or type(self).__name__} has dropout but no rng"
            )
        keep = 1.0 - self.dropout
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0)

    # ---- serde ----
    def to_dict(self) -> dict:
        d = {"type": type(self).__name__}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if isinstance(v, InputType):
                v = v.to_dict()
            d[f.name] = v
        return d

    def clone(self, **overrides) -> "Layer":
        return dataclasses.replace(self, **overrides)


@dataclass(kw_only=True)
class BaseLayer(Layer):
    """Base for layers with weights + an activation (dense/conv/rnn families)."""

    n_in: Optional[int] = None
    n_out: Optional[int] = None
    # None = inherit global default (activation: sigmoid, weight_init: xavier);
    # subclasses with a strong convention override the class default
    # (OutputLayer: softmax, LSTM: tanh) and explicit user values always win.
    activation: Optional[str] = None
    weight_init: Optional[str] = None
    bias_init: float = 0.0

    def has_params(self) -> bool:
        return True
