"""Pallas fused convolution pipeline kernels (TPU).

The cuDNN-helper tier reborn for TPU (parity role:
CudnnConvolutionHelper.java:54,120 hooked at ConvolutionLayer.java:74-84;
CudnnBatchNormalizationHelper.java). The reference's helper accelerates
each layer in isolation; on TPU the win is *pass-count*: a ResNet-style
conv→BN→relu(→add) chain costs XLA one conv kernel plus 2-3 full
HBM passes of BN-stats / BN-apply / add glue per activation (profiled in
PERF.md at ~70% of the step). These kernels collapse the chain:

  - PROLOGUE: the convolution reads its input as raw pre-BN conv output
    and applies `relu(scale*x + shift [+ residual])` per tile as it
    loads — the BN-apply/activation/residual-add pass never exists as an
    HBM round-trip.
  - MATMUL: 1x1 convs are row-major matmuls over M=B*H*W; 3x3 convs
    build an im2col tile in VMEM from a DMA'd halo block and do one
    [M_tile, 9C] x [9C, N] MXU matmul.
  - EPILOGUE: per-channel sum / sum-of-squares of the conv output are
    accumulated while output tiles are still in VMEM — the next BN's
    statistics pass never re-reads the activation. Optionally the
    post-prologue input `u` is written out (`emit_u`), materializing the
    residual-branch tensor for the block's skip connection as a
    byproduct instead of a separate add+relu pass.

Activations therefore cross layers as (raw conv output, per-channel
affine) pairs; batch-norm becomes [C]-vector algebra between kernels.

All matmuls accumulate in f32 (`preferred_element_type`); statistics are
taken over the rounded compute-dtype output so results match the XLA
path's numerics. Kernels run in interpret mode off-TPU so the same tests
drive both.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pick_mt(m: int, k: int) -> int:
    """Largest MXU-friendly row tile that divides M (keeps x/u tiles a
    few MB in VMEM)."""
    budget = max(128, min(1024, (4 * 1024 * 1024) // max(1, 2 * k)))
    for mt in (1024, 512, 256, 128):
        if mt <= budget and m % mt == 0:
            return mt
    for mt in (64, 32, 16, 8):
        if m % mt == 0:
            return mt
    return m


# --------------------------------------------------------------- 1x1 conv


def _conv1x1_kernel(x_ref, w_ref, b_ref, s_ref, t_ref, a_ref,
                    y_ref, ssum_ref, ssq_ref, u_ref,
                    *, affine, add, relu, emit_u, compute_dtype):
    i = pl.program_id(0)
    x = x_ref[:]
    if affine:
        u = x * s_ref[:].astype(x.dtype) + t_ref[:].astype(x.dtype)
    else:
        u = x
    if add:
        u = u + a_ref[:]
    if relu:
        u = jnp.maximum(u, 0)
    if emit_u:
        u_ref[:] = u
    acc = jnp.dot(u, w_ref[:], preferred_element_type=jnp.float32)
    acc = acc + b_ref[:]
    y = acc.astype(compute_dtype)
    y_ref[:] = y
    yf = y.astype(jnp.float32)

    @pl.when(i == 0)
    def _():
        ssum_ref[:] = jnp.zeros_like(ssum_ref)
        ssq_ref[:] = jnp.zeros_like(ssq_ref)

    ssum_ref[:] += jnp.sum(yf, axis=0, keepdims=True)
    ssq_ref[:] += jnp.sum(yf * yf, axis=0, keepdims=True)


def fused_conv1x1(x, w, b, scale=None, shift=None, add=None,
                  relu: bool = False, emit_u: bool = False):
    """Fused 1x1 conv: y = relu(scale*x + shift [+ add]) @ w + b, with
    per-channel sum/sumsq of y as byproducts.

    x: [M, K] (flattened B*H*W rows), w: [K, N], b: [N] or None,
    scale/shift: [K] f32, add: [M, K] (plain tensor, post-affine,
    pre-relu). Returns (y [M, N], ssum [N] f32, ssq [N] f32, u or None).
    """
    m, k = x.shape
    n = w.shape[1]
    dtype = x.dtype
    mt = _pick_mt(m, max(k, n))
    affine = scale is not None
    grid = (m // mt,)

    b2 = jnp.zeros((1, n), jnp.float32) if b is None else \
        b.reshape(1, n).astype(jnp.float32)
    s2 = scale.reshape(1, k).astype(jnp.float32) if affine else \
        jnp.zeros((1, k), jnp.float32)
    t2 = shift.reshape(1, k).astype(jnp.float32) if affine else \
        jnp.zeros((1, k), jnp.float32)
    a2 = add if add is not None else jnp.zeros((1, k), dtype)

    const = lambda *_: (0, 0)
    row = lambda i: (i, 0)
    in_specs = [
        pl.BlockSpec((mt, k), row, memory_space=pltpu.VMEM),
        pl.BlockSpec((k, n), const, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, n), const, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, k), const, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, k), const, memory_space=pltpu.VMEM),
        pl.BlockSpec((mt, k) if add is not None else (1, k),
                     row if add is not None else const,
                     memory_space=pltpu.VMEM),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((m, n), dtype),
        jax.ShapeDtypeStruct((1, n), jnp.float32),
        jax.ShapeDtypeStruct((1, n), jnp.float32),
        jax.ShapeDtypeStruct((m, k) if emit_u else (1, k), dtype),
    ]
    out_specs = [
        pl.BlockSpec((mt, n), row, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, n), const, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, n), const, memory_space=pltpu.VMEM),
        pl.BlockSpec((mt, k) if emit_u else (1, k),
                     row if emit_u else const, memory_space=pltpu.VMEM),
    ]
    kernel = functools.partial(
        _conv1x1_kernel, affine=affine, add=add is not None, relu=relu,
        emit_u=emit_u, compute_dtype=dtype)
    y, ssum, ssq, u = pl.pallas_call(
        kernel, grid=grid, in_specs=in_specs, out_specs=out_specs,
        out_shape=out_shape, interpret=_interpret(),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * k * n,
            bytes_accessed=(m * k + k * n + m * n) * x.dtype.itemsize,
            transcendentals=0),
    )(x, w, b2, s2, t2, a2)
    return y, ssum[0], ssq[0], (u if emit_u else None)


# --------------------------------------------------------------- 3x3 conv


def _pick_th(h: int) -> int:
    for th in (16, 14, 8, 7, 4):
        if h % th == 0:
            return th
    return h


def _conv3x3_kernel(x_ref, xprev_ref, xnext_ref, w_ref, b_ref, s_ref, t_ref,
                    y_ref, ssum_ref, ssq_ref,
                    scratch, col_scratch,
                    *, th, h, wdim, c, n, affine, relu, compute_dtype):
    i = pl.program_id(1)
    # assemble the haloed tile in VMEM scratch; the 1-row halo blocks
    # come from clamped index maps (clamped rows are garbage, masked
    # below together with the SAME zero-padding)
    scratch[0:1, 1:wdim + 1, :] = xprev_ref[0]
    scratch[1:th + 1, 1:wdim + 1, :] = x_ref[0]
    scratch[th + 1:th + 2, 1:wdim + 1, :] = xnext_ref[0]
    xs = scratch[:]
    if affine:
        u = xs * s_ref[:].astype(xs.dtype) + t_ref[:].astype(xs.dtype)
    else:
        u = xs
    if relu:
        u = jnp.maximum(u, 0)
    # zero everything outside the image (SAME padding + unDMA'd halo
    # rows at the image edge; garbage in those slots is masked here).
    # 3D int32 iota: Mosaic can't minor-expand an i1 vector, so the mask
    # is built at full rank from 32-bit iotas.
    shp = (th + 2, wdim + 2, c)
    rows = jax.lax.broadcasted_iota(jnp.int32, shp, 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, shp, 1)
    grow = rows + i * th - 1
    valid = ((grow >= 0) & (grow < h) & (cols >= 1) & (cols <= wdim))
    u = jnp.where(valid, u, 0)

    # im2col through VMEM scratch: direct register concat of the 9
    # shifted views trips Mosaic lane-offset alignment, so each tap is
    # written at its [tap*c] channel offset (stores realign) and the
    # buffer is read back as one [th*wdim, 9c] matmul operand
    for tap, (dh, dw) in enumerate((dh, dw) for dh in range(3)
                                   for dw in range(3)):
        col_scratch[:, :, tap * c:(tap + 1) * c] = \
            u[dh:dh + th, dw:dw + wdim, :]
    col = col_scratch[:].reshape(th * wdim, 9 * c)
    acc = jnp.dot(col, w_ref[:], preferred_element_type=jnp.float32)
    acc = acc + b_ref[:]
    y = acc.astype(compute_dtype)
    y_ref[:] = y.reshape(1, th, wdim, n)
    yf = y.astype(jnp.float32)

    @pl.when((pl.program_id(0) == 0) & (i == 0))
    def _():
        ssum_ref[:] = jnp.zeros_like(ssum_ref)
        ssq_ref[:] = jnp.zeros_like(ssq_ref)

    ssum_ref[:] += jnp.sum(yf, axis=0, keepdims=True)
    ssq_ref[:] += jnp.sum(yf * yf, axis=0, keepdims=True)


def fused_conv3x3(x, w, b, scale=None, shift=None, relu: bool = False):
    """Fused 3x3 SAME stride-1 conv over NHWC with affine+relu prologue
    and channel-stats epilogue.

    x: [B, H, W, C]; w: [3, 3, C, N] (HWIO); b: [N] or None.
    Returns (y [B, H, W, N], ssum [N] f32, ssq [N] f32).
    """
    bsz, h, wd, c = x.shape
    n = w.shape[-1]
    dtype = x.dtype
    th = _pick_th(h)
    affine = scale is not None
    grid = (bsz, h // th)

    wmat = w.reshape(9 * c, n)
    b2 = jnp.zeros((1, n), jnp.float32) if b is None else \
        b.reshape(1, n).astype(jnp.float32)
    s2 = (scale.reshape(1, 1, c).astype(jnp.float32) if affine
          else jnp.zeros((1, 1, c), jnp.float32))
    t2 = (shift.reshape(1, 1, c).astype(jnp.float32) if affine
          else jnp.zeros((1, 1, c), jnp.float32))

    const2 = lambda *_: (0, 0)
    const3 = lambda *_: (0, 0, 0)
    in_specs = [
        pl.BlockSpec((1, th, wd, c), lambda bi, i: (bi, i, 0, 0),
                     memory_space=pltpu.VMEM),
        # 1-row halo blocks: block shape 1 along H makes the block index
        # a row index, so clamped maps fetch rows i*th-1 / (i+1)*th
        pl.BlockSpec((1, 1, wd, c),
                     lambda bi, i: (bi, jnp.maximum(i * th - 1, 0), 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, wd, c),
                     lambda bi, i: (bi, jnp.minimum((i + 1) * th, h - 1),
                                    0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((9 * c, n), const2, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, n), const2, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, c), const3, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, c), const3, memory_space=pltpu.VMEM),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((bsz, h, wd, n), dtype),
        jax.ShapeDtypeStruct((1, n), jnp.float32),
        jax.ShapeDtypeStruct((1, n), jnp.float32),
    ]
    out_specs = [
        pl.BlockSpec((1, th, wd, n), lambda bi, i: (bi, i, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, n), const2, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, n), const2, memory_space=pltpu.VMEM),
    ]
    kernel = functools.partial(
        _conv3x3_kernel, th=th, h=h, wdim=wd, c=c, n=n, affine=affine,
        relu=relu, compute_dtype=dtype)
    y, ssum, ssq = pl.pallas_call(
        kernel, grid=grid, in_specs=in_specs, out_specs=out_specs,
        out_shape=out_shape, interpret=_interpret(),
        scratch_shapes=[pltpu.VMEM((th + 2, wd + 2, c), dtype),
                        pltpu.VMEM((th, wd, 9 * c), dtype)],
        cost_estimate=pl.CostEstimate(
            flops=2 * bsz * h * wd * 9 * c * n,
            bytes_accessed=(bsz * h * wd * (c + n) + 9 * c * n)
            * x.dtype.itemsize,
            transcendentals=0),
    )(x, x, x, wmat, b2, s2, t2)
    return y, ssum[0], ssq[0]


# ------------------------------------------------------------ 1x1 backward
#
# The backward of fused_conv costs XLA several full HBM passes: the
# effective cotangent ybar = dy + dssum + 2*y*dssq is materialized
# (needed by both grad convs), the input gradient du round-trips HBM
# before the mask/scale chain, and dx1/dx2 are separate passes. These
# kernels fold everything around the two matmuls:
#   dgrad: ybar recomputed in-prologue (reads dy, y) -> du = ybar@W^T
#          -> epilogue: +du_out, relu mask from recomputed u (reads
#          x[,x2]), writes dx1[, dx2], accumulates ds/dt/db.
#   wgrad: u and ybar recomputed in-prologue -> dW += u^T @ ybar.
# Each big tensor is read once per kernel, nothing extra is written.


def _dgrad1x1_kernel(dy_ref, y_ref, w_ref, x_ref, x2_ref, duo_ref,
                     s1_ref, t1_ref, s2_ref, t2_ref, dsum_ref, dsq_ref,
                     dx1_ref, dx2_ref, ds1_ref, dt1_ref, ds2_ref,
                     dt2_ref, db_ref,
                     *, aff1, aff2, has_x2, has_duo, relu, with_stats,
                     compute_dtype):
    i = pl.program_id(0)
    dyf = dy_ref[:].astype(jnp.float32)
    if with_stats:
        dyf = (dyf + dsum_ref[:]
               + 2.0 * y_ref[:].astype(jnp.float32) * dsq_ref[:])
    ybar = dyf.astype(compute_dtype)

    @pl.when(i == 0)
    def _():
        for r in (ds1_ref, dt1_ref, ds2_ref, dt2_ref, db_ref):
            r[:] = jnp.zeros_like(r)

    db_ref[:] += jnp.sum(dyf, axis=0, keepdims=True)
    du = jax.lax.dot_general(
        ybar, w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if has_duo:
        du = du + duo_ref[:].astype(jnp.float32)
    x = x_ref[:]
    if aff1:
        u = x * s1_ref[:].astype(x.dtype) + t1_ref[:].astype(x.dtype)
    else:
        u = x
    if has_x2:
        x2 = x2_ref[:]
        if aff2:
            u = u + (x2 * s2_ref[:].astype(x.dtype)
                     + t2_ref[:].astype(x.dtype))
        else:
            u = u + x2
    if relu:
        # compare in f32: Mosaic lacks bf16 vector compares on some targets
        du = jnp.where(u.astype(jnp.float32) > 0, du, 0.0)
    duf = du
    if aff1:
        ds1_ref[:] += jnp.sum(x.astype(jnp.float32) * duf, axis=0,
                              keepdims=True)
        dt1_ref[:] += jnp.sum(duf, axis=0, keepdims=True)
        dx1_ref[:] = (duf * s1_ref[:]).astype(compute_dtype)
    else:
        dx1_ref[:] = duf.astype(compute_dtype)
    if has_x2:
        if aff2:
            ds2_ref[:] += jnp.sum(x2_ref[:].astype(jnp.float32) * duf,
                                  axis=0, keepdims=True)
            dt2_ref[:] += jnp.sum(duf, axis=0, keepdims=True)
            dx2_ref[:] = (duf * s2_ref[:]).astype(compute_dtype)
        else:
            dx2_ref[:] = duf.astype(compute_dtype)


def dgrad_conv1x1(dy, y, w, x, x2=None, du_out=None, scale=None,
                  shift=None, scale2=None, shift2=None, dssum=None,
                  dssq=None, relu=False):
    """Fused input-gradient of fused_conv (1x1, stride 1): one pass over
    (dy, y, x[, x2]) producing dx1[, dx2] plus the [C]-sized ds/dt/db
    reductions. Returns (dx1, dx2, ds1, dt1, ds2, dt2, db)."""
    m, n = dy.shape
    k = w.shape[0]
    dtype = dy.dtype
    mt = _pick_mt(m, max(k, n))
    aff1 = scale is not None
    aff2 = scale2 is not None
    has_x2 = x2 is not None
    has_duo = du_out is not None
    with_stats = dssum is not None
    grid = (m // mt,)

    z1k = jnp.zeros((1, k), jnp.float32)
    z1n = jnp.zeros((1, n), jnp.float32)
    fill = lambda v, z: z if v is None else v.reshape(z.shape).astype(
        jnp.float32)
    zmk = jnp.zeros((1, k), dtype)

    const = lambda *_: (0, 0)
    row = lambda i: (i, 0)
    rowk = pl.BlockSpec((mt, k), row, memory_space=pltpu.VMEM)
    rown = pl.BlockSpec((mt, n), row, memory_space=pltpu.VMEM)
    c1k = pl.BlockSpec((1, k), const, memory_space=pltpu.VMEM)
    c1n = pl.BlockSpec((1, n), const, memory_space=pltpu.VMEM)
    in_specs = [
        rown, rown,
        pl.BlockSpec((k, n), const, memory_space=pltpu.VMEM),
        rowk,
        rowk if has_x2 else c1k,
        rowk if has_duo else c1k,
        c1k, c1k, c1k, c1k, c1n, c1n,
    ]
    out_shape = [
        jax.ShapeDtypeStruct((m, k), dtype),
        jax.ShapeDtypeStruct((m, k) if has_x2 else (1, k), dtype),
        jax.ShapeDtypeStruct((1, k), jnp.float32),
        jax.ShapeDtypeStruct((1, k), jnp.float32),
        jax.ShapeDtypeStruct((1, k), jnp.float32),
        jax.ShapeDtypeStruct((1, k), jnp.float32),
        jax.ShapeDtypeStruct((1, n), jnp.float32),
    ]
    out_specs = [
        rowk,
        rowk if has_x2 else pl.BlockSpec((1, k), const,
                                         memory_space=pltpu.VMEM),
        c1k, c1k, c1k, c1k, c1n,
    ]
    kernel = functools.partial(
        _dgrad1x1_kernel, aff1=aff1, aff2=aff2, has_x2=has_x2,
        has_duo=has_duo, relu=relu, with_stats=with_stats,
        compute_dtype=dtype)
    outs = pl.pallas_call(
        kernel, grid=grid, in_specs=in_specs, out_specs=out_specs,
        out_shape=out_shape, interpret=_interpret(),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * k * n,
            bytes_accessed=(2 * m * n + k * n + (2 + has_x2 + has_duo)
                            * m * k) * dy.dtype.itemsize,
            transcendentals=0),
    )(dy, y, w,
      x.reshape(m, k),
      x2.reshape(m, k) if has_x2 else zmk,
      du_out.reshape(m, k) if has_duo else zmk,
      fill(scale, z1k), fill(shift, z1k), fill(scale2, z1k),
      fill(shift2, z1k), fill(dssum, z1n), fill(dssq, z1n))
    dx1, dx2, ds1, dt1, ds2, dt2, db = outs
    return (dx1, dx2 if has_x2 else None,
            ds1[0] if aff1 else None, dt1[0] if aff1 else None,
            ds2[0] if aff2 else None, dt2[0] if aff2 else None, db[0])


def _wgrad1x1_kernel(dy_ref, y_ref, x_ref, x2_ref, s1_ref, t1_ref,
                     s2_ref, t2_ref, dsum_ref, dsq_ref, dw_ref,
                     *, aff1, aff2, has_x2, relu, with_stats):
    i = pl.program_id(0)
    dyf = dy_ref[:].astype(jnp.float32)
    if with_stats:
        dyf = (dyf + dsum_ref[:]
               + 2.0 * y_ref[:].astype(jnp.float32) * dsq_ref[:])
    x = x_ref[:]
    if aff1:
        u = x * s1_ref[:].astype(x.dtype) + t1_ref[:].astype(x.dtype)
    else:
        u = x
    if has_x2:
        x2 = x2_ref[:]
        if aff2:
            u = u + (x2 * s2_ref[:].astype(x.dtype)
                     + t2_ref[:].astype(x.dtype))
        else:
            u = u + x2
    if relu:
        u = jnp.maximum(u, 0)

    @pl.when(i == 0)
    def _():
        dw_ref[:] = jnp.zeros_like(dw_ref)

    dw_ref[:] += jax.lax.dot_general(
        u, dyf.astype(u.dtype), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def wgrad_conv1x1(dy, y, x, x2=None, scale=None, shift=None, scale2=None,
                  shift2=None, dssum=None, dssq=None, relu=False):
    """Fused weight-gradient of fused_conv (1x1, stride 1): recomputes u
    and ybar per tile, accumulates dW = u^T @ ybar in VMEM. Returns
    dW [K, N] f32."""
    m, n = dy.shape
    k = x.reshape(m, -1).shape[1]
    dtype = dy.dtype
    mt = _pick_mt(m, max(k, n))
    aff1 = scale is not None
    aff2 = scale2 is not None
    has_x2 = x2 is not None
    with_stats = dssum is not None
    grid = (m // mt,)
    z1k = jnp.zeros((1, k), jnp.float32)
    z1n = jnp.zeros((1, n), jnp.float32)
    fill = lambda v, z: z if v is None else v.reshape(z.shape).astype(
        jnp.float32)
    zmk = jnp.zeros((1, k), dtype)
    const = lambda *_: (0, 0)
    row = lambda i: (i, 0)
    rowk = pl.BlockSpec((mt, k), row, memory_space=pltpu.VMEM)
    rown = pl.BlockSpec((mt, n), row, memory_space=pltpu.VMEM)
    c1k = pl.BlockSpec((1, k), const, memory_space=pltpu.VMEM)
    c1n = pl.BlockSpec((1, n), const, memory_space=pltpu.VMEM)
    kernel = functools.partial(
        _wgrad1x1_kernel, aff1=aff1, aff2=aff2, has_x2=has_x2, relu=relu,
        with_stats=with_stats)
    dw = pl.pallas_call(
        kernel, grid=grid,
        in_specs=[rown, rown, rowk, rowk if has_x2 else c1k,
                  c1k, c1k, c1k, c1k, c1n, c1n],
        out_specs=pl.BlockSpec((k, n), const, memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((k, n), jnp.float32),
        interpret=_interpret(),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * k * n,
            bytes_accessed=(2 * m * n + (1 + has_x2) * m * k + k * n)
            * dy.dtype.itemsize,
            transcendentals=0),
    )(dy, y, x.reshape(m, k),
      x2.reshape(m, k) if has_x2 else zmk,
      fill(scale, z1k), fill(shift, z1k), fill(scale2, z1k),
      fill(shift2, z1k), fill(dssum, z1n), fill(dssq, z1n))
    return dw


# -------------------------------------------------------- reference impls


def ref_fused_conv1x1(x, w, b, scale=None, shift=None, add=None,
                      relu=False, emit_u=False):
    """Pure-jnp oracle for fused_conv1x1 (same rounding points)."""
    u = x
    if scale is not None:
        u = u * scale.astype(x.dtype) + shift.astype(x.dtype)
    if add is not None:
        u = u + add
    if relu:
        u = jnp.maximum(u, 0)
    y = (jnp.dot(u, w, preferred_element_type=jnp.float32)
         + (0 if b is None else b.astype(jnp.float32))).astype(x.dtype)
    yf = y.astype(jnp.float32)
    return y, jnp.sum(yf, 0), jnp.sum(yf * yf, 0), (u if emit_u else None)


def ref_fused_conv3x3(x, w, b, scale=None, shift=None, relu=False):
    """Pure-lax oracle for fused_conv3x3."""
    from jax import lax

    u = x
    if scale is not None:
        u = u * scale.astype(x.dtype) + shift.astype(x.dtype)
    if relu:
        u = jnp.maximum(u, 0)
    y = lax.conv_general_dilated(
        u, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32)
    y = (y + (0 if b is None else b.astype(jnp.float32))).astype(x.dtype)
    yf = y.astype(jnp.float32)
    return y, jnp.sum(yf, (0, 1, 2)), jnp.sum(yf * yf, (0, 1, 2))


def fused_conv_bn_act(x, w, b, gamma, beta, mean, var, eps=1e-5,
                      relu=True):
    """Convenience wrapper: one conv with BN-apply(+relu) of the GIVEN
    stats fused into the *output* side — used for inference-mode single
    convs. scale/shift fold BN into the next conv's prologue in the
    training pipeline; this helper is the standalone-layer form.

    w: [K, N] (1x1 conv over flattened rows) or [3, 3, C, N]."""
    if w.ndim == 4 and w.shape[:2] != (3, 3):
        raise ValueError(
            f"pallas helper supports 1x1 (2-D w) or 3x3 kernels, got "
            f"{w.shape[:2]}; use the XLA path for other geometries")
    s = gamma * jax.lax.rsqrt(var + eps)
    t = beta - mean * s
    if w.ndim == 2:
        y, _, _, _ = fused_conv1x1(x, w, b)
    else:
        y, _, _ = fused_conv3x3(x, w, b)
    out = y * s.astype(y.dtype) + t.astype(y.dtype)
    if relu:
        out = jnp.maximum(out, 0)
    return out
